# Container image for launch/docker_cluster.sh — the analog of the
# TF+Horovod images the reference's docker launchers assume
# (start-resnet-cifar-train.sh docker exec payloads). Any base with a
# jax[tpu] install works; this default targets TPU VM hosts.
FROM python:3.11-slim

RUN pip install --no-cache-dir "jax[tpu]" flax optax orbax-checkpoint \
    einops numpy \
    -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

WORKDIR /workspace
COPY . /workspace
# Build the native C++ data plane (falls back to numpy loaders if absent).
RUN python -m tpu_resnet.native.build || true

ENTRYPOINT []
CMD ["python", "-m", "tpu_resnet", "train", "--preset", "smoke"]
