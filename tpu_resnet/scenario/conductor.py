"""The scenario conductor: one engine runs every drill file.

Owns, exactly once, the skeleton every bespoke doctor probe used to
hand-roll:

- children under ``hostenv.scrubbed_cpu_env(devices)`` with the fault
  schedule merged in AFTER the scrub (the scrub strips ``TPU_*`` — a
  fault env var merged before it would silently vanish, the bug class
  this module exists to retire);
- child stdout/stderr to a FILE, never a pipe (nobody reads while we
  wait; a chatty child against a full 64K pipe deadlocks ``wait()``);
- discovery-file waits with deadlines (serve.json / serve-<name>.json /
  route.json / telemetry.json — free ports come from ``port=0`` plus
  these files, the repo's ephemeral-port idiom);
- a reaper thread collecting child exits (one lock around the exit
  table, an Event to wake waiters, polling outside the lock, stop-event
  + join teardown — the tpu_resnet/analysis/concurrency.py contract);
- survivor kill on first failure (SIGTERM, grace, SIGKILL);
- a single RESULT_JSON writer and the perfwatch hand-off
  (``sweep-scn:<scenario>:<metric>`` series).

Jax-free at module scope (jaxlint host-isolation scope): the conductor
runs on hosts whose accelerator stack is the thing being drilled; its
children are the only processes that touch jax.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

from tpu_resnet.hostenv import scrubbed_cpu_env
from tpu_resnet.resilience.exitcodes import (
    HOSTENV_SPAWN_FAILED,
    HOSTENV_TIMEOUT,
)
from tpu_resnet.scenario import assertions as _assertions
from tpu_resnet.scenario import spec as _spec

DEFAULT_STEP_TIMEOUT = 300.0
TAIL_LINES = 5
RESULT_FILE = "scenario_result.json"

_FAULT_ENV_PREFIX = "TPU_RESNET_FAULT_"  # faultinject.ENV_PREFIX


class StepFailure(Exception):
    """A step missed its contract: carries the structured observation
    the RESULT_JSON (and the doctor adapters) report."""

    def __init__(self, error=None, observed=None, tail=None):
        self.error = error
        self.observed = observed or {}
        self.tail = tail
        super().__init__(error or "step failed")


def _tail_of(path):
    try:
        with open(path) as f:
            return f.read().strip().splitlines()[-TAIL_LINES:]
    except OSError:
        return []


def _format_override(key, value) -> str:
    if isinstance(value, bool):
        value = "true" if value else "false"
    return f"{key}={value}"


def _build_argv(proc: dict, root: str) -> list:
    """Process spec → argv. Every kind funnels through the same three
    appendables: preset, overrides (file order), extra args."""
    kind = proc["kind"]
    if kind == "cmd":
        return list(proc["argv"])
    if kind == "loadgen":
        argv = [sys.executable, os.path.join(root, "tools", "loadgen.py")]
    elif kind == "supervise":
        argv = [sys.executable, os.path.join(root, "tools",
                                             "supervise.py")]
    elif kind == "sweep":
        argv = [sys.executable, "-m", "tpu_resnet.tools.sweep"]
    else:
        argv = [sys.executable, "-m", "tpu_resnet", kind]
        if proc.get("preset"):
            argv += ["--preset", proc["preset"]]
    argv += [_format_override(k, v)
             for k, v in (proc.get("overrides") or {}).items()]
    argv += [str(a) for a in (proc.get("args") or [])]
    return argv


def _child_env(proc: dict) -> dict:
    """Scrub FIRST, then merge the process env and the fault schedule —
    the ordering contract (scrubbed_cpu_env strips TPU_*, and the fault
    vars are TPU_RESNET_FAULT_*)."""
    env = scrubbed_cpu_env(int(proc.get("devices", 1)))
    env.update({k: str(v) for k, v in (proc.get("env") or {}).items()})
    for key, value in (proc.get("faults") or {}).items():
        env[_FAULT_ENV_PREFIX + key] = str(value)
    return env


class _Child:
    def __init__(self, name: str, proc_spec: dict, run_dir: str,
                 root: str):
        self.name = name
        self.spec = proc_spec
        self.log_path = os.path.join(run_dir, f"{name}.log")
        self.log_fh = open(self.log_path, "w")
        argv = _build_argv(proc_spec, root)
        try:
            self.proc = subprocess.Popen(
                argv, env=_child_env(proc_spec), stdout=self.log_fh,
                stderr=subprocess.STDOUT, text=True,
                cwd=proc_spec.get("cwd") or None)
        except OSError as e:
            self.log_fh.write(f"spawn failed: {e}\n")
            self.log_fh.flush()
            self.proc = None

    def tail(self):
        self.log_fh.flush()
        return _tail_of(self.log_path)

    def close(self):
        try:
            self.log_fh.close()
        except OSError:
            pass


class Conductor:
    """Runs one validated, template-expanded scenario dict.

    Threading contract (tpu_resnet/analysis/concurrency.py): ONE lock
    guards the children/exit tables; the reaper polls children OUTSIDE
    the lock and records exits under it; ``_exit_event`` wakes the main
    thread's waits; teardown is stop-event then ``join`` with a
    timeout. No I/O, no blocking call ever happens under ``_lock``.
    """

    def __init__(self, data: dict, run_dir: str, stream=None):
        self.data = data
        self.run_dir = run_dir
        self.root = _spec.repo_root()
        self.stream = stream
        self.default_timeout = float(data.get("timeout",
                                              DEFAULT_STEP_TIMEOUT))
        self._lock = threading.Lock()
        self._children: dict = {}   # name -> _Child (guarded by _lock)
        self._exits: dict = {}      # name -> rc     (guarded by _lock)
        self._exit_event = threading.Event()
        self._stop = threading.Event()
        self._reaper = threading.Thread(target=self._reap,
                                        name="scenario-reaper",
                                        daemon=True)
        self.rcs: dict = {}         # main-thread view for RESULT_JSON
        self.steps_out: list = []
        self.observed: dict = {}    # label -> observed dict

    # ----------------------------------------------------- child reaper
    def _reap(self):
        while not self._stop.is_set():
            with self._lock:
                live = [(n, c) for n, c in self._children.items()
                        if n not in self._exits and c.proc is not None]
            exited = []
            for name, child in live:  # poll OUTSIDE the lock
                rc = child.proc.poll()
                if rc is not None:
                    exited.append((name, rc))
            if exited:
                with self._lock:
                    self._exits.update(exited)
                self._exit_event.set()
            self._stop.wait(0.2)

    def _exit_code(self, name):
        with self._lock:
            return self._exits.get(name)

    def _spawn(self, name: str) -> _Child:
        child = _Child(name, self.data["processes"][name], self.run_dir,
                       self.root)
        with self._lock:
            self._children[name] = child
            if child.proc is None:
                self._exits[name] = HOSTENV_SPAWN_FAILED
        if child.proc is None:
            self._exit_event.set()
        return child

    def _child(self, name: str) -> _Child:
        with self._lock:
            return self._children[name]

    def _wait_exit(self, name: str, timeout: float):
        """rc once the reaper records the exit, None on deadline."""
        deadline = time.monotonic() + timeout
        while True:
            rc = self._exit_code(name)
            if rc is not None:
                return rc
            if time.monotonic() >= deadline:
                return None
            if self._exit_event.wait(0.2):
                self._exit_event.clear()

    def _kill_survivors(self):
        with self._lock:
            live = [(n, c) for n, c in self._children.items()
                    if n not in self._exits and c.proc is not None]
        for _, child in live:
            try:
                child.proc.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + 10
        for name, child in live:
            while (self._exit_code(name) is None
                   and time.monotonic() < deadline):
                if self._exit_event.wait(0.2):
                    self._exit_event.clear()
            if self._exit_code(name) is None:
                try:
                    child.proc.kill()
                except OSError:
                    pass

    # ------------------------------------------------------- utilities
    def _log(self, line: str):
        if self.stream is not None:
            print(f"[scenario] {line}", file=self.stream, flush=True)

    def _port_of(self, step: dict):
        """Discovery-file port for a step's source/target endpoint."""
        from tpu_resnet.obs.server import read_telemetry_port
        from tpu_resnet.serve.discovery import read_port
        from tpu_resnet.serve.router import read_route_port

        source = step.get("source") or step.get("target") or "serve"
        directory = step["dir"]
        if source == "route":
            return read_route_port(directory)
        if source == "telemetry":
            return read_telemetry_port(directory)
        if source == "fleetmon":
            from tpu_resnet.obs.fleet import FLEET_DISCOVERY
            return read_port(directory, FLEET_DISCOVERY)
        if source == "autopilot":
            from tpu_resnet.autopilot.controller import AUTOPILOT_DISCOVERY
            return read_port(directory, AUTOPILOT_DISCOVERY)
        name = step.get("name")
        return read_port(directory,
                         f"serve-{name}.json" if name else "serve.json")

    def _http_json(self, port: int, path: str, timeout: float = 2.0):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
            return json.loads(r.read())

    def _ckpt_steps(self, directory: str) -> list:
        return (sorted(int(n) for n in os.listdir(directory)
                       if n.isdigit())
                if os.path.isdir(directory) else [])

    def _run_spans(self, directory: str) -> list:
        from tpu_resnet.obs.spans import load_spans

        return [[s.get("start_step"), s.get("stop_step")]
                for s in load_spans(os.path.join(directory,
                                                 "events.jsonl"))
                if s["span"] == "run"]

    def _check_exit(self, step: dict, rc: int, tail):
        """The combined exit contract every drill shares: expected rc,
        optionally a checkpoint at the stop step, optionally the exact
        run-span history. Failures carry every observation at once (the
        historical probe shape: rc + expected_rc + ckpt_steps in ONE
        dict)."""
        observed = {"rc": rc}
        ok = True
        allowed = _spec.resolve_rc(step.get("expect_rc", 0))
        if allowed is not None:
            rc_ok = (rc in [a for a in allowed if a != "nonzero"]
                     or ("nonzero" in allowed and rc != 0))
            if not rc_ok:
                ok = False
                observed["expected_rc"] = (
                    allowed[0] if len(allowed) == 1 else allowed)
        if "expect_ckpt" in step:
            steps = self._ckpt_steps(step["expect_ckpt"]["dir"])
            observed["ckpt_steps"] = steps
            if step["expect_ckpt"]["step"] not in steps:
                ok = False
                allowed = allowed or []
                observed.setdefault(
                    "expected_rc",
                    allowed[0] if len(allowed) == 1 else allowed)
        if "expect_run_spans" in step:
            spans = self._run_spans(step["expect_run_spans"]["dir"])
            observed["run_spans"] = spans
            expect = [list(s) for s in
                      step["expect_run_spans"]["spans"]]
            if spans != expect:
                ok = False
        if not ok:
            raise StepFailure(observed=observed, tail=tail)
        return observed

    # ------------------------------------------------------- step kinds
    def _step_run(self, step):
        name = step["proc"]
        timeout = float(step.get("timeout", self.default_timeout))
        child = self._spawn(name)
        rc = self._wait_exit(name, timeout)
        if rc is None:
            try:
                child.proc.kill()
            except OSError:
                pass
            self._wait_exit(name, 10)
            rc = HOSTENV_TIMEOUT
            with self._lock:
                self._exits.setdefault(name, rc)
        self.rcs[name] = rc
        return self._check_exit(step, rc, child.tail())

    def _step_start(self, step):
        child = self._spawn(step["proc"])
        if child.proc is None:
            raise StepFailure(error="spawn failed",
                              observed={"rc": HOSTENV_SPAWN_FAILED},
                              tail=child.tail())
        return {"pid": child.proc.pid}

    def _step_signal(self, step):
        child = self._child(step["proc"])
        sig = getattr(signal, "SIG" + step["sig"].upper())
        try:
            child.proc.send_signal(sig)
        except OSError:
            pass
        return {"sig": step["sig"].upper()}

    def _step_wait_exit(self, step):
        name = step["proc"]
        timeout = float(step.get("timeout", self.default_timeout))
        child = self._child(name)
        rc = self._wait_exit(name, timeout)
        if rc is None:
            try:
                child.proc.kill()
            except OSError:
                pass
            self._wait_exit(name, 10)
            raise StepFailure(
                error=step.get("timeout_error",
                               f"{name} did not exit within "
                               f"{int(timeout)}s"),
                tail=child.tail())
        self.rcs[name] = rc
        return self._check_exit(step, rc, child.tail())

    def _step_stop(self, step):
        self._step_signal(dict(step, sig=step.get("sig", "TERM")))
        return self._step_wait_exit(step)

    def _step_wait_ready(self, step):
        """Discovery file names a port AND /healthz says ok, under a
        deadline, while the child is still alive."""
        name = step["proc"]
        child = self._child(name)
        timeout = float(step.get("timeout", self.default_timeout))
        deadline = time.monotonic() + timeout
        min_replicas = step.get("min_replicas", 0)
        while time.monotonic() < deadline:
            if self._exit_code(name) is not None:
                raise StepFailure(observed={"rc": self._exit_code(name)},
                                  tail=child.tail())
            port = self._port_of(step)
            if port is not None:
                try:
                    health = self._http_json(port, "/healthz")
                    if health.get("ok") and int(health.get(
                            "replicas_healthy", min_replicas)) \
                            >= min_replicas:
                        return {"port": port}
                except (OSError, ValueError):
                    pass  # 503 (warming) / not listening yet
            time.sleep(0.3)
        raise StepFailure(error=step.get(
            "timeout_error", f"{name} never became ready"),
            observed={"rc": self._exit_code(name)}, tail=child.tail())

    def _step_predict(self, step):
        port = self._port_of(step)
        shape = [int(x) for x in step["shape"]]
        n_bytes = 1
        for x in shape:
            n_bytes *= x
        body = bytes(n_bytes)
        expect = step.get("expect_predictions", shape[0])
        headers = {"Content-Type": "application/octet-stream",
                   "X-Shape": ",".join(str(x) for x in shape)}
        if step.get("lane"):
            headers["X-Lane"] = step["lane"]
        ok_requests = 0
        for _ in range(step.get("n", 1)):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/predict", data=body,
                headers=headers)
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    payload = json.loads(r.read())
                if len(payload.get("predictions", [])) == expect:
                    ok_requests += 1
            except (OSError, ValueError):
                pass
        observed = {"ok_requests": ok_requests, "port": port}
        if step.get("required") and ok_requests < step.get("n", 1):
            raise StepFailure(observed=observed)
        return observed

    def _step_scrape(self, step):
        """One /metrics scrape; NEVER fails the scenario (the historical
        probes degrade the value to -1 and let the composite verdict
        fail instead — a dead endpoint is a FAILED check downstream, not
        a conductor crash)."""
        from tpu_resnet.obs.server import parse_prometheus

        try:
            port = self._port_of(step)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                metrics = parse_prometheus(r.read().decode())
            return {m: metrics.get(m, 0) for m in step["metrics"]}
        except (OSError, ValueError, TypeError):
            return {m: -1 for m in step["metrics"]}

    def _step_scrape_until(self, step):
        """Poll /metrics while the child lives until every condition
        holds at once, then collect from that same scrape."""
        from tpu_resnet.obs.server import parse_histograms, parse_prometheus

        name = step["proc"]
        child = self._child(name)
        timeout = float(step.get("timeout", self.default_timeout))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline \
                and self._exit_code(name) is None:
            port = self._port_of(step)
            if port is not None:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/metrics",
                            timeout=2) as r:
                        text = r.read().decode()
                    metrics = parse_prometheus(text)
                    hists = parse_histograms(text)
                    if self._conditions_hold(step["conditions"],
                                             metrics, hists):
                        out = {}
                        for c in step.get("collect", []):
                            if "metric" in c:
                                out[c["key"]] = metrics.get(c["metric"])
                            else:
                                out[c["key"]] = hists.get(
                                    c["hist_count"], {}).get("count", 0)
                        return out
                except (OSError, ValueError):
                    pass  # not listening yet / mid-write
            time.sleep(0.3)
        raise StepFailure(
            error=step.get("timeout_error",
                           "metrics conditions never went live"),
            tail=child.tail())

    @staticmethod
    def _conditions_hold(conditions, metrics, hists) -> bool:
        for c in conditions:
            if "file" in c:
                if not os.path.exists(c["file"]):
                    return False
            elif "hist_count" in c:
                count = hists.get(c["hist_count"], {}).get("count", 0)
                if count <= c.get("gt", -1):
                    return False
            else:
                if c["metric"] not in metrics:
                    return False
                if "gt" in c and metrics[c["metric"]] <= c["gt"]:
                    return False
        return True

    def _step_http_json(self, step):
        """GET a JSON endpoint; with ``until`` poll (under the deadline)
        for dotted fields to equal the expected values; ``collect``
        records dotted fields into the observation."""
        timeout = float(step.get("timeout", self.default_timeout))
        deadline = time.monotonic() + timeout
        until = step.get("until") or {}
        last = None
        while time.monotonic() < deadline:
            try:
                port = self._port_of(step)
                if port is not None:
                    last = self._http_json(port, step["path"])
                    if all(_assertions.dotted_get(last, k) == v
                           for k, v in until.items()):
                        return {k: _assertions.dotted_get(last, d)
                                for k, d in
                                (step.get("collect") or {}).items()} \
                            or {"ok": True}
            except (OSError, ValueError, TypeError):
                pass
            if not until:
                break
            time.sleep(0.3)
        raise StepFailure(error=f"{step['path']} never matched {until}",
                          observed={"last": last})

    def _step_corrupt_ckpt(self, step):
        from tpu_resnet.resilience.faultinject import corrupt_checkpoint

        corrupted = corrupt_checkpoint(step["dir"],
                                       step.get("step"))
        if corrupted is None:
            raise StepFailure(error=f"no checkpoint to corrupt under "
                                    f"{step['dir']}")
        return {"corrupted_step": corrupted}

    def _step_drain(self, step):
        from tpu_resnet.serve.router import read_route_port, request_drain

        port = read_route_port(step["dir"])
        if port is None:
            raise StepFailure(error="no route.json to drain through")
        verdict = request_drain(f"http://127.0.0.1:{port}",
                                step["replica"])
        if not verdict.get("ok"):
            raise StepFailure(error="admin drain refused",
                              observed={"drain": verdict})
        return {"drain": verdict}

    def _step_sleep(self, step):
        time.sleep(float(step["seconds"]))
        return {}

    def _step_assert(self, step):
        return _assertions.evaluate(step, self)

    # ---------------------------------------------------------- driver
    _EXECUTORS = {
        "run": _step_run, "start": _step_start, "signal": _step_signal,
        "wait_exit": _step_wait_exit, "stop": _step_stop,
        "wait_ready": _step_wait_ready, "predict": _step_predict,
        "scrape": _step_scrape, "scrape_until": _step_scrape_until,
        "http_json": _step_http_json, "corrupt_ckpt": _step_corrupt_ckpt,
        "drain": _step_drain, "sleep": _step_sleep,
        "assert": _step_assert,
    }

    def conduct(self) -> dict:
        started = time.monotonic()
        self._reaper.start()
        result = {"scenario": self.data["name"], "ok": True,
                  "phase": None, "error": None, "rcs": self.rcs,
                  "steps": self.steps_out, "assertions": [],
                  "series": [], "perfwatch": {"ran": False},
                  "series_skipped": [], "elapsed_sec": None}
        steps = list(self.data["steps"])
        steps += [dict(a, do="assert")
                  for a in self.data.get("assertions") or []]
        try:
            for i, step in enumerate(steps):
                kind = step["do"]
                label = step.get("label", f"s{i}:{kind}")
                phase = step.get("phase", kind)
                entry = {"label": label, "do": kind, "phase": phase}
                if kind == "assert":
                    entry["check"] = step["check"]
                self._log(f"{label} ({phase})")
                try:
                    observed = self._EXECUTORS[kind](self, step)
                except StepFailure as f:
                    entry.update(ok=False, observed=f.observed)
                    if f.error:
                        entry["error"] = f.error
                    if f.tail is not None:
                        entry["tail"] = f.tail
                    self.steps_out.append(entry)
                    self.observed[label] = f.observed
                    result.update(ok=False, phase=phase,
                                  error=f.error)
                    break
                entry.update(ok=True, observed=observed)
                if kind in ("run", "wait_exit", "stop", "start"):
                    child = self._child(step["proc"])
                    entry["tail"] = child.tail()
                self.steps_out.append(entry)
                self.observed[label] = observed
            else:
                self._emit_series(result)
        finally:
            self._kill_survivors()
            self._stop.set()
            self._reaper.join(timeout=5)
            with self._lock:
                children = list(self._children.values())
            for child in children:
                child.close()
        result["elapsed_sec"] = round(time.monotonic() - started, 1)
        self._write_result(result)
        return result

    # ----------------------------------------------- series → perfwatch
    _FIELD_PREFIX = {"steps_per_sec": "sweep:",
                     "hbm_bytes_peak": "sweep-mem:",
                     "time_to_ready_s": "sweep-ttr:",
                     "latency_ms": "sweep-lat:",
                     "scenario_value": "sweep-scn:"}

    def _series_value(self, entry):
        source = entry["source"]
        if source == "metrics":
            from tpu_resnet.obs.spans import load_jsonl

            records = load_jsonl(os.path.join(entry["dir"],
                                              "metrics.jsonl"), "step")
            field = entry.get("field", "steps_per_sec")
            values = [r[field] for r in records
                      if r.get(field)
                      and r["step"] >= entry.get("min_step", 0)
                      and r["step"] <= entry.get("max_step", 1 << 60)]
            if not values:
                return None
            nd = entry.get("round", 3)
            mean = sum(values) / len(values)  # stat: mean
            # Keep the pre-scale mean alongside: normalized points feed
            # perfwatch cohorts, raw feeds byte-compatible probe JSON.
            return (round(mean * entry.get("scale", 1), nd),
                    round(mean, nd))
        if source == "ledger":
            path = os.path.join(entry["dir"], "memory.json")
            try:
                with open(path) as f:
                    entries = json.load(f).get("entries", {})
            except (OSError, ValueError):
                return None
            want_opt = entry.get("entry", "opt_state") == "opt_state"
            for _, e in sorted(entries.items()):
                if not want_opt or "opt_state_argument_bytes" in e:
                    value = e.get(entry.get("field", "peak_bytes"), 0)
                    return int(value) if value else None
            return None
        if source == "loadgen":
            try:
                with open(entry["path"]) as f:
                    data = json.load(f)
            except (OSError, ValueError):
                return None
            return _assertions.dotted_get(data, entry["field"])
        if source == "observed":
            return (self.observed.get(entry["step"]) or {}).get(
                entry["key"])
        return None

    def _emit_series(self, result: dict) -> None:
        """Build the sweep-shaped trajectory, hand it (plus any raw
        pass-through files) to tools/perfwatch.py, and record which
        expected metric tokens it printed. Scenario-native values ride
        the ``sweep-scn:<scenario>:<metric>`` prefix; entries may opt
        into a legacy field (steps_per_sec / hbm_bytes_peak / ...) to
        extend the historical probe cohorts."""
        entries = self.data.get("series") or []
        if not entries:
            return
        points, expected, extra_files = [], [], []
        for entry in entries:
            if entry["source"] == "file":
                extra_files.append(entry["path"])
                try:
                    with open(entry["path"]) as f:
                        for p in json.load(f).get("points", []):
                            if p.get("id"):
                                expected.append(f"sweep:{p['id']}")
                except (OSError, ValueError):
                    result["perfwatch"] = {
                        "ran": False,
                        "reason": f"unreadable {entry['path']}"}
                    result["ok"] = False
                    result.setdefault("phase", "perfwatch")
                continue
            value = self._series_value(entry)
            raw = None
            if isinstance(value, tuple):
                value, raw = value
            if value is None:
                result["series_skipped"].append(entry["id"])
                continue
            out_field = entry.get("out", "scenario_value")
            point_id = (entry["id"] if out_field != "scenario_value"
                        else f"{self.data['name']}:{entry['id']}")
            point = {"id": point_id, "status": "ok", "backend": "cpu"}
            if out_field != "steps_per_sec":
                point["steps_per_sec"] = 1.0
            point[out_field] = value
            if raw is not None and raw != value:
                point["raw_value"] = raw
            points.append(point)
            expected.append(self._FIELD_PREFIX[out_field] + point_id)
        result["series"] = points
        script = os.path.join(self.root, "tools", "perfwatch.py")
        if not os.path.exists(script):
            result["perfwatch"] = {
                "ran": False, "reason": "no tools/perfwatch.py"}
            return
        if not points and not extra_files:
            result["perfwatch"] = {
                "ran": False, "reason": "no series samples"}
            return
        argv = [sys.executable, script]
        if points:
            traj_path = os.path.join(self.run_dir, "scenario_sweep.json")
            with open(traj_path, "w") as f:
                json.dump({"metric": f"scenario:{self.data['name']}",
                           "backend": "cpu", "points": points}, f)
            argv += ["--sweep", traj_path]
        for path in extra_files:
            argv += ["--sweep", path]
        try:
            pw = subprocess.run(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                timeout=60)
        except subprocess.TimeoutExpired:
            result["perfwatch"] = {"ran": True, "rc": None,
                                   "hung": True, "ingested": {}}
            result["ok"] = False
            result["phase"] = result["phase"] or "perfwatch"
            return
        ingested = {t: (t in pw.stdout) for t in expected}
        result["perfwatch"] = {
            "ran": True, "rc": pw.returncode, "ingested": ingested,
            "tail": pw.stdout.strip().splitlines()[-TAIL_LINES:]}
        if pw.returncode != 0 or not all(ingested.values()):
            result["ok"] = False
            result["phase"] = result["phase"] or "perfwatch"
            result["error"] = result["error"] or \
                "perfwatch did not ingest every scenario series"

    def _write_result(self, result: dict) -> None:
        path = os.path.join(self.run_dir, RESULT_FILE)
        try:
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(result, f, indent=2)
            os.replace(tmp, path)
        except OSError:
            pass
        if self.stream is not None:
            print("RESULT_JSON: " + json.dumps(result),
                  file=self.stream, flush=True)


def conduct(data: dict, run_dir: str, stream=None) -> dict:
    """Run a validated scenario dict in ``run_dir`` (templates must
    already be expanded by the caller — see :func:`conduct_file`)."""
    return Conductor(data, run_dir, stream=stream).conduct()


def conduct_file(path: str, run_dir: str = None, stream=None) -> dict:
    """Load, validate, template-expand and run one scenario file. With
    no ``run_dir`` a temporary scratch directory is created and removed
    afterwards. Validation errors return a failed result without
    spawning anything (``"phase": "validate"``)."""
    import tempfile

    data, errors = _spec.load_scenario(path)
    if errors:
        return {"scenario": (data or {}).get("name") or
                os.path.basename(path), "ok": False,
                "phase": "validate", "error": "scenario file invalid",
                "validation_errors": errors}
    if run_dir is not None:
        os.makedirs(run_dir, exist_ok=True)
        expanded = _spec.expand_templates(data, run_dir,
                                          _spec.repo_root())
        return conduct(expanded, run_dir, stream=stream)
    with tempfile.TemporaryDirectory(
            prefix=f"tpu_resnet_scn_{data['name']}_") as d:
        expanded = _spec.expand_templates(data, d, _spec.repo_root())
        return conduct(expanded, d, stream=stream)
