"""``python -m tpu_resnet scenario {run,list,validate}``.

Exit codes follow resilience/exitcodes: 0 on success, 1 when a drill
ran and failed its contract, USAGE_ERROR (2) for bad invocations AND
invalid scenario files — a malformed drill file is an authoring error,
not a drill failure.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpu_resnet.resilience.exitcodes import USAGE_ERROR
from tpu_resnet.scenario import catalog, conductor, spec


def _cmd_list(args) -> int:
    rows = [(s["name"], s["tier"], s["description"], s["path"])
            for s in catalog.list_scenarios()]
    rows += [(name, "legacy", desc,
              f"tools/doctor.py --{name.replace('_', '-')}")
             for name, desc in sorted(catalog.LEGACY_PROBES.items())]
    if not rows:
        print("no scenarios found (scenarios/ missing?)")
        return 1
    width = max(len(r[0]) for r in rows)
    for name, tier, desc, path in rows:
        print(f"{name:{width}s}  [{tier:6s}]  {desc}")
        if args.paths:
            print(f"{'':{width}s}            {path}")
    return 0


def _cmd_validate(args) -> int:
    rc = 0
    for ref in args.scenario:
        path = catalog.scenario_path(ref)
        _, errors = spec.load_scenario(path)
        if errors:
            rc = USAGE_ERROR
            print(f"{path}: INVALID")
            for e in errors:
                print(f"  [{e['error']}] {e['where']}: {e['detail']}")
        else:
            print(f"{path}: ok")
    return rc


def _cmd_run(args) -> int:
    path = catalog.scenario_path(args.scenario)
    result = conductor.conduct_file(
        path, run_dir=args.run_dir,
        stream=None if args.quiet else sys.stdout)
    if args.quiet:
        print("RESULT_JSON: " + json.dumps(result), flush=True)
    if result.get("phase") == "validate":
        for e in result.get("validation_errors", []):
            print(f"  [{e['error']}] {e['where']}: {e['detail']}",
                  file=sys.stderr)
        return USAGE_ERROR
    return 0 if result.get("ok") else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpu_resnet scenario",
        description="run / list / validate declarative chaos scenarios")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_run = sub.add_parser("run", help="conduct one scenario file")
    p_run.add_argument("scenario",
                       help="scenario name (scenarios/<name>.json) or "
                            "a file path")
    p_run.add_argument("--run-dir", default=None,
                       help="keep artifacts here instead of a "
                            "temporary directory")
    p_run.add_argument("--quiet", action="store_true",
                       help="suppress per-step progress; still prints "
                            "the final RESULT_JSON line")

    p_list = sub.add_parser("list",
                            help="every scenario file + legacy probe")
    p_list.add_argument("--paths", action="store_true",
                        help="also print file paths")

    p_val = sub.add_parser("validate",
                           help="schema-check scenario files (rc 2 on "
                                "any error)")
    p_val.add_argument("scenario", nargs="+",
                       help="scenario names or file paths")

    args = parser.parse_args(argv)
    return {"run": _cmd_run, "list": _cmd_list,
            "validate": _cmd_validate}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
