"""Scenario pass/fail assertion evaluators.

Each checker receives the (template-expanded) assert step plus the
running :class:`~tpu_resnet.scenario.conductor.Conductor` and returns an
observation dict; a missed contract raises ``StepFailure`` carrying the
same observation, so the RESULT_JSON shows WHAT was seen either way and
the doctor adapters can rebuild their historical DOCTOR_JSON dicts from
the observations alone.

Imports of obs/* stay function-scope: those modules are stdlib at
module scope today, but this package's jax-free contract must not hinge
on theirs.
"""

from __future__ import annotations

import json
import os


def dotted_get(obj, dotted: str):
    """``dotted_get({"a": {"b": 3}}, "a.b") == 3``; None on any miss."""
    for part in dotted.split("."):
        if not isinstance(obj, dict) or part not in obj:
            return None
        obj = obj[part]
    return obj


def _fail(observed=None, error=None, tail=None):
    from tpu_resnet.scenario.conductor import StepFailure

    raise StepFailure(error=error, observed=observed, tail=tail)


def _load_json(path: str, observed_key: str = "path"):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        _fail(observed={observed_key: path},
              error=f"{os.path.basename(path)} unreadable: {e}")


# ---------------------------------------------------------------- checks
def _check_ckpt_step(step, conductor):
    steps = conductor._ckpt_steps(step["dir"])
    observed = {"ckpt_steps": steps}
    if step["step"] not in steps:
        _fail(observed,
              f"no checkpoint at step {step['step']}")
    return observed


def _check_run_spans(step, conductor):
    spans = conductor._run_spans(step["dir"])
    observed = {"run_spans": spans}
    if spans != [list(s) for s in step["spans"]]:
        _fail(observed, "run-span history does not match")
    return observed


def _check_span(step, conductor):
    from tpu_resnet.obs.spans import load_spans

    path = os.path.join(step["dir"], step.get("file", "events.jsonl"))
    spans = [s for s in load_spans(path) if s["span"] == step["name"]]
    observed = {"spans": spans}
    if not spans:
        _fail(observed, f"{step['name']} span missing")
    last = spans[-1]
    for dotted, want in (step.get("attrs") or {}).items():
        if dotted_get(last, dotted) != want:
            _fail(observed,
                  f"{step['name']} span has {dotted}="
                  f"{dotted_get(last, dotted)!r}, wanted {want!r}")
    return observed


def _check_artifact_json(step, conductor):
    data = _load_json(step["path"])
    observed = {k: dotted_get(data, d)
                for k, d in (step.get("collect") or {}).items()}
    for dotted, want in (step.get("expect") or {}).items():
        got = dotted_get(data, dotted)
        if got != want:
            observed["artifact"] = data
            _fail(observed,
                  f"{os.path.basename(step['path'])} has "
                  f"{dotted}={got!r}, wanted {want!r}")
    return observed


def _loss_stream(directory: str) -> dict:
    from tpu_resnet.obs.spans import load_jsonl

    records = load_jsonl(os.path.join(directory, "metrics.jsonl"),
                         "step")
    return {r["step"]: r["loss"] for r in records if "loss" in r}


def _check_loss_parity(step, conductor):
    ref = _loss_stream(step["ref_dir"])
    got = _loss_stream(step["dir"])
    if not ref or set(ref) != set(got):
        _fail({"reference_steps": sorted(ref),
               "elastic_steps": sorted(got)},
              "logged steps differ across the reshape")
    tol = float(step["tol"])
    worst = max(ref, key=lambda s: abs(ref[s] - got[s]))
    drift = abs(ref[worst] - got[worst])
    if drift > tol:
        _fail({"loss_steps": len(ref), "max_loss_drift": drift},
              f"loss stream diverged at step {worst}: "
              f"|{ref[worst]} - {got[worst]}| = {drift:g} > {tol:g}")
    return {"loss_steps": len(ref), "max_loss_drift": drift}


def _check_ledger_nonzero(step, conductor):
    ledger = _load_json(step["path"]).get("entries", {})
    bad = [k for k, e in ledger.items()
           if not all(e.get(f, 0) > 0 for f in step["fields"])]
    observed = {"entries": sorted(ledger), "missing_bytes": sorted(bad)}
    if not ledger or bad:
        _fail(observed,
              "ledger empty or missing nonzero "
              + "/".join(step["fields"]))
    return observed


def _check_ledger_keys_match(step, conductor):
    memory_keys = sorted(_load_json(step["memory"]).get("entries", {}))
    flops_keys = sorted(_load_json(step["flops"]).get("entries", {}))
    if memory_keys != flops_keys:
        _fail({"memory_keys": memory_keys, "flops_keys": flops_keys},
              "memory.json and flops.json certify different program "
              "keys")
    return {"ledger_keys": flops_keys}


def _opt_entry(directory: str):
    """First (sorted) ledger entry carrying the optimizer-slot
    breakdown, or (None, None)."""
    ledger = _load_json(os.path.join(directory, "memory.json")) \
        .get("entries", {})
    for key in sorted(ledger):
        if "opt_state_argument_bytes" in ledger[key]:
            return key, ledger[key]
    return None, None


def _check_ledger_opt_ratio(step, conductor):
    r_key, r = _opt_entry(step["replicated_dir"])
    z_key, z = _opt_entry(step["zero1_dir"])
    if r is None or z is None:
        _fail({"replicated_key": r_key, "zero1_key": z_key},
              "ledger entry with the optimizer-slot breakdown missing")
    r_opt = r.get("opt_state_argument_bytes", 0)
    z_opt = z.get("opt_state_argument_bytes", 0)
    ratio = (z_opt / r_opt) if r_opt else float("inf")
    observed = {"replicated_key": r_key, "zero1_key": z_key,
                "opt_bytes_replicated": r_opt,
                "opt_bytes_zero1": z_opt,
                "opt_ratio": round(ratio, 4),
                "zero1_alias_bytes": z.get("alias_bytes", 0)}
    if not (0 < z_opt and ratio < float(step["lt"])
            and z.get("alias_bytes", 0) > 0):
        _fail(observed,
              f"zero1 optimizer-slot argument bytes not < "
              f"{step['lt']}x the replicated twin's with donation "
              f"intact")
    return observed


def _check_trace_export(step, conductor):
    from tpu_resnet.obs.trace import export_trace

    directory = step["dir"]
    try:
        _, trace = export_trace(directory)
    except (OSError, ValueError) as e:
        _fail(error=f"{type(e).__name__}: {e}")
    run_id = None
    try:
        with open(os.path.join(directory, "manifest.json")) as f:
            run_id = json.load(f).get("run_id")
    except (OSError, ValueError):
        pass
    span_names = {e.get("name") for e in trace.get("traceEvents", [])}
    observed = {"run_id": run_id,
                "trace_events": len(trace.get("traceEvents", [])),
                "span_names": sorted(n for n in span_names if n)}
    ok = (run_id is not None
          and trace.get("metadata", {}).get("run_id") == run_id
          and set(step["require_spans"]) <= span_names)
    if not ok:
        _fail(observed,
              "trace export run_id mismatch or required spans missing")
    return observed


def _check_oom_report(step, conductor):
    from tpu_resnet.obs.memory import validate_oom_report

    report = _load_json(step["path"])
    problems = validate_oom_report(report)
    census = report.get("live_arrays") or {}
    if not census:
        problems = list(problems) + ["live-array census is empty"]
    observed = {"problems": problems,
                "oom_census_buckets": len(census.get("buckets", [])),
                "oom_census_bytes": census.get("total_bytes")}
    if problems:
        _fail(observed, "oom_report.json failed forensic validation")
    return observed


def _check_sweep_trajectory(step, conductor):
    points = _load_json(step["path"]).get("points", [])
    ids = {p.get("id") for p in points}
    complete = ids == set(step["expect_ids"])
    statuses = {p.get("id"): p.get("status") for p in points}
    all_ok = bool(points) and all(s == "ok" for s in statuses.values())
    deadline_honored = all(
        p.get("deadline_margin_sec", 0) > 0
        for p in points if p.get("status") == "ok")
    observed = {"complete": complete, "statuses": statuses,
                "deadline_honored": deadline_honored}
    if not (complete and all_ok and deadline_honored):
        _fail(observed, "sweep trajectory incomplete, failed, or over "
                        "deadline")
    return observed


def _check_loadgen_result(step, conductor):
    data = _load_json(step["path"])
    observed = {k: data.get(k, 0)
                for k in ("requests_ok", "failed", "timeouts",
                          "connect_failures")}
    bounds = (("failed", "max_failed", False),
              ("timeouts", "max_timeouts", False),
              ("connect_failures", "max_connect_failures", False),
              ("requests_ok", "min_ok", True))
    for field, knob, is_min in bounds:
        if knob not in step:
            continue
        got, want = observed[field], step[knob]
        if (got < want) if is_min else (got > want):
            _fail(observed,
                  f"loadgen {field}={got} violates {knob}={want}")
    return observed


def _check_burst_state(step, conductor):
    path = os.path.join(step["dir"], "fault_burst_state.json")
    state = _load_json(path)
    observed = {"burst": state}
    if state.get("fired", 0) != step["fired"]:
        _fail(observed,
              f"preempt burst fired {state.get('fired')} times, "
              f"expected {step['fired']}")
    return observed


def _check_file_exists(step, conductor):
    if not os.path.exists(step["path"]):
        _fail({"path": step["path"]},
              f"{step['path']} was never written")
    return {"path": step["path"]}


_CHECKERS = {
    "ckpt_step": _check_ckpt_step,
    "run_spans": _check_run_spans,
    "span": _check_span,
    "artifact_json": _check_artifact_json,
    "loss_parity": _check_loss_parity,
    "ledger_nonzero": _check_ledger_nonzero,
    "ledger_keys_match": _check_ledger_keys_match,
    "ledger_opt_ratio": _check_ledger_opt_ratio,
    "trace_export": _check_trace_export,
    "oom_report": _check_oom_report,
    "sweep_trajectory": _check_sweep_trajectory,
    "loadgen_result": _check_loadgen_result,
    "burst_state": _check_burst_state,
    "file_exists": _check_file_exists,
}


def evaluate(step: dict, conductor) -> dict:
    return _CHECKERS[step["check"]](step, conductor)
