"""The scenario catalog: checked-in drill files under ``scenarios/``.

``scenario list`` and ``doctor --list-probes`` both read this module so
the two surfaces can never drift: every scenario FILE plus every legacy
probe that still runs as bespoke code shows up in one listing with a
one-line description.
"""

from __future__ import annotations

import json
import os

from tpu_resnet.scenario import spec as _spec

# Doctor probes that still run as bespoke code, not scenario files: the
# fleet drills juggle per-replica hot-reload traffic loops and fleetmon
# burn-alert timing that the declarative step grammar does not yet
# express. Listed so `scenario list` shows the WHOLE drill surface.
LEGACY_PROBES = {
    "check": "end-to-end smoke: train + eval one batch on scrubbed CPU",
    "data_bench": "input-pipeline throughput bench (no accelerator)",
    "coldstart_probe": "AOT registry kills the warm-start recompile",
    "fleet_probe": "router + 2 replicas: hot reload, drain, merged trace",
    "fleetmon_probe": "fleet SLO aggregator: burn alerts + request lanes",
    "perfwatch": "regression-gate the perf ledger against baselines",
}


def scenarios_dir() -> str:
    return os.path.join(_spec.repo_root(), "scenarios")


def scenario_path(name: str) -> str:
    """Resolve a scenario reference: an existing file path wins, then
    ``scenarios/<name>.json`` (and ``.toml``)."""
    if os.path.exists(name):
        return name
    for ext in (".json", ".toml"):
        candidate = os.path.join(scenarios_dir(), name + ext)
        if os.path.exists(candidate):
            return candidate
    return os.path.join(scenarios_dir(), name + ".json")


def list_scenarios() -> list:
    """Sorted ``{"name", "path", "description", "tier"}`` for every
    scenario file; unparseable files still list (description flags the
    breakage) so a bad checked-in file can't hide from the catalog."""
    out = []
    directory = scenarios_dir()
    if not os.path.isdir(directory):
        return out
    for fname in sorted(os.listdir(directory)):
        if not fname.endswith((".json", ".toml")):
            continue
        path = os.path.join(directory, fname)
        name = fname.rsplit(".", 1)[0]
        description, tier = "(unparseable scenario file)", "?"
        try:
            with open(path, "rb") as f:
                data = json.loads(f.read().decode()) \
                    if fname.endswith(".json") else None
            if data is None:  # .toml on an interpreter without tomllib
                description, tier = "(toml scenario)", "?"
            else:
                name = data.get("name", name)
                description = data.get("description", description)
                tier = data.get("tier", "slow")
        except (OSError, ValueError):
            pass
        out.append({"name": name, "path": path,
                    "description": description, "tier": tier})
    return out
