"""Scenario schema: load + validate, with NAMED errors.

A scenario file is data, not code — so a typo'd field must fail
``scenario validate`` with an error an author can grep for, not surface
as a KeyError inside the conductor mid-drill. Validation is hand-rolled
(no external schema dependency) and exhaustive: unknown fields are
rejected everywhere, process references are resolved, fault keys are
checked against the ``faultinject`` env contract, and exit-code
expectations resolve through ``resilience/exitcodes``.

Errors are dicts ``{"error": <name>, "where": <path>, "detail": ...}``
where ``<name>`` is one of ERROR_NAMES — the test surface and the
``scenario validate`` output contract (rc 2 on any error).
"""

from __future__ import annotations

import json
import os
from typing import Optional

# Process kinds the conductor knows how to spawn (conductor._build_argv).
PROC_KINDS = ("train", "train_and_eval", "eval", "serve", "route",
              "fleetmon", "autopilot", "loadgen", "supervise", "sweep",
              "cmd")

# The faultinject env contract: TPU_RESNET_FAULT_<key> (faultinject.py
# FaultPlan.from_config). Validated here so a typo'd fault silently
# injecting nothing is impossible.
FAULT_KEYS = ("NAN_STEP", "STALL_STEP", "STALL_SEC", "SIGTERM_STEP",
              "CORRUPT_CKPT", "OOM_STEP", "PREEMPT_BURST",
              "PREEMPT_BURST_EVERY", "SERVE_SLOW_MS", "SERVE_HANG_REQ",
              "SERVE_KILL_REQ", "SERVE_DROP_REQ")

# Symbolic exit-code expectations → resilience/exitcodes names.
RC_NAMES = ("done", "drained", "preempt", "no_capacity", "usage_error",
            "nonzero", "any")

STEP_KINDS = ("run", "start", "signal", "wait_exit", "stop",
              "wait_ready", "predict", "scrape", "scrape_until",
              "http_json", "corrupt_ckpt", "drain", "sleep", "assert")

ASSERT_CHECKS = ("ckpt_step", "run_spans", "span", "artifact_json",
                 "loss_parity", "ledger_nonzero", "ledger_keys_match",
                 "ledger_opt_ratio", "trace_export", "oom_report",
                 "sweep_trajectory", "loadgen_result", "burst_state",
                 "file_exists")

SERIES_SOURCES = ("metrics", "ledger", "loadgen", "observed", "file")

ERROR_NAMES = ("unreadable", "not_an_object", "missing_field",
               "unknown_field", "bad_type", "empty", "unknown_kind",
               "unknown_step", "unknown_check", "unknown_source",
               "unknown_proc", "unknown_fault", "bad_expect_rc",
               "duplicate_label", "toml_unsupported")


def _err(name: str, where: str, detail: str) -> dict:
    assert name in ERROR_NAMES
    return {"error": name, "where": where, "detail": detail}


def load_scenario(path: str):
    """(data, errors): parse ``path`` as JSON (or TOML when the
    interpreter ships ``tomllib``) and validate. ``data`` is None when
    the file can't be parsed at all."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        return None, [_err("unreadable", path, f"{type(e).__name__}: {e}")]
    if path.endswith(".toml"):
        try:
            import tomllib
        except ImportError:
            return None, [_err("toml_unsupported", path,
                               "this interpreter has no tomllib (needs "
                               "python >= 3.11); use JSON")]
        try:
            data = tomllib.loads(raw.decode())
        except (ValueError, UnicodeDecodeError) as e:
            return None, [_err("unreadable", path,
                               f"TOML parse failed: {e}")]
    else:
        try:
            data = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError) as e:
            return None, [_err("unreadable", path,
                               f"JSON parse failed: {e}")]
    return data, validate_scenario(data)


# --------------------------------------------------------------- checks
def _check_fields(obj: dict, where: str, required: dict, optional: dict,
                  errors: list) -> None:
    """Required/optional field presence + type checks; unknown fields
    are named errors (the drill author typo'd something)."""
    for field, types in required.items():
        if field not in obj:
            errors.append(_err("missing_field", where,
                               f"required field {field!r} missing"))
        elif not isinstance(obj[field], types):
            errors.append(_err("bad_type", f"{where}.{field}",
                               f"expected {types}, got "
                               f"{type(obj[field]).__name__}"))
    for field, value in obj.items():
        if field in required:
            continue
        if field not in optional:
            errors.append(_err("unknown_field", f"{where}.{field}",
                               f"unknown field {field!r}"))
        elif not isinstance(value, optional[field]):
            errors.append(_err("bad_type", f"{where}.{field}",
                               f"expected {optional[field]}, got "
                               f"{type(value).__name__}"))


def _check_expect_rc(value, where: str, errors: list) -> None:
    items = value if isinstance(value, list) else [value]
    for item in items:
        if isinstance(item, bool) or not isinstance(item, (int, str)):
            errors.append(_err("bad_expect_rc", where,
                               f"expected int or one of {RC_NAMES}, "
                               f"got {item!r}"))
        elif isinstance(item, str) and item not in RC_NAMES:
            errors.append(_err("bad_expect_rc", where,
                               f"{item!r} is not one of {RC_NAMES}"))


_NUM = (int, float)
_STR_NUM_BOOL = (str, int, float, bool)
_EXPECT_RC = (int, str, list)
_CKPT_SPEC = {"dir": (str,), "step": (int,)}

# Per-step allowed fields beyond the common {label, phase, timeout}.
_STEP_REQUIRED = {
    "run": {"proc": (str,)},
    "start": {"proc": (str,)},
    "signal": {"proc": (str,), "sig": (str,)},
    "wait_exit": {"proc": (str,)},
    "stop": {"proc": (str,)},
    "wait_ready": {"proc": (str,), "dir": (str,)},
    "predict": {"dir": (str,), "shape": (list,)},
    "scrape": {"source": (str,), "dir": (str,), "metrics": (list,)},
    "scrape_until": {"proc": (str,), "source": (str,), "dir": (str,),
                     "conditions": (list,)},
    "http_json": {"source": (str,), "dir": (str,), "path": (str,)},
    "corrupt_ckpt": {"dir": (str,)},
    "drain": {"dir": (str,), "replica": (str,)},
    "sleep": {"seconds": _NUM},
    "assert": {"check": (str,)},
}
_STEP_OPTIONAL = {
    "run": {"expect_rc": _EXPECT_RC, "expect_ckpt": (dict,),
            "expect_run_spans": (dict,)},
    "start": {},
    "signal": {},
    "wait_exit": {"expect_rc": _EXPECT_RC, "expect_ckpt": (dict,),
                  "expect_run_spans": (dict,), "timeout_error": (str,)},
    "stop": {"sig": (str,), "expect_rc": _EXPECT_RC,
             "timeout_error": (str,)},
    "wait_ready": {"name": (str,), "min_replicas": (int,),
                   "source": (str,), "timeout_error": (str,)},
    "predict": {"target": (str,), "name": (str,), "n": (int,),
                "expect_predictions": (int,), "required": (bool,),
                "lane": (str,)},
    "scrape": {"name": (str,)},
    "scrape_until": {"collect": (list,), "name": (str,),
                     "timeout_error": (str,)},
    "http_json": {"name": (str,), "until": (dict,), "collect": (dict,)},
    "corrupt_ckpt": {"step": (int,)},
    "drain": {},
    "sleep": {},
    "assert": {},  # remaining fields validated per-check below
}

_ASSERT_REQUIRED = {
    "ckpt_step": {"dir": (str,), "step": (int,)},
    "run_spans": {"dir": (str,), "spans": (list,)},
    "span": {"dir": (str,), "name": (str,)},
    "artifact_json": {"path": (str,)},
    "loss_parity": {"dir": (str,), "ref_dir": (str,), "tol": _NUM},
    "ledger_nonzero": {"path": (str,), "fields": (list,)},
    "ledger_keys_match": {"memory": (str,), "flops": (str,)},
    "ledger_opt_ratio": {"replicated_dir": (str,), "zero1_dir": (str,),
                         "lt": _NUM},
    "trace_export": {"dir": (str,), "require_spans": (list,)},
    "oom_report": {"path": (str,)},
    "sweep_trajectory": {"path": (str,), "expect_ids": (list,)},
    "loadgen_result": {"path": (str,)},
    "burst_state": {"dir": (str,), "fired": (int,)},
    "file_exists": {"path": (str,)},
}
_ASSERT_OPTIONAL = {
    "span": {"file": (str,), "attrs": (dict,)},
    "artifact_json": {"expect": (dict,), "collect": (dict,)},
    "loadgen_result": {"max_failed": (int,), "max_timeouts": (int,),
                       "max_connect_failures": (int,), "min_ok": (int,)},
}

_SERIES_REQUIRED = {
    "metrics": {"id": (str,), "dir": (str,)},
    "ledger": {"id": (str,), "dir": (str,)},
    "loadgen": {"id": (str,), "path": (str,), "field": (str,)},
    "observed": {"id": (str,), "step": (str,), "key": (str,)},
    "file": {"path": (str,)},
}
_SERIES_OPTIONAL = {
    "metrics": {"field": (str,), "stat": (str,), "min_step": (int,),
                "max_step": (int,), "scale": _NUM, "round": (int,),
                "out": (str,)},
    "ledger": {"entry": (str,), "field": (str,), "out": (str,)},
    "loadgen": {"out": (str,)},
    "observed": {"out": (str,)},
    "file": {},
}


def _validate_step(i: int, step, proc_names, labels: set,
                   errors: list) -> None:
    where = f"steps[{i}]"
    if not isinstance(step, dict):
        errors.append(_err("bad_type", where, "step must be an object"))
        return
    kind = step.get("do")
    if kind not in STEP_KINDS:
        errors.append(_err("unknown_step", where,
                           f"do={kind!r} is not one of {STEP_KINDS}"))
        return
    common_opt = {"do": (str,), "label": (str,), "phase": (str,),
                  "timeout": _NUM}
    if kind == "assert":
        check = step.get("check")
        if not isinstance(check, str) or check not in ASSERT_CHECKS:
            errors.append(_err("unknown_check", where,
                               f"check={check!r} is not one of "
                               f"{ASSERT_CHECKS}"))
            return
        required = dict(_ASSERT_REQUIRED[check], check=(str,))
        optional = dict(_ASSERT_OPTIONAL.get(check, {}), **common_opt)
    else:
        required = _STEP_REQUIRED[kind]
        optional = dict(_STEP_OPTIONAL[kind], **common_opt)
    _check_fields(step, where, required, optional, errors)
    proc = step.get("proc")
    if proc is not None and isinstance(proc, str) \
            and proc not in proc_names:
        errors.append(_err("unknown_proc", f"{where}.proc",
                           f"step references undeclared process "
                           f"{proc!r}"))
    if "expect_rc" in step and isinstance(step["expect_rc"], _EXPECT_RC):
        _check_expect_rc(step["expect_rc"], f"{where}.expect_rc", errors)
    for field, shape in (("expect_ckpt", _CKPT_SPEC),):
        sub = step.get(field)
        if isinstance(sub, dict):
            _check_fields(sub, f"{where}.{field}", shape, {}, errors)
    label = step.get("label")
    if isinstance(label, str):
        if label in labels:
            errors.append(_err("duplicate_label", f"{where}.label",
                               f"label {label!r} already used"))
        labels.add(label)


def validate_scenario(data) -> list:
    """Full schema validation → list of named-error dicts (empty when
    the scenario is well-formed)."""
    errors: list = []
    if not isinstance(data, dict):
        return [_err("not_an_object", "$",
                     "scenario root must be an object")]
    _check_fields(
        data, "$",
        required={"name": (str,), "description": (str,),
                  "processes": (dict,), "steps": (list,)},
        optional={"timeout": _NUM, "tier": (str,),
                  "assertions": (list,), "series": (list,)},
        errors=errors)

    processes = data.get("processes")
    proc_names = set(processes) if isinstance(processes, dict) else set()
    if isinstance(processes, dict):
        if not processes:
            errors.append(_err("empty", "$.processes",
                               "a scenario needs at least one process"))
        for name, proc in processes.items():
            where = f"$.processes.{name}"
            if not isinstance(proc, dict):
                errors.append(_err("bad_type", where,
                                   "process must be an object"))
                continue
            kind = proc.get("kind")
            if kind not in PROC_KINDS:
                errors.append(_err("unknown_kind", f"{where}.kind",
                                   f"kind={kind!r} is not one of "
                                   f"{PROC_KINDS}"))
                continue
            required = {"kind": (str,)}
            optional = {"preset": (str,), "devices": (int,),
                        "overrides": (dict,), "args": (list,),
                        "env": (dict,), "faults": (dict,),
                        "cwd": (str,)}
            if kind == "cmd":
                required["argv"] = (list,)
            _check_fields(proc, where, required, optional, errors)
            for k in (proc.get("faults") or {}):
                if k not in FAULT_KEYS:
                    errors.append(_err("unknown_fault",
                                       f"{where}.faults.{k}",
                                       f"{k!r} is not one of "
                                       f"{FAULT_KEYS}"))
            for k, v in (proc.get("overrides") or {}).items():
                if not isinstance(v, _STR_NUM_BOOL):
                    errors.append(_err("bad_type",
                                       f"{where}.overrides.{k}",
                                       "override values must be "
                                       "scalars"))

    steps = data.get("steps")
    if isinstance(steps, list):
        if not steps:
            errors.append(_err("empty", "$.steps",
                               "a scenario needs at least one step"))
        labels: set = set()
        for i, step in enumerate(steps):
            _validate_step(i, step, proc_names, labels, errors)

    for i, a in enumerate(data.get("assertions") or []):
        if not isinstance(a, dict):
            errors.append(_err("bad_type", f"$.assertions[{i}]",
                               "assertion must be an object"))
            continue
        _validate_step(i, dict(a, do="assert"), proc_names, set(),
                       errors)

    for i, s in enumerate(data.get("series") or []):
        where = f"$.series[{i}]"
        if not isinstance(s, dict):
            errors.append(_err("bad_type", where,
                               "series entry must be an object"))
            continue
        source = s.get("source")
        if source not in SERIES_SOURCES:
            errors.append(_err("unknown_source", f"{where}.source",
                               f"source={source!r} is not one of "
                               f"{SERIES_SOURCES}"))
            continue
        required = dict(_SERIES_REQUIRED[source], source=(str,))
        _check_fields(s, where, required, _SERIES_OPTIONAL[source],
                      errors)
    return errors


def resolve_rc(spec) -> Optional[list]:
    """expect_rc spec → concrete list of acceptable codes, or None for
    'any'. ``"nonzero"`` is returned as-is (sentinel the conductor
    checks)."""
    from tpu_resnet.resilience import exitcodes

    names = {"done": exitcodes.DONE, "drained": exitcodes.DRAINED,
             "preempt": exitcodes.PREEMPTED,
             "no_capacity": exitcodes.NO_CAPACITY,
             "usage_error": exitcodes.USAGE_ERROR}
    items = spec if isinstance(spec, list) else [spec]
    if "any" in items:
        return None
    out = []
    for item in items:
        if item == "nonzero":
            out.append("nonzero")
        elif isinstance(item, str):
            out.append(names[item])
        else:
            out.append(int(item))
    return out


def expand_templates(obj, run_dir: str, root: str):
    """Recursively substitute ``{run}``/``{root}``/``{python}`` in every
    string of the (validated) scenario. Plain ``str.replace`` — scenario
    files legitimately hold other braces (JSON-in-args for sweep
    spaces), so ``str.format`` would be a trap."""
    import sys

    if isinstance(obj, str):
        return (obj.replace("{run}", run_dir).replace("{root}", root)
                .replace("{python}", sys.executable))
    if isinstance(obj, list):
        return [expand_templates(v, run_dir, root) for v in obj]
    if isinstance(obj, dict):
        return {k: expand_templates(v, run_dir, root)
                for k, v in obj.items()}
    return obj


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
