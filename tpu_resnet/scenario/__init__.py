"""Declarative chaos scenarios — one conductor for every drill.

The repo grew eight-plus bespoke ``doctor --*-probe/drill`` harnesses
that each hand-rolled the same skeleton: scrubbed-CPU children,
ephemeral ports, discovery-file waits, fault env vars, log-to-file,
survivor kill, RESULT_JSON, perfwatch hand-off. This package inverts
that: a scenario is a checked-in FILE (``scenarios/*.json``, TOML where
the interpreter has ``tomllib``) declaring

``processes``   trainer / serve replicas / router / loadgen /
                supervisor / raw commands, each with preset + config
                overrides + a fault schedule riding the
                ``resilience/faultinject.py`` ``TPU_RESNET_FAULT_*``
                env contract;
``steps``       the timed script: run/start children, wait for
                discovery-file readiness under a deadline, fire predict
                traffic, scrape /metrics until gauges go live, SIGTERM/
                SIGKILL, drain through the router, corrupt a
                checkpoint, assert mid-flight;
``assertions``  exit-code contracts (named via resilience/exitcodes),
                span/gauge/artifact presence, loss-stream parity
                bounds, zero-failed-request loadgen counts;
``series``      metrics handed to tools/perfwatch.py — scenario series
                adopt the ``sweep-scn:<scenario>:<metric>`` prefix so
                any scenario becomes regression-gated with zero glue.

The conductor (``conductor.py``) owns the shared skeleton exactly once:
``hostenv.scrubbed_cpu_env`` children (fault env merged AFTER the scrub
— the scrub strips ``TPU_*``), child logs to files (never pipes — a
chatty child against a full pipe deadlocks ``wait()``), a reaper thread
collecting exits, survivor kill on first failure, and a single
RESULT_JSON writer. ``tools/doctor.py``'s probe flags are thin aliases
that run these files and re-emit their historical DOCTOR_JSON shapes.

Everything here is jax-free at module scope (jaxlint host-isolation
scope): scenarios drill hosts whose accelerator stack is the thing
being broken.

CLI: ``python -m tpu_resnet scenario run|list|validate`` (cli.py).
Authoring reference: docs/SCENARIOS.md.
"""

from tpu_resnet.scenario.catalog import (  # noqa: F401
    LEGACY_PROBES,
    list_scenarios,
    scenario_path,
    scenarios_dir,
)
from tpu_resnet.scenario.conductor import conduct, conduct_file  # noqa: F401
from tpu_resnet.scenario.spec import (  # noqa: F401
    load_scenario,
    validate_scenario,
)
