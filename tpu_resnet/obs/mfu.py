"""MFU accounting — first-class FLOPs/utilization bookkeeping.

The MLPerf TPU-pod scaling report (arXiv:1909.09756) and the pjit TPUv4
training report (arXiv:2204.06514) both drive optimization campaigns off
hardware-utilization accounting, not throughput alone: a steps/sec win
that came from doing less math is not a win. Before this module the
repo's FLOPs math lived ad hoc in two places (bench.py's imagenet entry
and tools/mfu_probe.py) and a *running job* never knew its own MFU. Now:

``PEAK_FLOPS_BY_KIND``  per-device-kind peak dense bf16 FLOP/s (public
                        chip specs), the one table bench/probe/loop share.
``program_flops``       FLOPs of a compiled/lowered XLA program from its
                        cost analysis (handles the list/dict API forms).
``FlopsRegistry``       per-compiled-program FLOPs registry, keyed like
                        the golden-jaxpr entries of the config-matrix
                        verifier (``train|cifar10_rn50_bf16|mesh1x1|b128``)
                        so a FLOPs number is attributable to exactly one
                        certified program shape. Persisted to
                        ``<train_dir>/flops.json`` for tools.
``mfu``                 model FLOPs utilization: achieved model FLOP/s
                        over the mesh's aggregate peak.

Cost analysis runs on the *lowered* (pre-optimization) module via
``jit_fn.lower(...)`` — no second XLA compile, and the pre-fusion count
is the model-FLOPs definition MFU wants (XLA-added recompute, e.g.
remat, is utilization it would be cheating to claim). The lint suite
enforces that these host-side introspection calls never appear in jit
scope (docs/CHECKS.md, rule jit-host-sync): accounting happens once at
compile time, gauges are pure host arithmetic at log boundaries.

Module import stays jax-free (jax appears only inside functions) so
stdlib-only consumers (bench.py's parent process, perfwatch) can use the
peak table and registry file reader without a backend.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, Optional

log = logging.getLogger("tpu_resnet")

REGISTRY_FILE = "flops.json"

# Peak dense bf16 FLOP/s per chip by device_kind substring (public
# specs). Order matters: more specific names first. The single source the
# bench harness, tools/mfu_probe.py and the live mfu gauge all read.
PEAK_FLOPS_BY_KIND = (
    ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v6 lite", 918e12), ("v6e", 918e12),
    ("v4", 275e12),
)


def peak_flops_per_chip(device_kind: str,
                        env_var: str = "TPU_RESNET_PEAK_FLOPS"
                        ) -> Optional[float]:
    """Peak dense FLOP/s for one chip of ``device_kind``; None when the
    kind is unknown (CPU, new silicon). ``env_var`` (and the bench
    harness's historical ``BENCH_PEAK_FLOPS``) overrides the table —
    the escape hatch for chips the table hasn't learned yet."""
    for var in (env_var, "BENCH_PEAK_FLOPS"):
        env = os.environ.get(var)
        if env:
            try:
                return float(env)
            except ValueError:
                log.warning("ignoring non-numeric %s=%r", var, env)
    kind = (device_kind or "").lower()
    for sub, peak in PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return peak
    return None


def program_flops(cost) -> Optional[float]:
    """FLOPs from an XLA cost analysis — ``lowered.cost_analysis()`` or
    ``compiled.cost_analysis()`` (older jax returns a one-element list).
    None when the backend doesn't report them (some PJRT plugins)."""
    try:
        if isinstance(cost, list):
            cost = cost[0] if cost else None
        flops = (cost or {}).get("flops")
        if flops and flops > 0:
            return float(flops)
    except Exception:  # noqa: BLE001 - accounting must never crash a run
        pass
    return None


def lowered_flops(jit_fn, *args) -> Optional[float]:
    """FLOPs of ``jit_fn``'s program for ``args`` via AOT lowering (no
    XLA compile — tracing + HLO cost analysis only). ``args`` may mix
    concrete arrays and ``jax.ShapeDtypeStruct`` avals. The count covers
    the module as written (pre-SPMD-partitioning): for an auto-sharded
    jit program that is the GLOBAL per-step FLOPs."""
    try:
        return program_flops(jit_fn.lower(*args).cost_analysis())
    except Exception as e:  # noqa: BLE001 - never sink the caller
        log.debug("lowered cost analysis unavailable: %s", e)
        return None


def analytic_resnet50_flops(batch: int, image: int = 224) -> float:
    """Analytic fallback: ResNet-50 forward ≈ 4.09 GFLOPs per 224² image
    (He et al.); training ≈ 3× forward (fwd + 2×bwd). Scaled by pixel
    area for other resolutions. GLOBAL per-step FLOPs for ``batch``."""
    return 3 * 4.09e9 * batch * (image / 224.0) ** 2


def mfu(model_flops_per_sec: Optional[float], device_kind: str,
        n_chips: int) -> Optional[float]:
    """Model FLOPs utilization: achieved model FLOP/s over the aggregate
    peak of ``n_chips`` chips of ``device_kind``. None when either side
    is unknown — an unknown chip reports no number rather than a wrong
    one."""
    peak = peak_flops_per_chip(device_kind)
    if not peak or not model_flops_per_sec or n_chips < 1:
        return None
    return model_flops_per_sec / (peak * n_chips)


def train_program_key(cfg, mesh_shape: Dict[str, int],
                      kind: str = "train") -> str:
    """Registry key for the compiled program of ``cfg`` on a mesh:

        train|cifar10_rn50_bf16|mesh1x1|b128

    Pure delegation to :func:`tpu_resnet.programs.spell` — the ONE
    spelling the FLOPs registry, the memory ledger, the check engines'
    coverage map and the AOT executable cache all share (one key = one
    program; key-parity is pinned by tests/test_programs.py).
    ``data.engine`` is deliberately NOT part of the key: thread and
    process engines feed byte-identical programs (the engine-invariance
    twins the verifier pins), so their FLOPs must be one entry.
    ``mesh.partition`` IS: a zero1 step is a different compiled program
    (per-shard optimizer-slot arguments, reduce-scatter/all-gather
    structure), so its space budget must never be read as the
    replicated twin's.
    """
    from tpu_resnet.programs import spell

    return spell(cfg, mesh_shape, kind=kind)


class FlopsRegistry:
    """Per-compiled-program FLOPs entries, persisted per run.

    One entry per program key: global per-step FLOPs, the source of the
    number (xla_cost_analysis | analytic | none), bytes accessed when
    known. The registry file (``<train_dir>/flops.json``) is what
    trace-export, perfwatch and operators read back."""

    def __init__(self):
        self._entries: Dict[str, dict] = {}

    def register(self, key: str, flops_per_step: Optional[float],
                 source: str = "xla_cost_analysis", **extra) -> dict:
        entry = {"flops_per_step": flops_per_step,
                 "flops_source": source if flops_per_step else "none"}
        entry.update(extra)
        self._entries[key] = entry
        return entry

    def get(self, key: str) -> Optional[dict]:
        return self._entries.get(key)

    def flops(self, key: str) -> Optional[float]:
        entry = self._entries.get(key) or {}
        return entry.get("flops_per_step")

    def to_dict(self) -> dict:
        return {"format": 1, "entries": dict(self._entries)}

    def save(self, train_dir: str) -> Optional[str]:
        """Atomic ``<train_dir>/flops.json`` (tmp + rename, like every
        other run artifact)."""
        try:
            os.makedirs(train_dir, exist_ok=True)
            path = os.path.join(train_dir, REGISTRY_FILE)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.to_dict(), f, indent=1)
            os.replace(tmp, path)
            return path
        except OSError as e:
            log.warning("could not write %s: %s", REGISTRY_FILE, e)
            return None

    @classmethod
    def load(cls, train_dir: str) -> "FlopsRegistry":
        reg = cls()
        try:
            with open(os.path.join(train_dir, REGISTRY_FILE)) as f:
                payload = json.load(f)
            reg._entries.update(payload.get("entries", {}))
        except (OSError, ValueError):
            pass
        return reg


def account_train_step(cfg, mesh, state, base_step,
                       per_replica_bn: bool = False,
                       registry: Optional[FlopsRegistry] = None,
                       train_dir: Optional[str] = None) -> dict:
    """Measure and register the train step's per-step FLOPs for ``cfg``
    on ``mesh``. Called ONCE per run right after the first dispatch
    (compile already paid; this adds one abstract trace + HLO cost pass,
    never a second XLA compile). Returns the registry entry.

    The probe lowers the plain sharded single step over abstract batch
    avals — the same program every input path (resident chunks, staged
    superbatches, streaming) runs per step, so one entry covers all
    three dispatch shapes."""
    import jax

    from tpu_resnet import parallel
    from tpu_resnet.train.step import shard_step

    registry = registry or FlopsRegistry()
    key = train_program_key(cfg, dict(mesh.shape))
    bs = parallel.batch_sharding(mesh)
    size = cfg.data.resolved_image_size
    gb = cfg.train.global_batch_size
    # ImageNet streams pre-processed floats; every other dataset feeds
    # raw uint8 and augments on device — match what the step compiles on.
    img_dtype = "float32" if cfg.data.dataset == "imagenet" else "uint8"
    images = jax.ShapeDtypeStruct((gb, size, size, 3), img_dtype,
                                  sharding=bs)
    labels = jax.ShapeDtypeStruct((gb,), "int32", sharding=bs)
    probe = shard_step(base_step, mesh, donate_state=False,
                       per_replica_bn=per_replica_bn)
    flops = lowered_flops(probe, state, images, labels)
    source = "xla_cost_analysis"
    if flops is None and cfg.model.name == "resnet" \
            and cfg.data.dataset == "imagenet":
        flops, source = analytic_resnet50_flops(gb, size), "analytic"
    elif flops is not None and per_replica_bn:
        # The shard_map body is lowered per-shard: scale the local count
        # back to the global batch so the entry means the same thing on
        # every mesh shape.
        flops *= mesh.shape["data"]
    kind = mesh.devices.flat[0].device_kind
    entry = registry.register(
        key, flops, source=source, global_batch=gb,
        device_kind=kind, n_devices=int(mesh.size),
        peak_flops_per_chip=peak_flops_per_chip(kind))
    if train_dir:
        registry.save(train_dir)
    return entry
