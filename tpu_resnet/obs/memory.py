"""Memory observability — the space twin of the MFU/FLOPs accounting.

Every remaining scaling direction is a memory/compute trade the system
could not see: ZeRO-style optimizer-state sharding promises an ~N× cut
per device (arXiv:2004.13336) that nothing could measure, pod meshes
live or die on per-host HBM headroom (arXiv:2204.06514), and the MFU
campaign's next knobs (batch, remat, donation) move temp HBM as much as
they move FLOP/s. This module gives a run the same discipline
``obs/mfu.py`` gave time — measured once, gauged live, pinned golden:

``MemoryLedger``        per-compiled-program HBM budgets extracted from
                        ``compiled.memory_analysis()`` (argument/output/
                        temp/alias/generated-code bytes — donation shows
                        up as aliased bytes), keyed EXACTLY like the
                        FlopsRegistry / golden-jaxpr entries
                        (``train|cifar10_rn50_bf16|mesh8x1|b128``) and
                        persisted to ``<train_dir>/memory.json``.
``sample_device_memory``live per-device HBM gauges via
                        ``device.memory_stats()`` at existing log
                        boundaries — a pure host call, zero device
                        syncs; degrades to absent on backends without
                        stats (CPU), where the pre-declared gauges stay
                        at their explicit zeros.
``write_oom_report``    OOM forensics: on a RESOURCE_EXHAUSTED the loop/
                        serve closer chains persist
                        ``<train_dir>/oom_report.json`` — the last
                        ledger, the recent memory samples, a live-array
                        census (``jax.live_arrays()`` bucketed by
                        shape/dtype/sharding) and the offending program
                        key — so an OOM on a pod is a diagnosable
                        artifact instead of a dead log line.
``HBM_BYTES_BY_KIND``   per-device-kind HBM capacity (public chip
                        specs), the peak-FLOPs table's memory twin, for
                        ``hbm_utilization`` on backends whose
                        ``memory_stats()`` lacks a ``bytes_limit``.

The ledger extraction is the one place this subsystem pays real compile
time: ``memory_analysis()`` only exists on a COMPILED program, and jax's
AOT path shares no cache with the jit-dispatch executable, so
``account_train_step`` costs one extra XLA compile. It runs once per
run, inside the compile window (the loop re-primes its throughput meter
after it), is gated by ``train.memory_ledger`` and degrades to absent —
never a per-step or per-interval cost. The lint suite bans every
introspection call here from jit scope (docs/CHECKS.md, jit-host-sync).

Module import stays jax-free (jax only inside functions) so stdlib-only
consumers (bench.py's parent, tools/perfwatch.py, the doctor checks) can
read ledger files and the capacity table without a backend.
"""
# check: disable-file=jit-host-sync — this module IS the host-side
# memory prober: device.memory_stats()/jax.live_arrays()/
# .memory_analysis() are its whole purpose, called from host code at
# startup, log boundaries and crash handlers only, never from jit scope.

from __future__ import annotations

import collections
import json
import logging
import os
import time
from typing import Dict, List, Optional

log = logging.getLogger("tpu_resnet")

LEDGER_FILE = "memory.json"
OOM_REPORT_FILE = "oom_report.json"

# Per-chip HBM capacity in bytes by device_kind substring (public chip
# specs) — the memory twin of mfu.PEAK_FLOPS_BY_KIND, and the
# ``bytes_limit`` fallback for PJRT plugins whose memory_stats() report
# usage but no capacity. Order matters: more specific names first.
_GIB = 1024 ** 3
HBM_BYTES_BY_KIND = (
    ("v5p", 95 * _GIB),
    ("v5 lite", 16 * _GIB), ("v5e", 16 * _GIB), ("v5litepod", 16 * _GIB),
    ("v6 lite", 32 * _GIB), ("v6e", 32 * _GIB),
    ("v4", 32 * _GIB),
)

# Budget components extracted from CompiledMemoryStats, in report order.
BUDGET_COMPONENTS = ("argument_bytes", "output_bytes", "temp_bytes",
                     "alias_bytes", "generated_code_bytes")


def hbm_bytes_per_chip(device_kind: str,
                       env_var: str = "TPU_RESNET_HBM_BYTES"
                       ) -> Optional[int]:
    """HBM capacity in bytes for one chip of ``device_kind``; None when
    the kind is unknown (CPU, new silicon). ``env_var`` overrides the
    table — the escape hatch for chips it hasn't learned yet."""
    env = os.environ.get(env_var)
    if env:
        try:
            return int(float(env))
        except ValueError:
            log.warning("ignoring non-numeric %s=%r", env_var, env)
    kind = (device_kind or "").lower()
    for sub, cap in HBM_BYTES_BY_KIND:
        if sub in kind:
            return cap
    return None


def budget_from_compiled(compiled) -> Optional[dict]:
    """HBM budget of a compiled program from its
    ``compiled.memory_analysis()`` (None when the backend doesn't report
    one). Bytes are for one device's compiled module (the per-shard SPMD
    program). ``alias_bytes`` is the donation credit: input buffers the
    outputs alias — a broken donation collapses it to ~0 and every step
    double-buffers the state. ``peak_bytes`` counts each aliased byte
    once (argument + output - alias + temp + generated_code)."""
    try:
        ma = compiled.memory_analysis()
    except Exception as e:  # noqa: BLE001 - accounting must never crash
        log.debug("memory analysis unavailable: %s", e)
        return None
    if ma is None:
        return None

    def grab(name: str) -> int:
        try:
            return int(getattr(ma, name, 0) or 0)
        except (TypeError, ValueError):
            return 0

    budget = {
        "argument_bytes": grab("argument_size_in_bytes"),
        "output_bytes": grab("output_size_in_bytes"),
        "temp_bytes": grab("temp_size_in_bytes"),
        "alias_bytes": grab("alias_size_in_bytes"),
        "generated_code_bytes": grab("generated_code_size_in_bytes"),
    }
    budget["peak_bytes"] = (budget["argument_bytes"]
                            + budget["output_bytes"]
                            - budget["alias_bytes"]
                            + budget["temp_bytes"]
                            + budget["generated_code_bytes"])
    return budget


class MemoryLedger:
    """Per-compiled-program HBM budget entries, persisted per run.

    One entry per program key (the FlopsRegistry key spelling, so
    ``memory.json`` and ``flops.json`` describe the same certified
    programs): the budget components plus provenance (device kind,
    device count, per-chip capacity). ``<train_dir>/memory.json`` is
    what trace-export, the doctor mem-probe and operators read back."""

    def __init__(self):
        self._entries: Dict[str, dict] = {}

    def register(self, key: str, budget: Optional[dict], **extra) -> dict:
        entry = dict(budget) if budget else {"budget_source": "none"}
        if budget:
            entry["budget_source"] = "xla_memory_analysis"
        entry.update(extra)
        self._entries[key] = entry
        return entry

    def get(self, key: str) -> Optional[dict]:
        return self._entries.get(key)

    def keys(self) -> List[str]:
        return sorted(self._entries)

    def to_dict(self) -> dict:
        return {"format": 1, "entries": dict(self._entries)}

    def save(self, train_dir: str) -> Optional[str]:
        """Atomic ``<train_dir>/memory.json`` (tmp + rename, like every
        other run artifact)."""
        try:
            os.makedirs(train_dir, exist_ok=True)
            path = os.path.join(train_dir, LEDGER_FILE)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.to_dict(), f, indent=1)
            os.replace(tmp, path)
            return path
        except OSError as e:
            log.warning("could not write %s: %s", LEDGER_FILE, e)
            return None

    @classmethod
    def load(cls, train_dir: str) -> "MemoryLedger":
        ledger = cls()
        try:
            with open(os.path.join(train_dir, LEDGER_FILE)) as f:
                payload = json.load(f)
            ledger._entries.update(payload.get("entries", {}))
        except (OSError, ValueError):
            pass
        return ledger


def lower_train_step(cfg, mesh, state, base_step,
                     per_replica_bn: bool = False,
                     stage_rows: int = 1, chunk_steps: int = 1,
                     variant: str = "single-step",
                     partitioner=None):
    """Lower the train-step program the run's input edge actually
    dispatches, over abstract avals — the ONE shared builder behind the
    HBM (this module) and comms (``obs/comms.py``) accountants, so both
    ledgers describe the same compiled program: ``stage_rows > 1``
    builds the fused staged-chunk jit (``device_data.staged_chunk_jit``,
    the loop's exact constructor — superbatch arguments and scan temps
    included), else the plain sharded single step with the loop's real
    donation and partitioner shardings. Returns ``(lowered, variant)``
    where ``variant`` labels the program shape on ledger entries."""
    import jax

    from tpu_resnet import parallel
    from tpu_resnet.train.step import shard_step

    state_sharding = (partitioner.state_shardings(state)
                     if partitioner is not None and partitioner.is_sharded
                     else None)
    size = cfg.data.resolved_image_size
    gb = cfg.train.global_batch_size
    img_dtype = "float32" if cfg.data.dataset == "imagenet" else "uint8"
    if stage_rows > 1:
        # The staged/double-buffered input edge's fused chunk program —
        # built by the ONE canonical constructor the loop itself
        # dispatches (device_data.staged_chunk_jit), so a ledger entry
        # can never describe a different program than the run executes.
        from tpu_resnet.data.device_data import staged_chunk_jit

        jitted = staged_chunk_jit(base_step, mesh, max(1, chunk_steps),
                                  per_replica_bn=per_replica_bn,
                                  state_sharding=state_sharding)
        gi = jax.ShapeDtypeStruct((stage_rows, gb, size, size, 3),
                                  img_dtype)
        gl = jax.ShapeDtypeStruct((stage_rows, gb), "int32")
        off = jax.ShapeDtypeStruct((), "int32")
        lowered = jitted.lower(state, gi, gl, off)
        variant = (f"staged-chunk(steps={max(1, chunk_steps)}"
                   f",stage={stage_rows})")
    else:
        bs = parallel.batch_sharding(mesh)
        images = jax.ShapeDtypeStruct((gb, size, size, 3), img_dtype,
                                      sharding=bs)
        labels = jax.ShapeDtypeStruct((gb,), "int32", sharding=bs)
        probe = shard_step(base_step, mesh, per_replica_bn=per_replica_bn,
                           state_sharding=state_sharding)
        lowered = probe.lower(state, images, labels)
    return lowered, variant


def account_train_step(cfg, mesh, state, base_step,
                       per_replica_bn: bool = False,
                       stage_rows: int = 1, chunk_steps: int = 1,
                       variant: str = "single-step",
                       partitioner=None,
                       ledger: Optional[MemoryLedger] = None,
                       train_dir: Optional[str] = None) -> dict:
    """Measure and register the train step's HBM budget for ``cfg`` on
    ``mesh``. Called ONCE per run at first dispatch, inside the compile
    window: unlike the FLOPs probe (lowering only), ``memory_analysis``
    needs a COMPILED program and jax's AOT compile shares no cache with
    the already-paid jit dispatch — this is one extra XLA compile,
    amortized over the run and gated by ``train.memory_ledger``.

    The probe compiles the program the run's input edge actually
    dispatches, with the loop's real donation settings, over abstract
    avals: ``stage_rows > 1`` measures the fused staged-chunk program
    (``compile_staged_stream_steps``'s exact jit — superbatch arguments
    and scan temps included), else the plain sharded single step. The
    ``variant`` label is recorded on the entry so an OOM report says
    which program shape its budget describes (the resident path's
    epoch-buffer program is approximated by its single-step twin, and
    says so).

    ``partitioner`` (parallel.StatePartitioner) supplies the run's state
    layout: the probe compiles with the same in_shardings the loop
    dispatches (zero1 = per-shard optimizer-slot arguments) and the
    entry carries the partitioner's analytic per-component breakdown
    (``params_argument_bytes`` / ``opt_state_argument_bytes`` /
    ``batch_stats_argument_bytes``), so the zero1 optimizer cut is a
    named number next to XLA's aggregate ``argument_bytes``."""
    from tpu_resnet.obs.mfu import train_program_key

    ledger = ledger if ledger is not None else MemoryLedger()
    key = train_program_key(cfg, dict(mesh.shape))
    gb = cfg.train.global_batch_size
    lowered, variant = lower_train_step(
        cfg, mesh, state, base_step, per_replica_bn=per_replica_bn,
        stage_rows=stage_rows, chunk_steps=chunk_steps, variant=variant,
        partitioner=partitioner)
    budget = budget_from_compiled(lowered.compile())
    kind = mesh.devices.flat[0].device_kind
    extra = {}
    if partitioner is not None:
        extra["partition"] = partitioner.describe()
        try:
            extra.update(partitioner.state_argument_bytes(state))
        except Exception as e:  # noqa: BLE001 - accounting must not crash
            log.debug("state argument breakdown unavailable: %s", e)
    entry = ledger.register(
        key, budget, program_key=key, program=variant, global_batch=gb,
        device_kind=kind, n_devices=int(mesh.size),
        hbm_bytes_per_chip=hbm_bytes_per_chip(kind), **extra)
    if train_dir:
        ledger.save(train_dir)
    return entry


# ------------------------------------------------------------- live gauges
def sample_device_memory(devices=None) -> Dict[str, float]:
    """One live HBM sample across this host's devices — the gauge values
    the loop publishes at log boundaries. Pure host-side introspection
    (``device.memory_stats()``), zero device syncs.

    Returns ``{}`` when no device reports stats (CPU backends) — the
    degrade-to-absent contract; the pre-declared gauges then stay at
    their explicit zeros. Otherwise: ``hbm_bytes_in_use`` /
    ``hbm_bytes_peak`` are the MAX across local devices (the binding
    device), ``hbm_bytes_limit`` the MIN reported limit (falling back to
    the :data:`HBM_BYTES_BY_KIND` capacity) and ``hbm_utilization`` =
    in_use / limit."""
    if devices is None:
        import jax

        devices = jax.local_devices()
    in_use = peak = 0
    limit: Optional[int] = None
    kind = ""
    seen = False
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 - plugin-specific failures
            stats = None
        if not stats or stats.get("bytes_in_use") is None:
            continue
        seen = True
        kind = kind or getattr(d, "device_kind", "")
        used = int(stats["bytes_in_use"])
        in_use = max(in_use, used)
        peak = max(peak, int(stats.get("peak_bytes_in_use", used)))
        lim = stats.get("bytes_limit")
        if lim:
            limit = int(lim) if limit is None else min(limit, int(lim))
    if not seen:
        return {}
    out = {"hbm_bytes_in_use": float(in_use),
           "hbm_bytes_peak": float(peak)}
    if limit is None:
        limit = hbm_bytes_per_chip(kind)
    if limit:
        out["hbm_bytes_limit"] = float(limit)
        out["hbm_utilization"] = round(in_use / limit, 4)
    return out


def device_memory_detail(devices=None) -> List[dict]:
    """Per-device ``memory_stats()`` snapshot (id/kind + the raw stats
    dict, or ``stats: null`` where unsupported) — the OOM report's
    device section; the gauges above stay scalar."""
    if devices is None:
        import jax

        devices = jax.local_devices()
    detail = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001
            stats = None
        detail.append({"id": int(getattr(d, "id", -1)),
                       "device_kind": str(getattr(d, "device_kind", "?")),
                       "stats": {k: int(v) for k, v in stats.items()
                                 if isinstance(v, (int, float))}
                       if stats else None})
    return detail


class MemorySampleRing:
    """Last-N ring of (wall, step, gauges) memory samples the loop keeps
    so an OOM report can show the minutes BEFORE the kill, not just the
    corpse."""

    def __init__(self, capacity: int = 32):
        self._ring = collections.deque(maxlen=max(1, int(capacity)))

    def add(self, step: int, sample: Dict[str, float]) -> None:
        if sample:
            self._ring.append({"wall": round(time.time(), 3),
                               "step": int(step), **sample})

    def snapshot(self) -> List[dict]:
        return list(self._ring)

    def __len__(self) -> int:
        return len(self._ring)


# ------------------------------------------------------------ OOM forensics
def is_oom_error(exc) -> bool:
    """True for an XLA RESOURCE_EXHAUSTED failure (device out of
    memory). Duck-typed on the class NAME plus the canonical status
    string so this stays importable without jax and also recognizes the
    fault injector's synthetic OOM (a plain RuntimeError carrying the
    same status)."""
    if exc is None or "RESOURCE_EXHAUSTED" not in str(exc):
        return False
    return (type(exc).__name__ == "XlaRuntimeError"
            or isinstance(exc, (RuntimeError, MemoryError)))


def live_array_census(max_buckets: int = 50) -> dict:
    """``jax.live_arrays()`` bucketed by (shape, dtype, sharding):
    count, per-bucket bytes (global logical bytes), sorted largest
    first and capped at ``max_buckets`` buckets (the drop count is
    reported — never a silent truncation). The answer to "WHAT was
    filling HBM" that a bare RESOURCE_EXHAUSTED message never gives."""
    try:
        import jax

        arrays = jax.live_arrays()
    except Exception as e:  # noqa: BLE001 - forensics must never raise
        return {"error": f"{type(e).__name__}: {e}", "buckets": [],
                "total_arrays": 0, "total_bytes": 0}
    buckets: Dict[tuple, dict] = {}
    total_bytes = 0
    for a in arrays:
        try:
            shape = tuple(int(s) for s in a.shape)
            dtype = str(a.dtype)
            sharding = str(getattr(a, "sharding", "?"))[:120]
            nbytes = int(getattr(a, "nbytes", 0))
        except Exception:  # noqa: BLE001 - a deleted/donated buffer
            continue
        key = (shape, dtype, sharding)
        b = buckets.setdefault(key, {"shape": list(shape), "dtype": dtype,
                                     "sharding": sharding, "count": 0,
                                     "bytes": 0})
        b["count"] += 1
        b["bytes"] += nbytes
        total_bytes += nbytes
    ranked = sorted(buckets.values(),
                    key=lambda b: (-b["bytes"], -b["count"],
                                   b["dtype"], b["shape"]))
    return {"buckets": ranked[:max_buckets],
            "dropped_buckets": max(0, len(ranked) - max_buckets),
            "total_arrays": sum(b["count"] for b in ranked),
            "total_bytes": total_bytes}


def write_oom_report(train_dir: str, error, context: str = "train",
                     step: Optional[int] = None,
                     program_key: Optional[str] = None,
                     ledger: Optional[MemoryLedger] = None,
                     samples: Optional[List[dict]] = None,
                     run_id: Optional[str] = None) -> Optional[str]:
    """Persist ``<train_dir>/oom_report.json`` for a RESOURCE_EXHAUSTED
    failure: the error, the offending program key, the last ledger, the
    recent gauge samples, a live-array census and per-device stats.
    Guarded end-to-end (forensics on a dying process must never mask the
    original exception); returns the path or None."""
    try:
        report = {
            "format": 1,
            "written_at": time.time(),
            "context": str(context),
            "step": int(step) if step is not None else None,
            "run_id": run_id,
            "error": {"type": type(error).__name__,
                      "message": str(error)[:4000]},
            "program_key": program_key,
            "ledger": (ledger.to_dict().get("entries", {})
                       if ledger is not None else
                       MemoryLedger.load(train_dir).to_dict()["entries"]),
            "memory_samples": list(samples or []),
            "live_arrays": live_array_census(),
            "devices": device_memory_detail(),
        }
        os.makedirs(train_dir, exist_ok=True)
        path = os.path.join(train_dir, OOM_REPORT_FILE)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1)
        os.replace(tmp, path)
        log.error("RESOURCE_EXHAUSTED: OOM forensics written to %s "
                  "(program %s, %d live-array buckets)", path,
                  program_key, len(report["live_arrays"]["buckets"]))
        return path
    except Exception as e:  # noqa: BLE001 - never mask the real failure
        log.warning("could not write %s: %s", OOM_REPORT_FILE, e)
        return None


def validate_oom_report(report: dict) -> List[str]:
    """Schema check for an oom_report.json payload, shared by the tests
    and ``doctor --mem-probe``. Returns a list of problems (empty =
    valid)."""
    problems: List[str] = []
    if not isinstance(report, dict):
        return ["report is not a JSON object"]
    for key, types in (("format", int), ("written_at", (int, float)),
                       ("context", str), ("error", dict),
                       ("ledger", dict), ("memory_samples", list),
                       ("live_arrays", dict), ("devices", list)):
        if key not in report:
            problems.append(f"missing required key {key!r}")
        elif not isinstance(report[key], types):
            problems.append(f"{key!r} has wrong type "
                            f"{type(report[key]).__name__}")
    err = report.get("error")
    if isinstance(err, dict):
        if not err.get("type") or not err.get("message"):
            problems.append("error must carry type and message")
        elif "RESOURCE_EXHAUSTED" not in err["message"]:
            problems.append("error.message does not mention "
                            "RESOURCE_EXHAUSTED")
    census = report.get("live_arrays")
    if isinstance(census, dict):
        for key in ("buckets", "total_arrays", "total_bytes"):
            if key not in census:
                problems.append(f"live_arrays missing {key!r}")
        for i, b in enumerate(census.get("buckets", [])):
            if not isinstance(b, dict) or not {"shape", "dtype", "count",
                                               "bytes"} <= set(b):
                problems.append(f"live_arrays.buckets[{i}] malformed")
                break
    for i, s in enumerate(report.get("memory_samples", [])):
        if not isinstance(s, dict) or "wall" not in s or "step" not in s:
            problems.append(f"memory_samples[{i}] malformed")
            break
    return problems
