"""Event-span tracer — structured lifecycle events of a run.

The reference's run lifecycle (compiles, checkpoint saves, eval passes)
existed only as interleaved log lines across per-task files (SURVEY.md
§5); reconstructing "what happened when" meant grepping timestamps. The
tracer appends one JSON object per span to ``<dir>/events.jsonl``:

    {"span": "checkpoint_save", "start": <wall>, "end": <wall>,
     "duration_sec": 0.041, "step": 3000, "async": true}

``start``/``end`` are wall-clock (``time.time()``) so spans from
different hosts/processes can be laid on one timeline. Span kinds written
by the framework: ``run`` (whole training loop), ``compile`` (first
dispatch), ``checkpoint_save`` / ``checkpoint_restore``, ``eval_pass``,
``profiler_trace`` (the jax.profiler window). The writer is append-only,
line-buffered, idempotent on double-``close()`` and a no-op after close —
shutdown races (daemon threads, atexit, sidecars) can never turn
telemetry into a crash.
"""

from __future__ import annotations

import json
import logging
import os
import time
from contextlib import contextmanager

log = logging.getLogger("tpu_resnet")


class SpanTracer:
    def __init__(self, directory: str, enabled: bool = True,
                 filename: str = "events.jsonl",
                 run_id: str = None):
        """``run_id`` (obs/manifest.py::ensure_run_id) is stamped on
        every record — the correlation key obs/trace.py uses to lay
        trainer/eval/serve files on one timeline. Mutable: a sidecar
        that starts before the trainer minted the id can set
        ``tracer.run_id`` once discovered."""
        self.enabled = enabled
        self.run_id = run_id
        self._pid = os.getpid()
        self._f = None
        if not enabled:
            return
        os.makedirs(directory, exist_ok=True)
        self._f = open(os.path.join(directory, filename), "a", buffering=1)

    def record(self, kind: str, start: float, end: float, **attrs) -> None:
        """Append one finished span. Safe after ``close()`` (no-op)."""
        if self._f is None:
            return
        rec = {"span": kind, "start": round(start, 6), "end": round(end, 6),
               "duration_sec": round(end - start, 6), "pid": self._pid}
        if self.run_id is not None:
            rec["run_id"] = self.run_id
        rec.update(attrs)
        try:
            self._f.write(json.dumps(rec) + "\n")
        except ValueError:  # closed underneath us in a shutdown race
            self._f = None

    def event(self, kind: str, **attrs) -> None:
        """Instantaneous marker (zero-duration span)."""
        now = time.time()
        self.record(kind, now, now, **attrs)

    @contextmanager
    def span(self, kind: str, **attrs):
        """Time a block as a span. Yields the attrs dict so the body can
        attach results (e.g. ``a["precision"] = p``); an exception is
        recorded on the span and re-raised."""
        t0 = time.time()
        try:
            yield attrs
        except BaseException as e:
            attrs.setdefault("error", f"{type(e).__name__}: {e}"[:200])
            raise
        finally:
            self.record(kind, t0, time.time(), **attrs)

    def close(self) -> None:
        if self._f is not None:
            f, self._f = self._f, None
            try:
                f.close()
            except OSError:  # pragma: no cover - fs-specific
                pass


def load_jsonl(path: str, require_key: str):
    """Torn-tail-tolerant jsonl reader: one dict per parseable line that
    carries ``require_key``; partial trailing lines (live writer, crash
    mid-write) are skipped, not errors. The single tolerance policy shared
    by the span and metrics readers."""
    out = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if require_key in rec:
                out.append(rec)
    return out


def load_spans(path: str):
    """``events.jsonl`` → list of span records."""
    return load_jsonl(path, "span")
