"""Event-span tracer — structured lifecycle events of a run.

The reference's run lifecycle (compiles, checkpoint saves, eval passes)
existed only as interleaved log lines across per-task files (SURVEY.md
§5); reconstructing "what happened when" meant grepping timestamps. The
tracer appends one JSON object per span to ``<dir>/events.jsonl``:

    {"span": "checkpoint_save", "start": <wall>, "end": <wall>,
     "duration_sec": 0.041, "step": 3000, "async": true}

``start``/``end`` are wall-clock (``time.time()``) so spans from
different hosts/processes can be laid on one timeline. Span kinds written
by the framework: ``run`` (whole training loop), ``compile`` (first
dispatch), ``checkpoint_save`` / ``checkpoint_restore``, ``eval_pass``,
``profiler_trace`` (the jax.profiler window). The writer is append-only,
line-buffered, idempotent on double-``close()`` and a no-op after close —
shutdown races (daemon threads, atexit, sidecars) can never turn
telemetry into a crash.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

log = logging.getLogger("tpu_resnet")


class SpanTracer:
    def __init__(self, directory: str, enabled: bool = True,
                 filename: str = "events.jsonl",
                 run_id: str = None):
        """``run_id`` (obs/manifest.py::ensure_run_id) is stamped on
        every record — the correlation key obs/trace.py uses to lay
        trainer/eval/serve files on one timeline. Mutable: a sidecar
        that starts before the trainer minted the id can set
        ``tracer.run_id`` once discovered."""
        self.enabled = enabled
        self.run_id = run_id
        self._pid = os.getpid()
        self._f = None
        if not enabled:
            return
        os.makedirs(directory, exist_ok=True)
        self._f = open(os.path.join(directory, filename), "a", buffering=1)

    def record(self, kind: str, start: float, end: float, **attrs) -> None:
        """Append one finished span. Safe after ``close()`` (no-op)."""
        if self._f is None:
            return
        rec = {"span": kind, "start": round(start, 6), "end": round(end, 6),
               "duration_sec": round(end - start, 6), "pid": self._pid}
        if self.run_id is not None:
            rec["run_id"] = self.run_id
        rec.update(attrs)
        try:
            self._f.write(json.dumps(rec) + "\n")
        except ValueError:  # closed underneath us in a shutdown race
            self._f = None

    def event(self, kind: str, **attrs) -> None:
        """Instantaneous marker (zero-duration span)."""
        now = time.time()
        self.record(kind, now, now, **attrs)

    @contextmanager
    def span(self, kind: str, **attrs):
        """Time a block as a span. Yields the attrs dict so the body can
        attach results (e.g. ``a["precision"] = p``); an exception is
        recorded on the span and re-raised."""
        t0 = time.time()
        try:
            yield attrs
        except BaseException as e:
            attrs.setdefault("error", f"{type(e).__name__}: {e}"[:200])
            raise
        finally:
            self.record(kind, t0, time.time(), **attrs)

    def close(self) -> None:
        if self._f is not None:
            f, self._f = self._f, None
            try:
                f.close()
            except OSError:  # pragma: no cover - fs-specific
                pass


class TailSampler:
    """Tail-based retention decision for per-request tracing spans.

    Recording every request as a span would make the event log grow
    linearly with traffic — useless at fleet rates and a disk hazard on
    a long-lived replica. The sampler keeps exactly the traces an
    operator pulls up after an incident:

    * every error / shed / retried / hedged request (always kept),
    * everything slower than a rolling latency quantile ("the slowest
      percentile" — the p99 excursions the fleet plane exists to
      explain),
    * plus a thinning baseline sample of healthy traffic whose period
      doubles as volume accumulates, so steady-state kept-span volume is
      O(log N) in request count — sublinear by construction (asserted in
      tests/test_fleet.py).

    ``observe()`` returns the keep *reason* (stamped on the span as the
    ``sampled`` attr so readers know why a trace exists) or ``None`` to
    drop. Pure in-memory decision under its own lock; callers write the
    span *outside* any lock, keeping the concurrency engine's
    blocking-under-lock rule clean.
    """

    ALWAYS_KEEP = ("error", "shed", "retry", "hedge")

    def __init__(self, quantile: float = 0.95, base_period: int = 50,
                 ring: int = 512, min_samples: int = 100):
        self.quantile = float(quantile)
        self._lock = threading.Lock()
        self._ring = [0.0] * int(ring)
        self._n = 0                     # total observations
        self._kept_baseline = 0         # baseline keeps since last doubling
        self._period = int(base_period)
        self._since_sample = 0          # observations since last baseline keep
        self._threshold = None          # cached rolling quantile
        self._min_samples = int(min_samples)
        self._kept = 0

    def _slow_threshold(self) -> Optional[float]:
        """Rolling nearest-rank quantile over the latency ring, recomputed
        lazily every ~100 observations (sorting 512 floats per request
        would be hot-path noise)."""
        if self._n < self._min_samples:
            return None
        if self._threshold is None or self._n % 100 == 0:
            vals = sorted(self._ring[:min(self._n, len(self._ring))])
            idx = min(len(vals) - 1,
                      max(0, int(self.quantile * len(vals) + 0.5) - 1))
            self._threshold = vals[idx]
        return self._threshold

    def observe(self, latency_ms: float, error: bool = False,
                shed: bool = False, retried: bool = False,
                hedged: bool = False) -> Optional[str]:
        """Record one request; return the keep reason or None (drop)."""
        with self._lock:
            self._ring[self._n % len(self._ring)] = float(latency_ms)
            self._n += 1
            self._since_sample += 1
            reason = None
            if error:
                reason = "error"
            elif shed:
                reason = "shed"
            elif retried:
                reason = "retry"
            elif hedged:
                reason = "hedge"
            else:
                thr = self._slow_threshold()
                if thr is not None and latency_ms > thr:
                    reason = "slow"
                elif self._since_sample >= self._period:
                    reason = "sampled"
                    self._since_sample = 0
                    self._kept_baseline += 1
                    if self._kept_baseline >= 64:
                        self._kept_baseline = 0
                        self._period *= 2
            if reason is not None:
                self._kept += 1
            return reason

    def stats(self) -> dict:
        with self._lock:
            return {"observed": self._n, "kept": self._kept,
                    "period": self._period,
                    "slow_threshold_ms": self._threshold}


def load_jsonl(path: str, require_key: str):
    """Torn-tail-tolerant jsonl reader: one dict per parseable line that
    carries ``require_key``; partial trailing lines (live writer, crash
    mid-write) are skipped, not errors. The single tolerance policy shared
    by the span and metrics readers."""
    out = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if require_key in rec:
                out.append(rec)
    return out


def load_spans(path: str):
    """``events.jsonl`` → list of span records."""
    return load_jsonl(path, "span")
