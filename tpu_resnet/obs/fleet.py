"""Fleet telemetry aggregator — ``python -m tpu_resnet fleetmon``.

PR 11 turned serving into a fleet (router + N replicas, often colocated
with a trainer), but every /metrics endpoint still had to be scraped and
reasoned about one at a time — and "fleet p99" computed by averaging
per-replica percentiles is simply wrong. ``fleetmon`` is the
control-plane sensor that closes the gap, and the process ROADMAP's
autoscaler will read:

- **discovery**: every endpoint announces itself already —
  ``serve.json`` / ``serve-<name>.json`` (replicas), ``route.json``
  (router), ``telemetry*.json`` (trainer) — so one directory scan per
  round finds the whole fleet, including replicas that restarted on new
  ports.
- **scrape → timeseries**: all ``/metrics`` endpoints scraped each
  ``fleet.scrape_interval_secs``, one JSON line per round appended to
  ``<dir>/fleet_timeseries.jsonl`` (same torn-tail-tolerant jsonl
  contract as every other artifact).
- **exact fleet percentiles**: per-replica ``serve_latency_ms``
  histograms share the PR 6 fixed bucket edges, so
  :func:`~tpu_resnet.obs.server.merge_histograms` pools them bucket-wise
  and ``histogram_quantile`` over the merge IS the quantile of the
  pooled samples — true fleet p50/p95/p99, not average-of-percentiles.
- **SLO burn rate**: requests slower than ``fleet.slo_ms`` spend error
  budget; burn rates over a fast and a slow window (the multiwindow SRE
  shape) gate a ``fleet_burn_alert`` span event — the fast window
  catches the spike, the slow window keeps a blip from paging.
- **snapshot API**: every round also atomically replaces
  ``<dir>/fleet_snapshot.json`` — the latest merged percentiles,
  burn rates, and per-endpoint health (incl. HBM gauges) as ONE
  digest-stamped file. ``read_fleet_snapshot`` is the consumer API the
  autopilot (docs/AUTOPILOT.md) and ``obs_scrape --fleet`` share; the
  append-only timeseries stays the historian.
- **its own /metrics + /healthz**: the FLEET_GAUGES registry on
  ``fleet.port``, announced in ``<dir>/fleetmon.json``.

Pure host code: stdlib only, no jax — the jaxlint host-isolation rule
pins this file, and the concurrency engine covers the scraper thread
(scrapes happen with NO lock held; only the in-memory ring and counters
ride under the lock, and the timeseries file has a single writer).
"""

from __future__ import annotations

import glob
import json
import logging
import math
import os
import threading
import time
from typing import Dict, List, Optional

from tpu_resnet.config import RunConfig
from tpu_resnet.obs.manifest import read_run_id
from tpu_resnet.obs.server import (FLEET_GAUGES, NAMESPACE,
                                   TelemetryRegistry, TelemetryServer,
                                   histogram_quantile, merge_histograms,
                                   scrape)
from tpu_resnet.obs.spans import SpanTracer
from tpu_resnet.obs.trace import FLEET_EVENTS_FILE

log = logging.getLogger("tpu_resnet")

FLEET_DISCOVERY = "fleetmon.json"
FLEET_TIMESERIES_FILE = "fleet_timeseries.jsonl"
# Latest merged round as one atomically-replaced, digest-stamped file —
# the consumer API for control loops (the autopilot) and obs_scrape
# --fleet: read ONE file instead of re-parsing the timeseries stream.
FLEET_SNAPSHOT_FILE = "fleet_snapshot.json"
# Scraped series carry the exposition namespace — the key a /metrics
# consumer must use, distinct from the bare declaration name.
SERVE_LATENCY_SERIES = f"{NAMESPACE}_serve_latency_ms"
HBM_IN_USE_SERIES = f"{NAMESPACE}_hbm_bytes_in_use"
HBM_LIMIT_SERIES = f"{NAMESPACE}_hbm_bytes_limit"


def discover_endpoints(directory: str) -> List[dict]:
    """Every scrapable endpoint announced under ``directory``:
    serve replicas, the router, and trainer telemetry servers. Torn or
    unreadable files are skipped (the scraper re-reads every round);
    duplicate ports (telemetry.json + its hostname-keyed twin) collapse
    to one endpoint; fleetmon's own announcement is excluded."""
    out: List[dict] = []
    seen_ports = set()
    for path in sorted(glob.glob(os.path.join(directory, "*.json"))):
        base = os.path.basename(path)
        if base == "route.json":
            kind, name = "route", "router"
        elif base == "serve.json":
            kind, name = "serve", "default"
        elif base.startswith("serve-") and base.endswith(".json"):
            kind, name = "serve", base[len("serve-"):-len(".json")]
        elif base == "telemetry.json":
            kind, name = "train", "train"
        elif base.startswith("telemetry-") and base.endswith(".json"):
            kind, name = "train", base[len("telemetry-"):-len(".json")]
        else:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
            port = int(rec["port"])
        except (OSError, ValueError, KeyError, TypeError):
            continue
        if port in seen_ports:
            continue
        seen_ports.add(port)
        out.append({"kind": kind, "name": str(rec.get("name") or name),
                    "port": port, "pid": rec.get("pid"),
                    "run_id": rec.get("run_id"),
                    "url": f"http://127.0.0.1:{port}"})
    return out


def cumulative_at(snapshot: dict, x: float) -> float:
    """Interpolated count of observations <= ``x`` in a histogram
    snapshot — the inverse read of :func:`histogram_quantile`, and the
    "requests that met the SLO" numerator of the burn-rate math.
    Overflow-bucket samples are all slower than the largest finite edge,
    so they never count as good."""
    prev_edge, prev_cum = 0.0, 0.0
    for edge, cum in snapshot.get("buckets", []):
        if math.isinf(edge):
            break
        if x <= edge:
            span = edge - prev_edge
            frac = 1.0 if span <= 0 else \
                max(0.0, min(1.0, (x - prev_edge) / span))
            return prev_cum + (float(cum) - prev_cum) * frac
        prev_edge, prev_cum = edge, float(cum)
    return prev_cum


def burn_rate(cur: dict, old: dict, slo_ms: float,
              slo_target: float) -> float:
    """Error-budget burn rate between two merged snapshots: the
    fraction of the window's requests that blew ``slo_ms``, divided by
    the budget fraction ``1 - slo_target``. 1.0 = burning exactly the
    budget; 14 over a fast window is the classic page threshold. 0.0
    when the window saw no requests."""
    d_count = int(cur.get("count", 0)) - int(old.get("count", 0))
    if d_count <= 0:
        return 0.0
    d_good = cumulative_at(cur, slo_ms) - cumulative_at(old, slo_ms)
    bad_frac = min(1.0, max(0.0, 1.0 - d_good / d_count))
    budget = max(1e-9, 1.0 - float(slo_target))
    return bad_frac / budget


class FleetAggregator:
    """Scrape loop + in-memory round ring + burn-rate alerting.

    Threading contract (the concurrency engine covers this file): all
    network I/O and file appends happen on the scraper thread with NO
    lock held; ``self._lock`` guards only the round ring and counters
    that :meth:`snapshot` reads from other threads. The timeseries file
    has exactly one writer (the scraper); ``scrape_once`` must only ever
    be called from one thread at a time (the loop, or a test driving it
    directly before :meth:`start`)."""

    def __init__(self, cfg: RunConfig,
                 registry: Optional[TelemetryRegistry] = None,
                 clock=time.time):
        self.cfg = cfg
        self.directory = cfg.fleet.discover_dir or cfg.train.train_dir
        if not self.directory:
            raise ValueError("fleetmon needs fleet.discover_dir or "
                             "train.train_dir")
        self._clock = clock
        self.registry = registry if registry is not None else \
            TelemetryRegistry(gauges=FLEET_GAUGES)
        self.registry.set("fleet_slo_ms", cfg.fleet.slo_ms)
        self.registry.mark_unhealthy("starting: no scrape round yet")
        self.run_id = read_run_id(self.directory)
        self.spans = SpanTracer(self.directory,
                                filename=FLEET_EVENTS_FILE,
                                run_id=self.run_id)
        os.makedirs(self.directory, exist_ok=True)
        self._ts_f = open(os.path.join(self.directory,
                                       FLEET_TIMESERIES_FILE),
                          "a", buffering=1)
        self._lock = threading.Lock()
        self._rounds: List[dict] = []   # ring of per-round summaries
        self._scrapes = 0
        self._scrape_errors = 0
        self._alerts = 0
        self._alert_active = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="tpu-resnet-fleetmon-scraper",
            daemon=True)

    # ------------------------------------------------------------ scraping
    def scrape_once(self) -> dict:
        """One full round: discover, scrape every endpoint (no lock
        held), merge serve histograms, append the timeseries line,
        update the ring, evaluate the burn alert, publish gauges.
        Returns the round record (the timeseries line's dict)."""
        endpoints = discover_endpoints(self.directory)
        reports: Dict[str, dict] = {}
        errors = 0
        for ep in endpoints:
            try:
                reports[ep["name"]] = scrape(
                    ep["url"], timeout=self.cfg.fleet.scrape_timeout_secs)
            except (OSError, ValueError) as e:
                errors += 1
                reports[ep["name"]] = {"error":
                                       f"{type(e).__name__}: {e}"[:160]}
        serve_hists = [
            r.get("histograms", {}).get(SERVE_LATENCY_SERIES)
            for ep, r in ((e, reports[e["name"]]) for e in endpoints)
            if ep["kind"] == "serve" and "error" not in r]
        try:
            merged = merge_histograms(serve_hists)
        except ValueError as e:
            # Mismatched bucket edges across replicas (a version skew):
            # surface loudly, never fabricate a pooled quantile.
            log.error("fleetmon: histogram merge failed: %s", e)
            self.spans.event("fleet_merge_error", error=str(e)[:200])
            errors += 1
            merged = {"buckets": [], "sum": 0.0, "count": 0}
        quantiles = {q: histogram_quantile(merged, q)
                     for q in (0.50, 0.95, 0.99)}
        now = self._clock()
        record = {
            "wall": round(now, 3),
            "endpoints": len(endpoints),
            "up": len(endpoints) - errors if endpoints else 0,
            "errors": errors,
            "fleet": {"count": merged["count"],
                      "p50_ms": round(quantiles[0.50], 3),
                      "p95_ms": round(quantiles[0.95], 3),
                      "p99_ms": round(quantiles[0.99], 3)},
            "per": {
                name: ({"error": r["error"]} if "error" in r else {
                    "healthy": bool(r.get("health", {}).get("ok")),
                    "serve_p99_ms": round(histogram_quantile(
                        r.get("histograms", {}).get(
                            SERVE_LATENCY_SERIES, {}), 0.99), 3),
                    "requests": int(r.get("histograms", {}).get(
                        SERVE_LATENCY_SERIES, {}).get("count", 0)),
                    # Per-replica HBM, when the endpoint exports it —
                    # the colocation headroom signal the autopilot
                    # snapshot hands to its policy.
                    **({"hbm_bytes_in_use":
                        r["metrics"][HBM_IN_USE_SERIES],
                        "hbm_bytes_limit":
                        r["metrics"].get(HBM_LIMIT_SERIES, 0.0)}
                       if HBM_IN_USE_SERIES in r.get("metrics", {})
                       else {}),
                }) for name, r in sorted(reports.items())},
        }
        fast, slow, fired, cleared, active, scrapes = \
            self._note_round(now, merged)
        record["burn_rate_fast"] = round(fast, 3)
        record["burn_rate_slow"] = round(slow, 3)
        try:
            self._ts_f.write(json.dumps(record) + "\n")
        except ValueError:  # closed in a shutdown race
            pass
        # Snapshot satellite of the timeseries line: same fields plus
        # the round ordinal and alert state, replaced atomically and
        # digest-stamped so a reader can never act on a torn or
        # hand-edited file. Single writer (this scraper thread), I/O
        # with no lock held.
        write_fleet_snapshot(self.directory, {
            **record, "round": scrapes, "alert_active": active,
            "slo_ms": self.cfg.fleet.slo_ms,
            "slo_target": self.cfg.fleet.slo_target})
        if fired:
            self.spans.event(
                "fleet_burn_alert", burn_rate_fast=round(fast, 3),
                burn_rate_slow=round(slow, 3),
                slo_ms=self.cfg.fleet.slo_ms,
                fast_window_secs=self.cfg.fleet.fast_window_secs,
                slow_window_secs=self.cfg.fleet.slow_window_secs,
                fleet_p99_ms=record["fleet"]["p99_ms"])
            log.warning("fleetmon: burn-rate alert — fast %.1fx / slow "
                        "%.1fx over SLO %.0fms", fast, slow,
                        self.cfg.fleet.slo_ms)
        if cleared:
            self.spans.event("fleet_burn_clear",
                             burn_rate_fast=round(fast, 3),
                             burn_rate_slow=round(slow, 3))
            log.info("fleetmon: burn-rate alert cleared")
        self._publish(record)
        return record

    def _note_round(self, now: float, merged: dict):
        """Ring append + burn evaluation + alert transition, all under
        the lock (pure in-memory — the I/O stays outside). Returns
        ``(burn_fast, burn_slow, fired, cleared, active, scrapes)``."""
        cfg = self.cfg.fleet
        with self._lock:
            self._scrapes += 1
            self._rounds.append({"wall": now, "merged": merged})
            ring = max(2, int(cfg.ring))
            if len(self._rounds) > ring:
                del self._rounds[:-ring]
            fast = slow = 0.0
            if cfg.slo_ms > 0:
                fast = burn_rate(merged,
                                 self._window_base(now,
                                                   cfg.fast_window_secs),
                                 cfg.slo_ms, cfg.slo_target)
                slow = burn_rate(merged,
                                 self._window_base(now,
                                                   cfg.slow_window_secs),
                                 cfg.slo_ms, cfg.slo_target)
            hot = (cfg.slo_ms > 0 and fast >= cfg.burn_alert_fast
                   and slow >= cfg.burn_alert_slow)
            fired = hot and not self._alert_active
            cleared = self._alert_active and not hot
            self._alert_active = hot
            if fired:
                self._alerts += 1
            scrapes = self._scrapes
        return fast, slow, fired, cleared, hot, scrapes

    def _window_base(self, now: float, window_secs: float) -> dict:
        """Oldest ring round inside the window (lock held by caller).
        The first round of a young process anchors every window — burn
        is then computed over all available history, which is the
        honest read when the window hasn't filled yet."""
        base = {"buckets": [], "sum": 0.0, "count": 0}
        cutoff = now - window_secs
        for r in self._rounds[:-1]:
            if r["wall"] >= cutoff:
                return r["merged"]
            base = r["merged"]
        return base if self._rounds[:-1] else \
            {"buckets": [], "sum": 0.0, "count": 0}

    def _publish(self, record: dict) -> None:
        with self._lock:
            scrapes, errors = self._scrapes, self._scrape_errors
            alerts, active = self._alerts, self._alert_active
        self.registry.update({
            "fleet_endpoints_total": record["endpoints"],
            "fleet_endpoints_up": record["up"],
            "fleet_scrapes_total": scrapes,
            "fleet_scrape_errors_total": errors,
            "fleet_requests_total": record["fleet"]["count"],
            "fleet_serve_p50_ms": record["fleet"]["p50_ms"],
            "fleet_serve_p95_ms": record["fleet"]["p95_ms"],
            "fleet_serve_p99_ms": record["fleet"]["p99_ms"],
            "fleet_slo_ms": self.cfg.fleet.slo_ms,
            "fleet_burn_rate_fast": record["burn_rate_fast"],
            "fleet_burn_rate_slow": record["burn_rate_slow"],
            "fleet_alerts_total": alerts,
            "fleet_alert_active": 1.0 if active else 0.0,
        })
        self.registry.heartbeat(scrapes)
        self.registry.clear_unhealthy()

    def _loop(self) -> None:
        interval = max(0.05, self.cfg.fleet.scrape_interval_secs)
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 - the sensor must outlive
                log.exception("fleetmon: scrape round failed")
                with self._lock:
                    self._scrape_errors += 1
            self._stop.wait(interval)

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "FleetAggregator":
        self.spans.event("fleet_start", directory=self.directory,
                         scrape_interval_secs=
                         self.cfg.fleet.scrape_interval_secs,
                         slo_ms=self.cfg.fleet.slo_ms)
        self._thread.start()
        return self

    def snapshot(self) -> dict:
        """Newest round summary + counters (thread-safe read)."""
        with self._lock:
            last = dict(self._rounds[-1]) if self._rounds else None
            return {"rounds": len(self._rounds),
                    "scrapes": self._scrapes,
                    "scrape_errors": self._scrape_errors,
                    "alerts": self._alerts,
                    "alert_active": self._alert_active,
                    "last": last}

    def close(self) -> None:
        """Stop and JOIN the scraper (a daemon thread left running at
        interpreter teardown would race the file closes below), then
        close the timeseries and span writers."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)
        try:
            self._ts_f.close()
        except OSError:  # pragma: no cover - fs-specific
            pass
        self.spans.close()


def write_fleet_snapshot(directory: str, payload: dict) -> None:
    """Atomic ``<dir>/fleet_snapshot.json``: the payload plus a sha256
    ``digest`` over its canonical JSON. tmp + ``os.replace`` means a
    reader sees the previous complete snapshot or this one, never a
    torn write — and the digest catches everything replace can't
    (a partial copy, a hand edit)."""
    import hashlib

    body = dict(payload)
    body.pop("digest", None)
    canon = json.dumps(body, sort_keys=True)
    body["digest"] = hashlib.sha256(canon.encode()).hexdigest()
    path = os.path.join(directory, FLEET_SNAPSHOT_FILE)
    tmp = path + f".tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(body, f, indent=2)
        os.replace(tmp, path)
    except OSError as e:  # the sensor must outlive a full disk
        log.warning("fleetmon: snapshot write failed: %s", e)


def read_fleet_snapshot(directory: str) -> Optional[dict]:
    """Digest-verified read of the latest fleet snapshot. None when the
    file is absent, unparseable, or fails its digest — a control loop
    (the autopilot) treats all three the same: no trustworthy fleet
    signal this round."""
    import hashlib

    path = os.path.join(directory, FLEET_SNAPSHOT_FILE)
    try:
        with open(path) as f:
            body = json.load(f)
        digest = body.pop("digest")
    except (OSError, ValueError, KeyError):
        return None
    canon = json.dumps(body, sort_keys=True)
    if hashlib.sha256(canon.encode()).hexdigest() != digest:
        log.warning("fleetmon: snapshot digest mismatch — ignoring %s",
                    path)
        return None
    body["digest"] = digest
    return body


def write_fleet_discovery(directory: str, port: int,
                          run_id: Optional[str] = None) -> None:
    """Atomic ``<dir>/fleetmon.json`` — the route.json analog for the
    aggregator (obs_scrape --fleet and the doctor probe dial from
    here)."""
    from tpu_resnet.serve.discovery import write_record

    write_record(directory, FLEET_DISCOVERY, port,
                 extra={"run_id": run_id, "kind": "fleetmon"})


def read_fleet_port(directory: str) -> Optional[int]:
    from tpu_resnet.serve.discovery import read_port

    return read_port(directory, FLEET_DISCOVERY)


def fleetmon(cfg: RunConfig) -> int:
    """CLI entry: start the aggregator + its telemetry server, announce
    fleetmon.json, block until SIGTERM/SIGINT (flag-only
    ShutdownCoordinator), stop the scraper, exit 0."""
    from tpu_resnet.resilience import ShutdownCoordinator

    directory = cfg.fleet.discover_dir or cfg.train.train_dir
    if not directory:
        log.error("fleetmon: need fleet.discover_dir=<dir with "
                  "serve*.json/route.json> or train.train_dir")
        return 2
    coordinator = ShutdownCoordinator(
        enabled=cfg.resilience.graceful_shutdown,
        action_desc="stopping the fleet scraper and closing the "
                    "timeseries, then exiting 0")
    agg = FleetAggregator(cfg)
    server = None
    with coordinator:
        agg.start()
        if cfg.fleet.port >= 0:
            server = TelemetryServer(agg.registry, cfg.fleet.port,
                                     cfg.fleet.host)
            write_fleet_discovery(directory, server.port,
                                  run_id=agg.run_id)
            log.info("fleetmon: ready on :%d — scraping %s every %.1fs "
                     "(SLO %.0fms; /metrics; /healthz)", server.port,
                     directory, cfg.fleet.scrape_interval_secs,
                     cfg.fleet.slo_ms)
        try:
            while not coordinator.event.wait(0.5):
                pass
            log.info("fleetmon: shutdown requested (%s)",
                     coordinator.signum)
        except KeyboardInterrupt:
            log.warning("fleetmon: immediate abort requested")
        finally:
            if server is not None:
                server.close()
            agg.close()
    log.info("fleetmon: exited cleanly")
    return 0
