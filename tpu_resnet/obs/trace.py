"""Unified run timeline — merge every observability artifact of a
train_dir into ONE Chrome-trace/Perfetto JSON.

Before this module the run's timeline was four disconnected files
(events.jsonl spans, metrics.jsonl breakdown samples, the eval sidecar's
own events, the serve process's events) that could only be correlated by
eyeballing wall-clock numbers. The exporter lays them on one timeline the
way the TPU scaling reports drive their optimization campaigns
(arXiv:2204.06514, arXiv:1909.09756 — profiler timelines, not throughput
logs):

    python -m tpu_resnet trace-export --dir /tmp/run1
    # → /tmp/run1/trace.json ; open in https://ui.perfetto.dev or
    #   chrome://tracing (no upload needed — Perfetto parses locally)

Lanes (Chrome trace "processes"/"threads"):

- **trainer** (pid from its spans): the run/compile/checkpoint/
  nan_rollback/preempt spans from ``events.jsonl``, plus two counter
  threads derived from ``metrics.jsonl`` — the step-time breakdown
  (data_wait_frac, steps_per_sec, mfu, model_flops_per_sec) and the
  data-engine ring (occupancy, decode rate). Logged intervals render as
  ``train_interval`` slices carrying the full breakdown in args.
- **eval sidecar** (``eval/events.jsonl``): eval_pass/restore spans. An
  in-process sidecar (train_and_eval) shares the trainer's pid and shows
  up as another thread of the same process — which is the truth.
- **serve** (``serve_events.jsonl``): warmup, hot-reload, drain spans —
  one lane per replica pid when a fleet shares the train_dir.
- **router** (``route_events.jsonl``): the serving fleet's front router
  (serve/router.py) — replica up/down transitions, drain spans, shed
  events, laid beside the replica lanes they caused.
- **fleetmon** (``fleet_events.jsonl``): the fleet telemetry aggregator
  (obs/fleet.py) — scrape rounds and SLO burn-rate alert events.
- **autopilot** (``autopilot_events.jsonl``): the autoscaling control
  plane (tpu_resnet/autopilot/) — every policy decision, spawn/drain
  actuation, admission denial, and capacity-lease handoff, laid beside
  the router/replica lanes it steered.
- **requests** (synthetic process): per-request distributed-trace lanes
  — one thread per tail-sampled trace id, holding the router's
  ``route_request`` span (per-leg attribution in args) with the
  replica's ``serve_request`` span nested inside it by containment,
  itself broken into ``queue_wait`` / ``infer`` / ``stall`` segments
  from the batcher's timing attrs. The slowest
  :data:`_REQUEST_LANE_CAP` traces render (never a silent cap — the
  drop count lands in ``metadata.request_lanes``), answering "why was
  THIS request slow" hop by hop.
- **device-memory** (counter thread on the trainer lane): the live
  ``hbm_bytes_in_use``/``hbm_bytes_peak``/``hbm_utilization`` gauges the
  loop samples from ``device.memory_stats()`` at log boundaries
  (obs/memory.py) — HBM pressure rendered against the same timeline as
  the compile/checkpoint/step spans that move it.
- **device trace** (``--device-trace``): the ``jax.profiler`` capture of
  a step window (tools/profiling.py StepTracer,
  ``train.profile_steps``) merged in as per-device lanes. The profiler's
  own Chrome-trace export (``profile/plugins/profile/<ts>/*.trace.json
  [.gz]``) uses a timebase relative to its session start; the exporter
  re-anchors it on the wall clock of the trainer's ``profiler_trace``
  span — the host span that wrapped the capture — so XLA device/compile
  activity lands in true host time next to the dispatch spans that
  caused it, closing the host↔device attribution gap. Python-tracer
  events (``$``-prefixed) are dropped: the host-side story already lives
  on the trainer lane as spans.

Correlation key: the ``run_id`` every writer stamps (obs/manifest.py).
The exporter records it in trace metadata and appends it to each lane's
process name, so a screenshotless review can still assert "these lanes
are one session". Mismatched run_ids are kept (they are evidence of a
mixed directory) and reported under ``metadata.source_run_ids``.

Stdlib-only, no jax: exports run on any machine that can read the files.
Output is deterministic — same inputs, byte-identical trace — so
re-exports diff clean and tests can pin stability.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from tpu_resnet.obs.spans import load_jsonl, load_spans

SERVE_EVENTS_FILE = "serve_events.jsonl"
ROUTE_EVENTS_FILE = "route_events.jsonl"
FLEET_EVENTS_FILE = "fleet_events.jsonl"
AUTOPILOT_EVENTS_FILE = "autopilot_events.jsonl"
TRACE_FILE = "trace.json"

# Synthetic lane ids used when a source file predates pid stamping.
_FALLBACK_PID = {"train": 1, "eval": 2, "serve": 3, "route": 4,
                 "fleet": 5, "autopilot": 6}
# Thread ids within a lane (Chrome traces key threads by (pid, tid)).
_TID_SPANS = {"train": 1, "eval": 11, "serve": 21, "route": 31,
              "fleet": 41, "autopilot": 51}
_TID_BREAKDOWN = 2
_TID_ENGINE = 3
# Dedicated transfer lane: h2d_transfer spans (the double-buffered
# staged superbatch copies, data/pipeline.py::DoubleBufferedH2D) render
# on their own thread so the overlap with the train/compile spans above
# is visible at a glance in Perfetto.
_TID_H2D = 4
_H2D_SPAN = "h2d_transfer"
# Device-memory counter thread: the hbm_* gauges obs/memory.py samples
# at log boundaries, rendered as their own lane so HBM pressure lines up
# against the spans (compile, checkpoint, eval) that move it.
_TID_MEMORY = 5
# Merged jax.profiler lanes keep their own pid space well away from the
# host lanes (real host pids are ~1e3-1e6; profiler pids are small ints
# that would collide with the synthetic fallbacks).
_DEVICE_TRACE_PID_BASE = 9000000
_DEVICE_TRACE_EVENT_CAP = 200000
_PROFILER_SPAN = "profiler_trace"
# Per-request distributed-trace lanes: a synthetic process well below
# the device-trace pid space, one thread per tail-sampled trace id.
_REQUEST_PID = 7000000
_REQUEST_LANE_CAP = 100
_REQUEST_SPANS = ("route_request", "serve_request")

# Counter series lifted from metrics.jsonl records onto counter threads:
# (record key, counter thread, counter name).
_COUNTER_KEYS = (
    ("steps_per_sec", _TID_BREAKDOWN, "steps_per_sec"),
    ("data_wait_frac", _TID_BREAKDOWN, "data_wait_frac"),
    ("model_flops_per_sec", _TID_BREAKDOWN, "model_flops_per_sec"),
    ("mfu", _TID_BREAKDOWN, "mfu"),
    ("data_ring_occupancy", _TID_ENGINE, "data_ring_occupancy"),
    ("data_decode_images_per_sec", _TID_ENGINE,
     "data_decode_images_per_sec"),
    ("h2d_bytes_per_sec", _TID_H2D, "h2d_bytes_per_sec"),
    ("h2d_overlap_frac", _TID_H2D, "h2d_overlap_frac"),
    ("hbm_bytes_in_use", _TID_MEMORY, "hbm_bytes_in_use"),
    ("hbm_bytes_peak", _TID_MEMORY, "hbm_bytes_peak"),
    ("hbm_utilization", _TID_MEMORY, "hbm_utilization"),
)

_INTERVAL_ARG_KEYS = (
    "loss", "precision", "learning_rate", "steps_per_sec",
    "images_per_sec", "data_wait_sec", "data_wait_frac", "dispatch_sec",
    "device_sync_sec", "device_step_sec_sampled", "compile_seconds",
    "model_flops_per_sec", "mfu", "train_step_ms_p50", "train_step_ms_p95",
    "train_step_ms_p99", "data_ring_occupancy",
    "data_decode_images_per_sec", "h2d_bytes_per_sec",
    "h2d_overlap_frac", "hbm_bytes_in_use", "hbm_utilization",
)


def _us(wall: float, base: float) -> float:
    """Wall-clock seconds → trace microseconds relative to ``base``,
    rounded so float formatting is stable across platforms."""
    return round((wall - base) * 1e6, 1)


def _span_events(spans: List[dict], source: str, base: float,
                 pid_of: Dict[str, int]) -> List[dict]:
    events = []
    default_pid = pid_of[source]
    for s in spans:
        try:
            start, end = float(s["start"]), float(s["end"])
        except (KeyError, TypeError, ValueError):
            continue
        if end < start:
            continue
        name = str(s.get("span", "span"))
        tid = (_TID_H2D if source == "train" and name == _H2D_SPAN
               else _TID_SPANS[source])
        # Fleet sources (serve replicas sharing one serve_events.jsonl,
        # the router): each writer pid keeps its OWN lane so a rolling
        # drain renders as N replica lanes + a router lane, not one
        # merged smear. Train/eval keep the single-lane behavior (their
        # multi-pid case is supervised restarts of the same logical
        # process, reviewed as one lane on purpose).
        pid = (s["pid"] if source in ("serve", "route")
               and isinstance(s.get("pid"), int) else default_pid)
        args = {k: v for k, v in s.items()
                if k not in ("span", "start", "end", "pid")}
        common = {"name": name, "cat": source,
                  "pid": pid, "tid": tid, "ts": _us(start, base),
                  "args": args}
        if end == start:
            events.append({**common, "ph": "i", "s": "t"})
        else:
            events.append({**common, "ph": "X",
                           "dur": round((end - start) * 1e6, 1)})
    return events


def _metrics_events(records: List[dict], base: float, pid: int
                    ) -> List[dict]:
    """metrics.jsonl → counter samples + per-interval slices on the
    trainer lane."""
    events = []
    prev = None
    for rec in sorted(records, key=lambda r: r.get("wall", 0.0)):
        wall = rec.get("wall")
        if wall is None:
            continue
        ts = _us(wall, base)
        for key, tid, name in _COUNTER_KEYS:
            if key in rec:
                events.append({"name": name, "ph": "C", "pid": pid,
                               "tid": tid, "ts": ts,
                               "args": {"value": rec[key]}})
        if prev is not None and "data_wait_sec" in rec:
            args = {k: rec[k] for k in _INTERVAL_ARG_KEYS if k in rec}
            args["step"] = rec.get("step")
            events.append({
                "name": f"train_interval@{rec.get('step')}",
                "cat": "train", "ph": "X", "pid": pid,
                "tid": _TID_BREAKDOWN, "ts": _us(prev, base),
                "dur": round((wall - prev) * 1e6, 1), "args": args})
        prev = wall
    return events


def _serve_segments(s: dict, start: float, end: float, tid: int,
                    base: float) -> List[dict]:
    """Break one ``serve_request`` span into nested timing segments from
    the batcher-stamped attrs: ``queue_wait`` (enqueue → batch formed),
    ``infer`` (batch dispatch → logits), and ``stall`` — the unaccounted
    remainder (hot-reload stalls, HTTP/parse overhead). Segments are
    clamped inside the parent span so containment nesting holds."""
    segs: List[dict] = []
    cursor = start

    def push(name: str, dur_ms) -> None:
        nonlocal cursor
        if not isinstance(dur_ms, (int, float)) or dur_ms <= 0:
            return
        seg_end = min(end, cursor + float(dur_ms) / 1e3)
        if seg_end <= cursor:
            return
        segs.append({"name": name, "cat": "request", "ph": "X",
                     "pid": _REQUEST_PID, "tid": tid,
                     "ts": _us(cursor, base),
                     "dur": round((seg_end - cursor) * 1e6, 1),
                     "args": {}})
        cursor = seg_end

    push("queue_wait", s.get("queue_wait_ms"))
    push("infer", s.get("infer_ms"))
    push("stall", (end - cursor) * 1e3)
    return segs


def _request_lane_events(sources: Dict[str, List[dict]], base: float
                         ) -> Tuple[List[dict], Optional[dict]]:
    """Per-request lanes from the tail-sampled route_request /
    serve_request spans: group by trace id, render the slowest
    :data:`_REQUEST_LANE_CAP` traces one thread each (router span with
    the replica span nested inside by containment), report any drop in
    the returned info dict (never a silent cap)."""
    traced: Dict[str, List[dict]] = {}
    for src in ("route", "serve"):
        for s in sources.get(src, []):
            if s.get("span") not in _REQUEST_SPANS or not s.get("trace_id"):
                continue
            try:
                float(s["start"]), float(s["end"])
            except (KeyError, TypeError, ValueError):
                continue
            traced.setdefault(str(s["trace_id"]), []).append(s)
    if not traced:
        return [], None

    def cost(key: str) -> float:
        return max(float(s.get("duration_sec") or 0.0)
                   for s in traced[key])

    order = sorted(traced, key=lambda k: (-cost(k), k))
    keep = order[:_REQUEST_LANE_CAP]
    events = [_meta("process_name", _REQUEST_PID,
                    label="requests (tail-sampled)")]
    for tid, key in enumerate(keep, start=1):
        events.append(_meta("thread_name", _REQUEST_PID, tid,
                            f"req {key}"))
        for s in sorted(traced[key],
                        key=lambda s: (float(s["start"]),
                                       str(s.get("span")))):
            start, end = float(s["start"]), float(s["end"])
            if end < start:
                continue
            args = {k: v for k, v in s.items()
                    if k not in ("span", "start", "end", "pid")}
            events.append({"name": str(s["span"]), "cat": "request",
                           "ph": "X", "pid": _REQUEST_PID, "tid": tid,
                           "ts": _us(start, base),
                           "dur": round((end - start) * 1e6, 1),
                           "args": args})
            if s.get("span") == "serve_request":
                events.extend(_serve_segments(s, start, end, tid, base))
    info = {"traces": len(traced), "rendered": len(keep),
            "dropped": len(traced) - len(keep)}
    return events, info


def _meta(name: str, pid: int, tid: Optional[int] = None,
          label: str = "") -> dict:
    ev = {"name": name, "ph": "M", "pid": pid, "ts": 0.0,
          "args": {"name": label}}
    if tid is not None:
        ev["tid"] = tid
    return ev


def _source_pid(spans: List[dict], source: str) -> int:
    for s in spans:
        pid = s.get("pid")
        if isinstance(pid, int):
            return pid
    return _FALLBACK_PID[source]


def _run_ids(spans: List[dict]) -> List[str]:
    return sorted({str(s["run_id"]) for s in spans if s.get("run_id")})


def find_device_trace_files(train_dir: str) -> List[str]:
    """Chrome-trace exports of the NEWEST ``jax.profiler`` capture under
    ``<train_dir>/profile`` (tools/profiling.py StepTracer layout:
    ``profile/plugins/profile/<timestamp>/<host>.trace.json[.gz]``).
    Capture dirs are named by timestamp, so lexical order is capture
    order; files within a capture sort by name (one per host)."""
    root = os.path.join(train_dir, "profile", "plugins", "profile")
    try:
        captures = sorted(d for d in os.listdir(root)
                          if os.path.isdir(os.path.join(root, d)))
    except OSError:
        return []
    for cap in reversed(captures):
        files = sorted(
            os.path.join(root, cap, f)
            for f in os.listdir(os.path.join(root, cap))
            if f.endswith(".trace.json") or f.endswith(".trace.json.gz"))
        if files:
            return files
    return []


def _load_profiler_json(path: str) -> dict:
    import gzip

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def _device_trace_events(train_dir: str, train_spans: List[dict],
                         base: float) -> Tuple[List[dict], dict]:
    """Merge the newest profiler capture as per-device lanes. Returns
    ``(events, info)`` where ``info`` lands in trace metadata.

    Timebase: profiler ``ts`` is microseconds since its session start.
    The trainer's ``profiler_trace`` span wraps exactly that session
    (StepTracer records it start_trace→stop_trace), so its wall-clock
    ``start`` re-anchors the capture; without the span (a capture taken
    out-of-band) the file's mtime end-anchors it — stable for fixed
    inputs, so exports stay deterministic either way."""
    files = find_device_trace_files(train_dir)
    if not files:
        raise FileNotFoundError(
            f"--device-trace: no profiler capture under "
            f"{os.path.join(train_dir, 'profile')} — capture one with "
            f"train.profile_steps='A:B' (tools/profiling.py)")
    anchor = None
    for s in train_spans:  # newest capture ↔ newest profiler span
        if s.get("span") == _PROFILER_SPAN and s.get("start") is not None:
            anchor = float(s["start"])
    events: List[dict] = []
    pid_map: Dict[int, int] = {}
    dropped = python_tracer = 0
    max_ts = 0.0
    for path in files:
        try:
            payload = _load_profiler_json(path)
        except (OSError, ValueError) as e:
            raise ValueError(f"--device-trace: unreadable profiler "
                             f"export {path}: {e}")
        for ev in payload.get("traceEvents", []):
            if not isinstance(ev, dict):
                continue
            ph = ev.get("ph")
            name = str(ev.get("name", ""))
            if ph == "M":
                if ev.get("name") not in ("process_name", "thread_name",
                                          "process_sort_index",
                                          "thread_sort_index"):
                    dropped += 1
                    continue
            elif ph in ("X", "C", "i"):
                if name.startswith("$"):
                    # Python-tracer events: the host-side story is
                    # already on the trainer lane as spans.
                    python_tracer += 1
                    continue
                ts = ev.get("ts")
                if not isinstance(ts, (int, float)):
                    dropped += 1
                    continue
                max_ts = max(max_ts, float(ts))
            else:
                dropped += 1
                continue
            events.append(ev)
    if anchor is None:
        # End-anchor on the newest file's mtime: mtime is stop_trace's
        # write, so capture start ≈ mtime - duration.
        anchor = max(os.path.getmtime(p) for p in files) - max_ts / 1e6
    offset = _us(anchor, base)
    out: List[dict] = []
    for ev in events:
        pid = ev.get("pid")
        pid = pid if isinstance(pid, int) else -1
        if pid not in pid_map:
            pid_map[pid] = _DEVICE_TRACE_PID_BASE + len(pid_map)
        ph = ev.get("ph")
        mapped = {"name": str(ev.get("name", "")), "ph": ph,
                  "pid": pid_map[pid]}
        if "tid" in ev:
            mapped["tid"] = ev["tid"]
        if ph == "M":
            mapped["ts"] = 0.0
            mapped["args"] = dict(ev.get("args") or {})
            if ev.get("name") == "process_name":
                label = str((ev.get("args") or {}).get("name", "?"))
                mapped["args"]["name"] = f"device-trace: {label}"
        else:
            mapped["ts"] = max(0.0, round(offset + float(ev["ts"]), 1))
            mapped["cat"] = "device"
            if ph == "X":
                try:
                    dur = max(0.0, float(ev.get("dur", 0.0)))
                except (TypeError, ValueError):
                    dur = 0.0
                mapped["dur"] = round(dur, 1)
            if ph == "i":
                mapped["s"] = "t"
            if ev.get("args"):
                mapped["args"] = ev["args"]
        out.append(mapped)
    slices = [e for e in out if e["ph"] != "M"]
    if len(slices) > _DEVICE_TRACE_EVENT_CAP:
        # Never a silent cap: keep the earliest slices (the window start
        # is where dispatch↔device attribution is read) and report the
        # drop in metadata.
        slices.sort(key=lambda e: e["ts"])
        dropped += len(slices) - _DEVICE_TRACE_EVENT_CAP
        keep = set(map(id, slices[:_DEVICE_TRACE_EVENT_CAP]))
        out = [e for e in out if e["ph"] == "M" or id(e) in keep]
    info = {"files": [os.path.relpath(p, train_dir) for p in files],
            "anchor_unix": round(anchor, 6),
            "anchored_by": ("profiler_trace_span" if any(
                s.get("span") == _PROFILER_SPAN for s in train_spans)
                else "file_mtime"),
            "events": sum(1 for e in out if e["ph"] != "M"),
            "python_tracer_events_dropped": python_tracer,
            "events_dropped": dropped}
    return out, info


def build_trace(train_dir: str, device_trace: bool = False) -> dict:
    """Assemble the merged Chrome-trace dict (pure read; no writes)."""
    sources: Dict[str, List[dict]] = {
        "train": load_spans(os.path.join(train_dir, "events.jsonl")),
        "eval": load_spans(os.path.join(train_dir, "eval",
                                        "events.jsonl")),
        "serve": load_spans(os.path.join(train_dir, SERVE_EVENTS_FILE)),
        "route": load_spans(os.path.join(train_dir, ROUTE_EVENTS_FILE)),
        "fleet": load_spans(os.path.join(train_dir, FLEET_EVENTS_FILE)),
        "autopilot": load_spans(os.path.join(train_dir,
                                             AUTOPILOT_EVENTS_FILE)),
    }
    metrics = load_jsonl(os.path.join(train_dir, "metrics.jsonl"), "step")

    manifest_run_id = None
    try:
        with open(os.path.join(train_dir, "manifest.json")) as f:
            manifest_run_id = json.load(f).get("run_id")
    except (OSError, ValueError):
        pass
    if manifest_run_id is None:
        try:
            with open(os.path.join(train_dir, "run_id.json")) as f:
                manifest_run_id = json.load(f).get("run_id")
        except (OSError, ValueError):
            pass

    walls = [float(s[k]) for spans in sources.values() for s in spans
             for k in ("start", "end") if isinstance(s.get(k), (int, float))]
    walls += [float(r["wall"]) for r in metrics
              if isinstance(r.get("wall"), (int, float))]
    if not walls:
        raise FileNotFoundError(
            f"no observability artifacts under {train_dir} — need "
            "events.jsonl and/or metrics.jsonl (train with "
            "train.telemetry-enabled defaults)")
    base = min(walls)

    pid_of = {src: _source_pid(spans, src)
              for src, spans in sources.items()}
    # Distinct sources that fell back to the same synthetic pid must not
    # merge lanes; the real-pid collision (in-process eval sidecar) is a
    # true shared process and keeps one lane on purpose.
    events: List[dict] = []
    source_run_ids = {src: _run_ids(spans)
                      for src, spans in sources.items() if spans}
    run_id = manifest_run_id or next(
        (ids[0] for ids in source_run_ids.values() if ids), None)

    labels = {"train": "trainer", "eval": "eval-sidecar",
              "serve": "serve", "route": "router", "fleet": "fleetmon",
              "autopilot": "autopilot"}
    for src, spans in sources.items():
        if not spans and not (src == "train" and metrics):
            continue
        pid = pid_of[src]
        rid = (source_run_ids.get(src) or [run_id or ""])[0]
        suffix = f" run={rid}" if rid else ""
        if src in ("serve", "route"):
            # One lane per writer pid (replica): labels carry the pid
            # when more than one replica appended to the shared file.
            pids = sorted({s["pid"] for s in spans
                           if isinstance(s.get("pid"), int)}) or [pid]
            for p in pids:
                label = (labels[src] if len(pids) == 1
                         else f"{labels[src]}[{p}]")
                events.append(_meta("process_name", p,
                                    label=f"{label}{suffix}"))
                events.append(_meta("thread_name", p, _TID_SPANS[src],
                                    f"{labels[src]}-spans"))
        else:
            events.append(_meta("process_name", pid,
                                label=f"{labels[src]}{suffix}"))
            events.append(_meta("thread_name", pid, _TID_SPANS[src],
                                f"{labels[src]}-spans"))
        if src == "train" and any(s.get("span") == _H2D_SPAN
                                  for s in spans):
            events.append(_meta("thread_name", pid, _TID_H2D,
                                "h2d-transfer"))
        events.extend(_span_events(spans, src, base, pid_of))
    if metrics:
        pid = pid_of["train"]
        events.append(_meta("thread_name", pid, _TID_BREAKDOWN,
                            "step-breakdown"))
        if any("data_ring_occupancy" in r for r in metrics):
            events.append(_meta("thread_name", pid, _TID_ENGINE,
                                "data-engine"))
        if any("hbm_bytes_in_use" in r for r in metrics):
            events.append(_meta("thread_name", pid, _TID_MEMORY,
                                "device-memory"))
        events.extend(_metrics_events(metrics, base, pid))

    req_events, request_info = _request_lane_events(sources, base)
    events.extend(req_events)

    device_trace_info = None
    if device_trace:
        dev_events, device_trace_info = _device_trace_events(
            train_dir, sources["train"], base)
        events.extend(dev_events)

    events.sort(key=lambda e: (e["ts"], e["pid"], e.get("tid", 0),
                               e["ph"], e["name"]))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "tool": "tpu_resnet trace-export",
            "train_dir": os.path.abspath(train_dir),
            "run_id": run_id,
            "source_run_ids": source_run_ids,
            "base_time_unix": base,
            **({"request_lanes": request_info} if request_info else {}),
            **({"device_trace": device_trace_info}
               if device_trace_info else {}),
        },
    }


def validate_trace(trace: dict) -> List[str]:
    """Chrome-trace schema check shared by the tests and
    ``doctor --trace-probe``. Returns a list of problems (empty = valid):
    required top-level keys, per-event required fields, known phases,
    non-negative monotonically ordered ``ts``, non-negative ``dur``."""
    problems: List[str] = []
    if not isinstance(trace, dict):
        return ["trace is not a JSON object"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    if not events:
        problems.append("traceEvents is empty")
    known_ph = {"X", "i", "C", "M", "B", "E"}
    last_ts = None
    for i, ev in enumerate(events):
        where = f"event[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        for key in ("name", "ph", "pid", "ts"):
            if key not in ev:
                problems.append(f"{where}: missing required key {key!r}")
        ph = ev.get("ph")
        if ph not in known_ph:
            problems.append(f"{where}: unknown phase {ph!r}")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"{where}: ts must be a non-negative number, "
                            f"got {ts!r}")
        elif last_ts is not None and ts < last_ts:
            problems.append(f"{where}: ts {ts} < previous {last_ts} — "
                            "events must be sorted")
        else:
            last_ts = ts
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: X event needs dur >= 0, "
                                f"got {dur!r}")
        if len(problems) > 50:
            problems.append("... (truncated)")
            break
    return problems


def export_trace(train_dir: str, out: Optional[str] = None,
                 device_trace: bool = False) -> Tuple[str, dict]:
    """Build + write the merged trace. Deterministic output (atomic
    tmp+rename, sorted keys) so a re-export over unchanged inputs is
    byte-identical. Returns ``(path, trace)``."""
    trace = build_trace(train_dir, device_trace=device_trace)
    problems = validate_trace(trace)
    if problems:  # exporting an invalid trace would hide the bug
        raise ValueError("trace-export produced an invalid trace: "
                         + "; ".join(problems[:5]))
    out = out or os.path.join(train_dir, TRACE_FILE)
    os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
    tmp = out + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(trace, f, indent=None, sort_keys=True,
                  separators=(",", ":"))
        f.write("\n")
    os.replace(tmp, out)
    return out, trace


def main(argv=None) -> int:
    """CLI: ``python -m tpu_resnet trace-export --dir D [--out F]``."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="trace-export",
        description="merge a run's events/metrics/eval/serve artifacts "
                    "into one Chrome-trace JSON (open in ui.perfetto.dev)")
    ap.add_argument("--dir", required=True, help="train dir of the run")
    ap.add_argument("--out", default="",
                    help="output path (default <dir>/trace.json)")
    ap.add_argument("--device-trace", action="store_true",
                    help="also merge the newest jax.profiler capture "
                         "(<dir>/profile, train.profile_steps) as "
                         "per-device lanes re-anchored on the trainer's "
                         "profiler_trace span")
    args = ap.parse_args(argv)
    try:
        path, trace = export_trace(args.dir, out=args.out or None,
                                   device_trace=args.device_trace)
    except (OSError, ValueError) as e:
        print(f"trace-export failed: {e}")
        return 1
    n = len(trace["traceEvents"])
    meta = trace["metadata"]
    print(f"wrote {path} ({n} events, run_id={meta['run_id']})")
    if meta.get("device_trace"):
        dt = meta["device_trace"]
        print(f"device-trace: {dt['events']} events from "
              f"{len(dt['files'])} file(s), anchored by "
              f"{dt['anchored_by']}")
    return 0
