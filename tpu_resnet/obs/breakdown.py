"""Step-time breakdown — where wall time goes between log boundaries.

The reference could only *infer* step timing from LoggingTensorHook
timestamps (reference resnet_cifar_train.py:282-287); whether a run was
input-bound, dispatch-bound or device-bound was guesswork. The tracker
decomposes every logged interval into the three host-observable places
time is spent:

``data_wait``      blocked in ``next(data_iter)`` — the input edge can't
                   keep up (the reference bounded this with 16 queue
                   threads and never measured it, cifar_input.py:99-100).
``dispatch``       enqueueing the jitted chunk (host→device command path;
                   dominated by tracing only on the first call).
``device_sync``    a *sampled* block at the interval boundary: time the
                   host waits for the device to drain the chunks it
                   dispatched. With async dispatch this is the device-
                   compute backlog — ≈0 when the host is the bottleneck,
                   ≈ device step time × interval steps when the device is.

Sampling happens only at the loop's existing log/summary boundaries (the
chunk clipper already ends a fused dispatch exactly there), so the
breakdown never changes fusion behavior. The first dispatch — which pays
XLA tracing + compilation — is reported separately as ``compile_seconds``
and excluded from the first interval so throughput numbers are never
polluted by compile time.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Optional


class StepBreakdown:
    """Accumulates per-interval timings; ``interval()`` drains them as a
    metrics dict merged into the run's ``metrics.jsonl`` records."""

    def __init__(self):
        self.compile_seconds: Optional[float] = None
        self._data_wait = 0.0
        self._dispatch = 0.0
        self._sync: Optional[float] = None       # last boundary sample
        self._sync_steps = 0
        self._interval_start = time.perf_counter()

    # ------------------------------------------------------------ timers
    @contextmanager
    def data_wait(self):
        """Time a blocking ``next(data_iter)``."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._data_wait += time.perf_counter() - t0

    @contextmanager
    def dispatch(self):
        """Time the (normally async) dispatch of a chunk."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._dispatch += time.perf_counter() - t0

    def first_dispatch_done(self, sync) -> float:
        """Call right after the first dispatch of the run returns: blocks
        until the chunk is ready and records ``compile_seconds`` — the
        first-dispatch wall time (jit trace + XLA compile + the first
        chunk's device run). Resets the interval clock so the first logged
        interval excludes compile entirely (the throughput meter is
        re-primed at the same point)."""
        import jax

        jax.block_until_ready(sync)
        # Everything since construction minus time blocked on input: the
        # dispatch call (trace + compile) plus the first chunk's device run.
        self.compile_seconds = (time.perf_counter() - self._interval_start
                                - self._data_wait)
        self.reset_interval()
        return self.compile_seconds

    def add_device_sample(self, seconds: float, steps: int) -> None:
        """Record an externally-timed boundary sync (bench harness path)."""
        self._sync = seconds
        self._sync_steps = max(1, steps)

    def sample_device(self, sync, steps: int) -> float:
        """Block on the newest chunk's result at an interval boundary and
        record the wait — the sampled device-compute backlog. ``steps`` is
        the number of steps dispatched since the last full sync."""
        import jax

        t0 = time.perf_counter()
        jax.block_until_ready(sync)
        dt = time.perf_counter() - t0
        self.add_device_sample(dt, steps)
        return dt

    # ---------------------------------------------------------- reporting
    def reset_interval(self) -> None:
        self._data_wait = 0.0
        self._dispatch = 0.0
        self._sync = None
        self._sync_steps = 0
        self._interval_start = time.perf_counter()

    def interval(self) -> Dict[str, float]:
        """Drain the interval accumulators into a metrics dict.

        Always contains ``data_wait_sec``/``data_wait_frac``/
        ``dispatch_sec``; ``device_sync_sec``/``device_step_sec_sampled``
        when a boundary sample was taken; ``compile_seconds`` (a run
        constant — the first-dispatch wall time) once it is known."""
        wall = max(time.perf_counter() - self._interval_start, 1e-9)
        out = {
            "data_wait_sec": round(self._data_wait, 6),
            "data_wait_frac": round(min(self._data_wait / wall, 1.0), 6),
            "dispatch_sec": round(self._dispatch, 6),
        }
        if self._sync is not None:
            out["device_sync_sec"] = round(self._sync, 6)
            out["device_step_sec_sampled"] = round(
                self._sync / self._sync_steps, 6)
        if self.compile_seconds is not None:
            out["compile_seconds"] = round(self.compile_seconds, 4)
        self.reset_interval()
        return out
