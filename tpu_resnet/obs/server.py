"""Per-host HTTP telemetry — ``/metrics`` (Prometheus text) + ``/healthz``.

Multi-host runs of the reference could only be health-checked by tailing
per-task log files on each node (SURVEY.md §5); a straggling or wedged
worker was found by hand. Every training process can instead serve two
stdlib-only endpoints:

``GET /healthz``   JSON liveness: last heartbeat step, heartbeat age in
                   seconds, ``ok`` (age under the staleness threshold).
                   HTTP 200 when ok, 503 when stale — load balancers and
                   ``kubectl``-style probes need no body parsing.
``GET /metrics``   Prometheus text exposition (version 0.0.4) of the
                   newest training gauges — step, loss, precision, lr,
                   steps/sec, images/sec(/chip), data-wait fraction,
                   compile seconds, checkpoint lag, heartbeat age — so a
                   pod can be scraped and stragglers spotted by a stock
                   Prometheus/Grafana stack without log-grepping.

No third-party dependency: ``http.server`` + a thread. The bound port is
written to ``<train_dir>/telemetry.json`` (port 0 binds an OS-assigned
ephemeral port) so scrapers (tools/obs_scrape.py, the doctor check) can
discover it. This module imports no jax — stdlib-only consumers can use
``parse_prometheus``/``read_telemetry_port`` without a backend.
"""

from __future__ import annotations

import bisect
import json
import logging
import math
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

log = logging.getLogger("tpu_resnet")

NAMESPACE = "tpu_resnet"

# Gauges pre-declared at registry creation so every scrape — including one
# taken during the first compile, before any log interval completed — sees
# the full series set (Prometheus convention: series exist from process
# start).
CORE_GAUGES = (
    ("step", "Current training step (host counter)"),
    ("loss", "Training loss at the last log interval"),
    ("precision", "Training top-1 precision at the last log interval"),
    ("learning_rate", "Learning rate at the last log interval"),
    ("steps_per_sec", "Training steps per second over the last interval"),
    ("images_per_sec", "Global images per second over the last interval"),
    ("images_per_sec_per_chip", "Per-chip images per second"),
    ("data_wait_frac", "Fraction of interval wall time blocked on input"),
    # Host data engine (tpu_resnet/data/engine.py) — the cause signal
    # behind data_wait: occupancy 0 while waiting = producer-bound host.
    ("data_ring_occupancy", "Decoded batches waiting in the engine ring"),
    ("data_ring_slots", "Total engine ring slots"),
    ("data_decode_images_per_sec",
     "Host decode throughput over the last interval"),
    # Double-buffered H2D prefetch (data/pipeline.py::DoubleBufferedH2D):
    # the staged superbatch transfer rate and how much of it hid under
    # compute. overlap ~0 with data_wait high = link-bound; ~1 = the
    # transfer is free (docs/PERF.md tuning playbook).
    ("h2d_bytes_per_sec",
     "Host->device staged transfer rate over the last interval"),
    ("h2d_overlap_frac",
     "Fraction of H2D transfer wall time overlapped with dispatch "
     "(0..1)"),
    ("compile_seconds", "First-dispatch wall time (trace+compile+run)"),
    ("checkpoint_lag_steps", "Steps since the last checkpoint save"),
    # MFU accounting (tpu_resnet/obs/mfu.py): achieved model FLOP/s and
    # utilization vs the chip peak — the numbers the MFU campaign's
    # per-knob wins must show up in (ROADMAP item 3). 0 until the first
    # log boundary; mfu stays 0 on chips the peak table doesn't know.
    ("model_flops_per_sec", "Achieved model FLOP/s over the last "
                            "interval (global, all chips)"),
    ("mfu", "Model FLOPs utilization vs aggregate peak (0..1)"),
    # Live device memory (tpu_resnet/obs/memory.py): device.memory_stats()
    # sampled at log boundaries — zero device syncs. On backends without
    # stats (CPU) the series stay at these explicit zeros
    # (degrade-to-absent for the values, never for the series).
    ("hbm_bytes_in_use", "Device memory in use, max across this host's "
                         "devices (0 where memory_stats is unsupported)"),
    ("hbm_bytes_peak", "Peak device memory since process start, max "
                       "across this host's devices"),
    ("hbm_bytes_limit", "Per-device memory capacity (backend-reported, "
                        "else the obs/memory HBM table)"),
    ("hbm_utilization", "hbm_bytes_in_use / hbm_bytes_limit (0..1)"),
    # Comms accounting (tpu_resnet/obs/comms.py): predicted fraction of
    # step time spent on the wire (ring-model bytes over the per-chip
    # ICI bandwidth vs peak-compute time). Set once at first dispatch;
    # stays 0 on chips the ICI table doesn't know (CPU).
    ("predicted_comms_fraction",
     "Predicted time-on-wire / (time-on-wire + peak-compute time) for "
     "the compiled step (0..1; 0 where the ICI bandwidth is unknown)"),
    # Fault counters (tpu_resnet/resilience) — pre-declared so a scrape on
    # a healthy run reports explicit zeros, not absent series.
    ("fault_nan_rollbacks", "NaN/divergence rollbacks performed"),
    ("fault_watchdog_stalls", "Hang-watchdog stall detections"),
    ("fault_preemptions", "Graceful preemption stops (SIGTERM/SIGINT)"),
    ("fault_preempt_burst", "Injected preemption-burst SIGTERMs fired "
                            "so far across supervised restarts "
                            "(resilience/faultinject.py drill)"),
    # Elastic capacity (tpu_resnet/resilience/elastic.py): 1 when this
    # (re)start's mesh/partition differs from the recorded topology —
    # the gauge twin of the topology_change span/manifest entry.
    ("topology_changes", "This restart resumed across a mesh/partition "
                         "reshape (resilience/elastic.py)"),
    # Program registry (tpu_resnet/programs): persistent AOT executable
    # cache traffic. hits > 0 on a resume/restart means cold-start
    # compiles were actually skipped; misses on a supposedly-warm
    # restart are the cache-regression signal doctor --coldstart-probe
    # gates on.
    ("compile_cache_hits", "Compiled programs loaded from the "
                           "persistent AOT executable cache"),
    ("compile_cache_misses", "Programs compiled because the cache had "
                             "no trustworthy entry (cold, stale, "
                             "evicted, or disabled)"),
)

# Serving-process gauge set (tpu_resnet/serve; docs/SERVING.md). The
# predict server reuses this registry/HTTP stack on its own port —
# /healthz doubles as the readiness probe (unhealthy until the model is
# loaded and every bucket shape is compiled; 503 again while draining).
SERVE_GAUGES = (
    ("serve_requests_total", "Predict requests admitted"),
    ("serve_requests_rejected", "Requests rejected by admission control "
                                "(bounded queue full -> HTTP 429)"),
    ("serve_requests_failed", "Requests that failed during inference"),
    ("serve_images_total", "Images admitted across all requests"),
    ("serve_batches_total", "Coalesced batches dispatched to the model"),
    ("serve_queue_depth", "Requests currently queued for batching"),
    ("serve_batch_size_last", "Images in the most recent batch"),
    ("serve_batch_size_mean", "Mean images per batch since start"),
    ("serve_pad_fraction", "Padded fraction of all bucket slots "
                           "dispatched (compile-avoidance cost)"),
    ("serve_latency_p50_ms", "p50 request latency over the recent ring"),
    ("serve_latency_p95_ms", "p95 request latency over the recent ring"),
    ("serve_latency_p99_ms", "p99 request latency over the recent ring"),
    ("serve_model_step", "Checkpoint step being served (-1 = frozen "
                         "export bundle)"),
    ("serve_reloads_total", "Checkpoint hot-reloads completed"),
    # Cold-start observability (tpu_resnet/programs; docs/PERF.md "Cold
    # start"): how long this replica took to reach ready, how many
    # bucket programs are warm so far (partial readiness), and the AOT
    # executable-cache traffic behind those numbers.
    ("serve_time_to_ready_seconds", "Backend build + restore + bucket "
                                    "warmup wall time until /healthz ok"),
    ("serve_buckets_warm", "Bucket programs warmed so far (== bucket "
                           "count once ready; partial during warmup)"),
    # Quantized-arm memory (ops/quant.py; docs/SERVING.md "Quantized
    # arm"): weight-argument bytes of one bucket program — int8 arms
    # read ~0.25x their f32 twin (the golden-memory-twin ratio, live).
    ("serve_weight_bytes", "Weight-argument bytes per bucket program "
                           "(int8 quantized arms ~0.25x of f32)"),
    ("compile_cache_hits", "Bucket programs loaded from the persistent "
                           "AOT executable cache instead of compiling"),
    ("compile_cache_misses", "Bucket programs XLA-compiled because the "
                             "cache had no trustworthy entry"),
)

# Router gauge set (tpu_resnet/serve/router.py; docs/SERVING.md "Serving
# fleet"). The front router runs the same registry/HTTP stack on its own
# port — /healthz is 503 while no replica is healthy.
ROUTE_GAUGES = (
    ("route_requests_total", "Predict requests accepted by the router"),
    ("route_requests_ok", "Requests answered 2xx end to end"),
    ("route_requests_failed", "Requests that exhausted replicas/retries "
                              "or blew the deadline budget"),
    ("route_retries_total", "Failover retries sent to a second replica "
                            "(connect failure / 5xx / deadline)"),
    ("route_hedges_total", "Hedged duplicate sends fired (requests "
                           "sitting past the hedge threshold)"),
    ("route_hedge_wins_total", "Hedged sends whose duplicate answered "
                               "first"),
    ("route_shed_total", "Requests shed by SLO admission (rolling p99 "
                         "over route.slo_ms) -> HTTP 429"),
    ("route_shed_batch_total", "Batch-lane requests shed (lowest "
                               "priority sheds first)"),
    ("route_shed_interactive_total", "Interactive-lane requests shed "
                                     "(p99 past slo*shed_hard_factor)"),
    ("route_replica_errors_total", "Passive replica failures observed "
                                   "(connect/5xx/timeout)"),
    ("route_replicas_total", "Replicas known to the router (static + "
                             "discovered)"),
    ("route_replicas_healthy", "Replicas currently in rotation (circuit "
                               "closed, not draining)"),
    ("route_inflight", "Requests currently in flight across replicas"),
    ("route_p50_ms", "Rolling p50 end-to-end router latency"),
    ("route_p99_ms", "Rolling p99 end-to-end router latency (the shed/"
                     "hedge signal)"),
    ("route_slo_ms", "Configured p99 SLO target (0 = shedding off)"),
    ("route_lane_interactive_total", "Interactive-lane requests routed"),
    ("route_lane_batch_total", "Batch-lane requests routed"),
)

# Fleet-aggregator gauge set (tpu_resnet/obs/fleet.py; docs/
# OBSERVABILITY.md "Fleet"). fleetmon runs the same registry/HTTP stack
# on its own port; the fleet_serve_p* series are EXACT pooled quantiles
# from bucket-wise histogram merges (merge_histograms), never an
# average of per-replica percentiles.
FLEET_GAUGES = (
    ("fleet_endpoints_total", "Endpoints found in the discovery dir on "
                              "the last scrape round"),
    ("fleet_endpoints_up", "Endpoints whose /metrics answered on the "
                           "last round"),
    ("fleet_scrapes_total", "Scrape rounds completed since start"),
    ("fleet_scrape_errors_total", "Individual endpoint scrapes that "
                                  "failed (cumulative)"),
    ("fleet_requests_total", "Requests admitted across all serve "
                             "replicas (summed serve_latency_ms count)"),
    ("fleet_serve_p50_ms", "Fleet-wide p50 predict latency (bucket-"
                           "merged across replicas)"),
    ("fleet_serve_p95_ms", "Fleet-wide p95 predict latency (bucket-"
                           "merged across replicas)"),
    ("fleet_serve_p99_ms", "Fleet-wide p99 predict latency (bucket-"
                           "merged across replicas)"),
    ("fleet_slo_ms", "Configured fleet latency SLO threshold (0 = burn "
                     "tracking off)"),
    ("fleet_burn_rate_fast", "Error-budget burn rate over the fast "
                             "window (1.0 = burning exactly the "
                             "budget)"),
    ("fleet_burn_rate_slow", "Error-budget burn rate over the slow "
                             "window"),
    ("fleet_alerts_total", "Burn-rate alerts fired since start"),
    ("fleet_alert_active", "1 while a burn-rate alert condition holds"),
)

# Autopilot gauge set (tpu_resnet/autopilot/; docs/AUTOPILOT.md). The
# autoscaling control loop runs the same registry/HTTP stack on its own
# port; every gauge here mirrors a field of the decision records it
# appends to autopilot_events.jsonl, so the scrape plane and the ledger
# can never tell different stories.
AUTOPILOT_GAUGES = (
    ("autopilot_rounds_total", "Control-loop rounds completed (one "
                               "snapshot + one policy decision each)"),
    ("autopilot_signal_errors_total", "Rounds whose signal scrape failed "
                                      "(router unreachable etc.) — the "
                                      "policy holds on a blind round"),
    ("autopilot_scale_ups_total", "Scale-up decisions actuated"),
    ("autopilot_scale_downs_total", "Scale-down decisions actuated"),
    ("autopilot_holds_total", "Rounds the policy decided to do nothing"),
    ("autopilot_spawns_total", "Replica spawns launched (supervise/"
                               "discovery path)"),
    ("autopilot_spawn_failures_total", "Spawns that crashed or blew "
                                       "ready_timeout_secs"),
    ("autopilot_admission_denied_total", "Spawns denied by colocation "
                                         "admission (exit 3) — each "
                                         "arms the scale-up backoff"),
    ("autopilot_drains_total", "Replicas drained via the router's "
                               "/admin/drain rolling contract"),
    ("autopilot_target_replicas", "The policy's current target replica "
                                  "count"),
    ("autopilot_replicas_total", "Replicas the router knows (from the "
                                 "last signal snapshot)"),
    ("autopilot_replicas_healthy", "Replicas in rotation (from the last "
                                   "signal snapshot)"),
    ("autopilot_p99_ms", "Router rolling p99 from the last snapshot "
                         "(the primary pressure signal)"),
    ("autopilot_slo_ms", "Effective SLO the hysteresis bands are "
                         "anchored to"),
    ("autopilot_burn_rate_fast", "fleetmon fast-window burn rate from "
                                 "the last snapshot"),
    ("autopilot_scale_up_latency_ms", "Last observed spawn -> healthy-"
                                      "in-router latency (the series "
                                      "the autoscale scenarios gate)"),
    ("autopilot_slo_violation_seconds", "Integrated seconds the fleet "
                                        "p99 sat above the SLO while "
                                        "the autopilot watched"),
    ("autopilot_replica_seconds", "Integrated healthy-replica x seconds "
                                  "(the capacity-spend denominator)"),
    ("autopilot_utilization", "Router requests served per healthy "
                              "replica-second (capacity efficiency)"),
    ("autopilot_capacity_granted", "1 while the capacity lease is "
                                   "granted to the colocated trainer"),
)


# Histogram bucket edges (upper bounds; +Inf is implicit). Latencies in
# ms span sub-ms CPU inference to multi-second stragglers; the fraction
# scale covers 0..1 ratios (pad fraction).
LATENCY_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
                      500.0, 1000.0, 2000.0, 5000.0)
FRACTION_BUCKETS = (0.01, 0.02, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.75, 1.0)

# Pre-declared histogram series, same convention as the gauges: a scrape
# taken before the first observation sees empty buckets, not absent
# series. (name, help, bucket edges).
CORE_HISTOGRAMS = (
    ("train_step_ms", "Per-step wall time, observed once per step at "
                      "each log boundary", LATENCY_BUCKETS_MS),
)
# Seconds-scale buckets for once-per-process durations (time-to-ready):
# sub-second cache-hit restarts through multi-minute cold compiles.
READY_BUCKETS_S = (0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 60.0, 120.0,
                   300.0)

SERVE_HISTOGRAMS = (
    ("serve_latency_ms", "End-to-end predict latency (enqueue to "
                         "result)", LATENCY_BUCKETS_MS),
    ("serve_queue_wait_ms", "Time a request waited in the queue before "
                            "its batch was formed", LATENCY_BUCKETS_MS),
    ("serve_pad_fraction", "Padded fraction of each dispatched bucket "
                           "(compile-avoidance cost per batch)",
     FRACTION_BUCKETS),
    ("serve_time_to_ready_s", "Time-to-ready per process start (backend "
                              "build + restore + bucket warmup) — the "
                              "series the cold-vs-warm restart gate "
                              "reads", READY_BUCKETS_S),
)
ROUTE_HISTOGRAMS = (
    ("route_latency_ms", "End-to-end router latency (accept to client "
                         "response, retries/hedges included)",
     LATENCY_BUCKETS_MS),
    ("route_upstream_ms", "Single upstream attempt latency per replica "
                          "send", LATENCY_BUCKETS_MS),
)


def _sanitize(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_]", "_", name)


class Histogram:
    """Fixed-bucket histogram with Prometheus exposition semantics.

    ``observe(v, n)`` adds ``n`` observations of value ``v`` (n>1 is the
    weighted form the train loop uses: one interval = ``steps``
    observations of the interval's mean step time). Rendering follows
    the Prometheus histogram convention exactly — cumulative
    ``_bucket{le="..."}`` counts, ``_sum`` and ``_count`` — so a stock
    Prometheus server can do ``histogram_quantile()`` over scrapes while
    :func:`histogram_quantile` here gives the same answer offline.

    Not thread-safe by itself; TelemetryRegistry serializes access under
    its lock."""

    __slots__ = ("name", "help", "edges", "counts", "total", "sum")

    def __init__(self, name: str, help: str = "", edges=LATENCY_BUCKETS_MS):
        edges = tuple(float(e) for e in edges)
        if not edges or list(edges) != sorted(set(edges)):
            raise ValueError(f"bucket edges must be strictly increasing, "
                             f"got {edges}")
        self.name = _sanitize(name)
        self.help = help
        self.edges = edges
        self.counts = [0] * (len(edges) + 1)  # last = overflow (+Inf)
        self.total = 0
        self.sum = 0.0

    def observe(self, value, n: int = 1) -> None:
        try:
            value = float(value)
            n = int(n)
        except (TypeError, ValueError):
            return
        if n < 1:
            return
        i = bisect.bisect_left(self.edges, value)
        self.counts[i] += n
        self.total += n
        self.sum += value * n

    def snapshot(self) -> dict:
        """``{"buckets": [(le, cumulative_count)...], "sum", "count"}``
        with the trailing +Inf bucket — the same structure
        :func:`parse_histograms` reconstructs from a scrape."""
        cum, buckets = 0, []
        for edge, c in zip(self.edges, self.counts):
            cum += c
            buckets.append((edge, cum))
        buckets.append((math.inf, self.total))
        return {"buckets": buckets, "sum": self.sum, "count": self.total}

    def percentile(self, q: float) -> float:
        return histogram_quantile(self.snapshot(), q)

    def render(self, namespace: str = NAMESPACE) -> list:
        full = f"{namespace}_{self.name}"
        lines = []
        if self.help:
            lines.append(f"# HELP {full} {self.help}")
        lines.append(f"# TYPE {full} histogram")
        cum = 0
        for edge, c in zip(self.edges, self.counts):
            cum += c
            lines.append(f'{full}_bucket{{le="{edge!r}"}} {cum}')
        lines.append(f'{full}_bucket{{le="+Inf"}} {self.total}')
        lines.append(f"{full}_sum {self.sum!r}")
        lines.append(f"{full}_count {self.total}")
        return lines


def histogram_quantile(hist: dict, q: float) -> float:
    """Quantile from a histogram snapshot (``Histogram.snapshot()`` or a
    :func:`parse_histograms` entry): linear interpolation inside the
    bucket containing the target rank — the same estimator Prometheus's
    ``histogram_quantile()`` uses, so live dashboards and offline tools
    agree. Returns 0.0 for an empty histogram; the overflow bucket
    reports its lower edge (the largest finite edge)."""
    buckets = hist.get("buckets") or []
    total = hist.get("count", 0)
    if not buckets or total <= 0:
        return 0.0
    q = min(max(q, 0.0), 1.0)
    rank = q * total
    prev_edge, prev_cum = 0.0, 0
    for edge, cum in buckets:
        if cum >= rank:
            if math.isinf(edge):
                return float(prev_edge)
            if cum == prev_cum:
                return float(edge)
            frac = (rank - prev_cum) / (cum - prev_cum)
            return float(prev_edge + (edge - prev_edge) * frac)
        prev_edge, prev_cum = edge, cum
    return float(prev_edge)


def merge_histograms(snapshots) -> dict:
    """Bucket-wise merge of histogram snapshots from different processes
    into one pooled snapshot.

    Because every replica uses the same fixed bucket edges (the PR 6
    pre-declared exposition), summing cumulative counts position-wise is
    EXACT pooling: ``histogram_quantile`` over the merge equals the
    quantile of the pooled samples to within one bucket's interpolation
    error — the true fleet p99, not an average of per-replica
    percentiles (tests/test_fleet.py proves the equivalence vs numpy).

    Mismatched bucket boundaries raise ValueError — merging histograms
    with different edges silently would fabricate counts in buckets that
    never existed. Empty input merges to an empty snapshot."""
    snapshots = [s for s in snapshots if s and s.get("buckets")]
    if not snapshots:
        return {"buckets": [], "sum": 0.0, "count": 0}
    edges = [e for e, _ in snapshots[0]["buckets"]]
    for s in snapshots[1:]:
        other = [e for e, _ in s["buckets"]]
        if other != edges:
            raise ValueError(
                f"cannot merge histograms with mismatched bucket edges: "
                f"{edges} vs {other}")
    buckets = []
    for i, edge in enumerate(edges):
        buckets.append((edge, sum(s["buckets"][i][1] for s in snapshots)))
    return {"buckets": buckets,
            "sum": sum(float(s.get("sum", 0.0)) for s in snapshots),
            "count": sum(int(s.get("count", 0)) for s in snapshots)}


class TelemetryRegistry:
    """Thread-safe gauge store shared by the training loop (writer) and
    the HTTP server threads (readers)."""

    def __init__(self, stale_after_sec: float = 300.0, gauges=CORE_GAUGES,
                 histograms=()):
        """``gauges``/``histograms`` are the pre-declared series sets —
        CORE_* for a training process, SERVE_* for the predict server
        (scrapes taken before the first batch must see explicit
        zeros/empty buckets, not absent series)."""
        self.stale_after_sec = float(stale_after_sec)
        self._lock = threading.Lock()
        self._gauges: Dict[str, float] = {}
        self._help: Dict[str, str] = {}
        self._hists: Dict[str, Histogram] = {}
        self._hb_wall: Optional[float] = None
        self._hb_step: Optional[int] = None
        self._unhealthy_reason: Optional[str] = None
        self._started = time.time()
        for name, help_text in gauges:
            self.set(name, 0.0, help=help_text)
        for name, help_text, edges in histograms:
            h = Histogram(name, help_text, edges)
            self._hists[h.name] = h

    def set(self, name: str, value, help: str = "") -> None:
        try:
            value = float(value)
        except (TypeError, ValueError):
            return
        name = _sanitize(name)
        with self._lock:
            self._gauges[name] = value
            if help:
                self._help[name] = help

    def update(self, scalars: Dict[str, float]) -> None:
        for k, v in scalars.items():
            self.set(k, v)

    def observe(self, name: str, value, n: int = 1) -> None:
        """Add ``n`` observations of ``value`` to histogram ``name``
        (created on first use with the default latency buckets if it was
        not pre-declared)."""
        name = _sanitize(name)
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name)
            h.observe(value, n)

    def hist_percentile(self, name: str, q: float) -> float:
        """Quantile estimate over histogram ``name`` (0.0 when absent or
        empty) — the host-side read the serve bucket retuning and the
        loop's step-time percentile metrics use."""
        with self._lock:
            h = self._hists.get(_sanitize(name))
            snap = h.snapshot() if h is not None else None
        return histogram_quantile(snap, q) if snap else 0.0

    def heartbeat(self, step: int) -> None:
        """Mark the trainer alive at ``step`` (call at every log point)."""
        with self._lock:
            self._hb_wall = time.time()
            self._hb_step = int(step)
            self._gauges["step"] = float(step)

    def heartbeat_age(self) -> float:
        with self._lock:
            base = self._hb_wall if self._hb_wall is not None \
                else self._started
        return max(0.0, time.time() - base)

    def mark_unhealthy(self, reason: str) -> None:
        """Force /healthz to 503 with an explicit reason — used by the
        hang watchdog, whose stall deadline is typically much tighter than
        the heartbeat-staleness threshold."""
        with self._lock:
            self._unhealthy_reason = str(reason)

    def clear_unhealthy(self) -> None:
        with self._lock:
            self._unhealthy_reason = None

    def health(self) -> dict:
        age = self.heartbeat_age()
        with self._lock:
            step = self._hb_step
            reason = self._unhealthy_reason
        out = {
            "ok": age < self.stale_after_sec and reason is None,
            "step": step,
            "heartbeat_age_sec": round(age, 3),
            "stale_after_sec": self.stale_after_sec,
            "time": time.time(),
        }
        if reason is not None:
            out["unhealthy_reason"] = reason
        return out

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4 — gauges plus
        histogram series (cumulative ``_bucket{le=...}``/``_sum``/
        ``_count``, the standard exposition
        :func:`parse_histograms` round-trips)."""
        with self._lock:
            gauges = dict(self._gauges)
            helps = dict(self._help)
            hist_lines = []
            for name in sorted(self._hists):
                hist_lines.extend(self._hists[name].render())
        gauges["heartbeat_age_seconds"] = round(self.heartbeat_age(), 3)
        helps.setdefault("heartbeat_age_seconds",
                         "Seconds since the trainer's last heartbeat")
        lines = []
        for name in sorted(gauges):
            full = f"{NAMESPACE}_{name}"
            if name in helps:
                lines.append(f"# HELP {full} {helps[name]}")
            lines.append(f"# TYPE {full} gauge")
            lines.append(f"{full} {gauges[name]!r}")
        lines.extend(hist_lines)
        return "\n".join(lines) + "\n"


class TelemetryServer:
    """Daemon-threaded HTTP server over a registry. ``port=0`` binds an
    OS-assigned ephemeral port (exposed as ``self.port``)."""

    def __init__(self, registry: TelemetryRegistry, port: int = 0,
                 host: str = "0.0.0.0"):
        self.registry = registry

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._send(200, registry.render().encode(),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    health = registry.health()
                    self._send(200 if health["ok"] else 503,
                               json.dumps(health).encode(),
                               "application/json")
                else:
                    self._send(404, b'{"error": "not found"}\n',
                               "application/json")

            def log_message(self, *args):  # scrapes must not spam the run log
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tpu-resnet-telemetry",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        if self._httpd is not None:
            httpd, self._httpd = self._httpd, None
            httpd.shutdown()
            httpd.server_close()

    @classmethod
    def maybe_start(cls, port: int, registry: TelemetryRegistry,
                    train_dir: Optional[str] = None
                    ) -> Optional["TelemetryServer"]:
        """Start a server per the config semantics: ``port < 0`` disabled,
        ``0`` ephemeral, ``> 0`` fixed. A bind failure (port taken) logs a
        warning and returns None — telemetry must never kill training. The
        bound port is recorded in ``<train_dir>/telemetry.json``."""
        if port is None or port < 0:
            return None
        try:
            server = cls(registry, port)
        except OSError as e:
            log.warning("telemetry server failed to bind port %s: %s "
                        "(training continues without /metrics)", port, e)
            return None
        log.info("telemetry server on :%d (/metrics Prometheus text, "
                 "/healthz liveness)", server.port)
        if train_dir:
            # Every host runs a server, and multi-host runs often share
            # one train_dir — a single discovery file would be clobbered
            # by whichever host wrote last, pointing local scrapers at a
            # port bound on a DIFFERENT machine. Each host writes its own
            # hostname-keyed file; the bare telemetry.json is kept as the
            # single-host/common case (written when this host is the one
            # that would win anyway: process_index 0).
            try:
                import socket

                os.makedirs(train_dir, exist_ok=True)
                record = {"port": server.port, "pid": os.getpid(),
                          "hostname": socket.gethostname(),
                          "started_at": time.time()}
                names = [f"telemetry-{socket.gethostname()}.json"]
                try:
                    import jax
                    primary = jax.process_index() == 0
                except Exception:
                    primary = True
                if primary:
                    names.append("telemetry.json")
                for name in names:
                    path = os.path.join(train_dir, name)
                    tmp = path + f".tmp{os.getpid()}"
                    with open(tmp, "w") as f:
                        json.dump(record, f)
                    os.replace(tmp, path)
            except OSError as e:  # discovery file is best-effort
                log.warning("could not write telemetry.json: %s", e)
        return server


def read_telemetry_port(train_dir: str) -> Optional[int]:
    """Port recorded by ``TelemetryServer.maybe_start`` for this run.

    Prefers this host's ``telemetry-<hostname>.json`` (shared train_dirs
    hold one file per host; local scrapers dial 127.0.0.1 and must get the
    port bound on THIS machine), falling back to the bare
    ``telemetry.json`` written by the primary process."""
    import socket

    for name in (f"telemetry-{socket.gethostname()}.json",
                 "telemetry.json"):
        try:
            with open(os.path.join(train_dir, name)) as f:
                return int(json.load(f)["port"])
        except (OSError, ValueError, KeyError):
            continue
    return None


def scrape(base_url: str, timeout: float = 5.0) -> dict:
    """One scrape of a telemetry server: GET ``/metrics`` + ``/healthz``.

    ``base_url`` is ``host[:port]`` or a full http URL. Returns
    ``{"metrics": {name: value}, "health": {...}, "health_status": int}``
    (a 503 — stale heartbeat — is a valid report, not an error). Raises
    OSError when the server is unreachable and ValueError on malformed
    bodies. Stdlib-only: the doctor check and tools/obs_scrape.py share
    this without importing a backend."""
    import urllib.error
    import urllib.request

    base_url = base_url.rstrip("/")
    if "://" not in base_url:
        base_url = "http://" + base_url
    with urllib.request.urlopen(base_url + "/metrics",
                                timeout=timeout) as resp:
        text = resp.read().decode()
    metrics = parse_prometheus(text)
    try:
        with urllib.request.urlopen(base_url + "/healthz",
                                    timeout=timeout) as resp:
            status, body = resp.status, resp.read()
    except urllib.error.HTTPError as e:  # 503 stale: report, don't raise
        status, body = e.code, e.read()
    return {"metrics": metrics, "histograms": parse_histograms(text),
            "health": json.loads(body.decode()),
            "health_status": status}


def parse_prometheus(text: str) -> Dict[str, float]:
    """Prometheus text → {metric_name: value}. Raises ValueError on a
    malformed sample line (the scrape tests use this as the parser).
    Histogram component series collapse to their last sample here; use
    :func:`parse_histograms` for the bucket structure."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) < 2:
            raise ValueError(f"malformed sample line: {line!r}")
        name = parts[0].split("{", 1)[0]
        out[name] = float(parts[1])
    return out


_LE_LABEL = re.compile(r'\{le="([^"]+)"\}')


def parse_histograms(text: str) -> Dict[str, dict]:
    """Prometheus text → histogram structures.

    Collects ``name_bucket{le="..."}``/``name_sum``/``name_count``
    triplets declared ``# TYPE name histogram`` into
    ``{name: {"buckets": [(le, cum)...], "sum": s, "count": n}}`` — the
    same snapshot shape :meth:`Histogram.snapshot` produces, so
    :func:`histogram_quantile` works on live scrapes and in-process
    histograms alike. Unparseable histogram lines are skipped (a gauge
    parser strictness here would make every scraper crash on a
    mid-write exposition)."""
    declared = set()
    for line in text.splitlines():
        if line.startswith("# TYPE ") and line.rstrip().endswith(
                " histogram"):
            declared.add(line.split()[2])
    out: Dict[str, dict] = {
        name: {"buckets": [], "sum": 0.0, "count": 0} for name in declared}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) < 2:
            continue
        sample, value = parts[0], parts[1]
        base = sample.split("{", 1)[0]
        for name in declared:
            if base == name + "_bucket":
                m = _LE_LABEL.search(sample)
                if not m:
                    break
                le = math.inf if m.group(1) == "+Inf" else float(m.group(1))
                try:
                    out[name]["buckets"].append((le, int(float(value))))
                except ValueError:
                    pass
                break
            if base == name + "_sum":
                try:
                    out[name]["sum"] = float(value)
                except ValueError:
                    pass
                break
            if base == name + "_count":
                try:
                    out[name]["count"] = int(float(value))
                except ValueError:
                    pass
                break
    for hist in out.values():
        hist["buckets"].sort(key=lambda b: b[0])
    return out
