"""Comms observability — the wire twin of the FLOPs and HBM accounting.

The reference system's entire distributed story was its collective
structure (SURVEY §2: ``SyncReplicasOptimizer``, Horovod allreduce), and
every remaining scaling direction — the 2-D ("data","model") mesh
multi-host push, ZeRO-2/3 — stands or falls on putting exactly the
right collectives on exactly the right mesh axes. ``obs/mfu.py`` gave a
run its compute truth and ``obs/memory.py`` its space truth; this
module gives it the third axis: what the compiled program puts ON THE
WIRE per step, measured once at startup and pinned golden by the
collectives check engine (``analysis/collectives.py``).

``extract_collectives``   every collective op (all-reduce, all-gather,
                          reduce-scatter, collective-permute,
                          all-to-all) from a compiled program's HLO
                          module text, with payload bytes, replica
                          groups (both the explicit ``{{0,2},{1,3}}``
                          and the iota ``[2,4]<=[4,2]T(1,0)`` forms)
                          and a mesh-axis bucket (data / model / all /
                          mixed) derived from the run's (data, model)
                          mesh shape.
``summarize_collectives`` the per-program comms budget: op multiset,
                          canonical structure signature, analytic
                          bytes-on-wire per step bucketed by mesh axis
                          (ring-algorithm cost model), and the ZeRO
                          exchange components (reduce-scatter /
                          all-gather / plain all-reduce bytes) the
                          zero1 twin gate reads.
``CommsLedger``           per-compiled-program comms entries keyed
                          EXACTLY like ``flops.json`` / ``memory.json``
                          (``registry.spell``), persisted to
                          ``<train_dir>/comms.json``.
``ICI_BYTES_BY_KIND``     per-chip interconnect bandwidth (public chip
                          specs) — the ``HBM_BYTES_BY_KIND`` pattern,
                          ``TPU_RESNET_ICI_BYTES`` override — feeding
                          the predicted time-on-wire and the
                          ``predicted_comms_fraction`` gauge.

One subtlety the parser owns so every consumer doesn't have to: XLA's
CPU pipeline runs the reduce-scatter DECOMPOSER (reduce-scatter becomes
a full all-reduce whose result is immediately sliced), so a ZeRO-1
gradient exchange never shows a literal ``reduce-scatter`` op in a CPU
compile. ``extract_collectives`` re-derives the logical op: an
all-reduce whose every consumer keeps at most ``1/group_size`` of the
payload is classified (and costed) as a reduce-scatter. On TPU the
literal op appears and classifies identically, so goldens and gates
mean the same thing on both backends.

Like the FLOPs/HBM accountants this pays its cost ONCE per run at first
dispatch (one extra XLA compile, gated by ``train.comms_ledger``,
charged to the compile window) and degrades to absent — never a
per-step cost. Module import stays jax-free (jax only inside functions)
so stdlib-only consumers (tools/perfwatch.py, the doctor checks, the
analysis engines' compare paths) can parse HLO text and read ledger
files without a backend.
"""
# check: disable-file=jit-host-sync — this module IS the host-side
# comms prober: compiled-program introspection at startup/check time
# only, never from jit scope.

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

log = logging.getLogger("tpu_resnet")

LEDGER_FILE = "comms.json"

# Per-chip aggregate inter-chip-interconnect bandwidth in bytes/s by
# device_kind substring (public Cloud TPU chip specs: v4 2400 Gb/s, v5e
# 1600 Gb/s, v5p 4800 Gb/s, v6e 3584 Gb/s per chip) — the comms twin of
# mfu.PEAK_FLOPS_BY_KIND / memory.HBM_BYTES_BY_KIND. Order matters:
# more specific names first.
_GBPS = 1e9 / 8
ICI_BYTES_BY_KIND = (
    ("v5p", 4800 * _GBPS),
    ("v5 lite", 1600 * _GBPS), ("v5e", 1600 * _GBPS),
    ("v5litepod", 1600 * _GBPS),
    ("v6 lite", 3584 * _GBPS), ("v6e", 3584 * _GBPS),
    ("v4", 2400 * _GBPS),
)

COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")
FLOAT_DTYPES = {"f16", "bf16", "f32", "f64"}

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
                "f8e4m3fn": 1, "f8e5m2": 1,
                "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
                "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}

_SHAPE_RE = re.compile(r"([a-z]\w*)\[([0-9,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<name>[\w.-]+)\s*=\s*"
    r"(?P<type>\([^)]*\)|[a-z]\w*\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(?P<op>[\w-]+)\(")
_EXPLICIT_GROUPS_RE = re.compile(r"replica_groups=\{(\{[0-9, ]*\}"
                                 r"(?:,\{[0-9, ]*\})*)?\}")
_IOTA_GROUPS_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]"
                             r"<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)*)\}")


def ici_bytes_per_chip(device_kind: str,
                       env_var: str = "TPU_RESNET_ICI_BYTES"
                       ) -> Optional[float]:
    """Aggregate ICI bandwidth in bytes/s for one chip of
    ``device_kind``; None when the kind is unknown (CPU, new silicon).
    ``env_var`` overrides the table — the escape hatch for chips it
    hasn't learned yet (and how CPU CI exercises the prediction path)."""
    env = os.environ.get(env_var)
    if env:
        try:
            return float(env)
        except ValueError:
            log.warning("ignoring non-numeric %s=%r", env_var, env)
    kind = (device_kind or "").lower()
    for sub, bw in ICI_BYTES_BY_KIND:
        if sub in kind:
            return bw
    return None


def _type_bytes(type_text: str) -> int:
    """Total bytes of an HLO result/operand type string — scalar
    (``f32[]``), array (``f32[3,3,16,16]{3,2,1,0}``) or tuple (every
    array inside the parens summed)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_text):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _type_dtype(type_text: str) -> str:
    m = _SHAPE_RE.search(type_text)
    return m.group(1) if m else "?"


def _iota_groups(n_groups: int, group_size: int, dims: Sequence[int],
                 perm: Optional[Sequence[int]]) -> List[Tuple[int, ...]]:
    """Expand XLA's IotaReplicaGroupList form
    ``[n_groups,group_size]<=[dims]T(perm)``: device ids are
    ``iota(prod(dims))`` reshaped to ``dims``, transposed by ``perm``,
    then reshaped row-major to ``[n_groups, group_size]``."""
    dims = list(dims)
    perm = list(perm) if perm is not None else list(range(len(dims)))
    pdims = [dims[p] for p in perm]
    total = 1
    for d in dims:
        total *= d
    flat: List[int] = []
    coords = [0] * len(pdims)
    for _ in range(max(total, 0)):
        orig = [0] * len(dims)
        for k, p in enumerate(perm):
            orig[p] = coords[k]
        v = 0
        for d, c in zip(dims, orig):
            v = v * d + c
        flat.append(v)
        for k in reversed(range(len(coords))):
            coords[k] += 1
            if coords[k] < pdims[k]:
                break
            coords[k] = 0
    return [tuple(flat[i * group_size:(i + 1) * group_size])
            for i in range(n_groups)]


def _parse_groups(line: str, n_devices: int) -> List[Tuple[int, ...]]:
    """Replica groups of one collective line, in either HLO spelling;
    empty ``replica_groups={}`` means one group of every device."""
    m = _IOTA_GROUPS_RE.search(line)
    if m:
        dims = [int(d) for d in m.group(3).split(",")]
        perm = ([int(p) for p in m.group(4).split(",")]
                if m.group(4) else None)
        return _iota_groups(int(m.group(1)), int(m.group(2)), dims, perm)
    m = _EXPLICIT_GROUPS_RE.search(line)
    if m:
        if not m.group(1):
            return [tuple(range(n_devices))]
        return [tuple(int(x) for x in g.split(",") if x.strip())
                for g in re.findall(r"\{([0-9, ]*)\}", m.group(1))]
    m = _PAIRS_RE.search(line)
    if m and m.group(1):
        return [tuple(int(x) for x in p.split(","))
                for p in re.findall(r"\{(\d+,\d+)\}", m.group(1))]
    return [tuple(range(n_devices))]


def classify_groups(groups: Sequence[Tuple[int, ...]], data_axis: int,
                    model_axis: int) -> str:
    """Mesh-axis bucket of a collective's replica groups on the
    row-major ("data","model") device mesh: ``"data"`` / ``"model"``
    (groups vary exactly one mesh coordinate), ``"all"`` (one group,
    the full mesh), ``"mixed"`` (both coordinates vary in a group that
    is NOT the whole mesh — the axis-confinement violation), ``"self"``
    (degenerate single-member groups). On a 1-D mesh (model_axis == 1)
    the full mesh classifies as ``"data"`` — there is no second axis to
    confuse it with."""
    n = data_axis * model_axis
    buckets = set()
    for g in groups:
        members = set(g)
        if len(members) <= 1:
            buckets.add("self")
            continue
        d_varies = len({i // model_axis for i in members}) > 1
        m_varies = len({i % model_axis for i in members}) > 1
        if d_varies and m_varies:
            buckets.add("all" if len(members) == n and len(groups) == 1
                        else "mixed")
        elif d_varies:
            buckets.add("data")
        elif m_varies:
            buckets.add("model")
    buckets.discard("self")
    if not buckets:
        return "self"
    if len(buckets) == 1:
        return buckets.pop()
    return "mixed"


@dataclasses.dataclass
class Collective:
    """One collective op extracted from compiled HLO: the effective op
    (decomposed reduce-scatter re-derived), full logical payload bytes,
    replica-group shape and the analytic per-device bytes-on-wire under
    the ring cost model."""
    op: str                # effective op (all-reduce | all-gather | ...)
    raw_op: str            # opcode as spelled in the HLO text
    name: str              # instruction name
    dtype: str
    payload_bytes: int     # full (unsharded) logical payload
    group_size: int
    n_groups: int
    bucket: str            # data | model | all | mixed | self
    wire_bytes: float      # per participating device, per execution

    def signature(self) -> str:
        """Canonical structure key: effective op, payload dtype+bytes,
        mesh-axis bucket and group size — the multiset the golden
        compare pins (instruction names and channel ids are compiler
        noise and deliberately excluded)."""
        return (f"{self.op}|{self.dtype}:{self.payload_bytes}b"
                f"|{self.bucket}|g{self.group_size}")


def _split_computations(hlo_text: str) -> List[List[str]]:
    """HLO module text → instruction-line blocks, one per computation
    (collectives and their consumers always live in the same
    computation; fusions are separate blocks)."""
    blocks: List[List[str]] = []
    current: Optional[List[str]] = None
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.endswith("{") and ("(" in stripped or
                                       stripped.startswith(("ENTRY", "%"))):
            current = []
            continue
        if stripped == "}":
            if current:
                blocks.append(current)
            current = None
            continue
        if current is not None and stripped:
            current.append(line)
    if current:
        blocks.append(current)
    return blocks


def _ring_wire_bytes(op: str, payload: int, group_size: int) -> float:
    """Per-device bytes-on-wire of one collective under the standard
    ring algorithms (payload S, group size G): all-reduce moves
    2·S·(G−1)/G (reduce-scatter phase + all-gather phase), all-gather /
    reduce-scatter / all-to-all move S·(G−1)/G, collective-permute
    forwards the payload once."""
    g = max(group_size, 1)
    if g == 1:
        return 0.0
    if op == "all-reduce":
        return 2.0 * payload * (g - 1) / g
    if op in ("all-gather", "reduce-scatter", "all-to-all"):
        return float(payload) * (g - 1) / g
    return float(payload)  # collective-permute


def extract_collectives(hlo_text: str, data_axis: int,
                        model_axis: int) -> List[Collective]:
    """Every collective op in ``hlo_text`` (post-SPMD-partitioner HLO —
    collectives only exist after partitioning) with payloads, groups,
    axis buckets and ring-model wire bytes. Async ``-start``/``-done``
    pairs count once; an all-reduce whose consumers all keep at most
    ``1/group_size`` of the payload is re-derived as the logical
    reduce-scatter XLA's CPU decomposer hid (see module docstring)."""
    n_devices = max(data_axis * model_axis, 1)
    out: List[Collective] = []
    for block in _split_computations(hlo_text):
        instrs = []  # (name, result_bytes, line)
        for line in block:
            m = _INSTR_RE.match(line)
            if m:
                instrs.append((m.group("name"),
                               _type_bytes(m.group("type")), m, line))
        for name, result_bytes, m, line in instrs:
            raw_op = m.group("op")
            base_op = raw_op[:-6] if raw_op.endswith("-start") else raw_op
            if base_op not in COLLECTIVE_OPS:
                continue
            type_text = m.group("type")
            groups = _parse_groups(line, n_devices)
            group_size = max((len(set(g)) for g in groups), default=1)
            if base_op == "collective-permute":
                # source_target_pairs: payload forwarded once per pair;
                # per-device cost is one payload send.
                group_size = 2
            payload = result_bytes
            if base_op == "reduce-scatter":
                # Output is the shard: the logical payload is the full
                # operand. Operand types sit inside the call parens.
                tail = line[m.end():]
                depth = 1
                for i, ch in enumerate(tail):
                    if ch == "(":
                        depth += 1
                    elif ch == ")":
                        depth -= 1
                        if depth == 0:
                            payload = _type_bytes(tail[:i]) or result_bytes
                            break
            op = base_op
            if base_op == "all-reduce" and not type_text.startswith("("):
                # Re-derive the decomposed reduce-scatter: every
                # consumer keeps <= ceil(payload/G) (+ one element of
                # layout slack) of the reduced result.
                shard_cap = (payload + group_size - 1) // group_size \
                    + _DTYPE_BYTES.get(_type_dtype(type_text), 4)
                ref = re.compile(re.escape("%" + name) + r"(?![\w.-])")
                consumers = [cb for cn, cb, _, cl in instrs
                             if cn != name and ref.search(
                                 cl.split(" = ", 1)[-1])]
                if consumers and group_size > 1 \
                        and all(cb <= shard_cap for cb in consumers):
                    op = "reduce-scatter"
            out.append(Collective(
                op=op, raw_op=raw_op, name=name,
                dtype=_type_dtype(type_text),
                payload_bytes=payload, group_size=group_size,
                n_groups=len(groups),
                bucket=classify_groups(groups, data_axis, model_axis),
                wire_bytes=_ring_wire_bytes(op, payload, group_size)))
    return out


def summarize_collectives(hlo_text: str, data_axis: int,
                          model_axis: int) -> dict:
    """The per-program comms budget the golden engine pins and the
    ledger persists: op multiset (effective ops), canonical structure
    signature counts, per-axis bytes-on-wire, and the ZeRO exchange
    components — ``all_gather_bytes`` / ``reduce_scatter_bytes`` /
    ``plain_all_reduce_bytes`` are FULL float payload bytes (not wire
    bytes), because the zero1 twin gate compares them against the
    analytic parameter footprint."""
    cols = extract_collectives(hlo_text, data_axis, model_axis)
    ops: Dict[str, int] = {}
    structure: Dict[str, int] = {}
    bytes_by_axis: Dict[str, int] = {}
    ag = rs = ar = 0
    wire = 0.0
    for c in cols:
        ops[c.op] = ops.get(c.op, 0) + 1
        structure[c.signature()] = structure.get(c.signature(), 0) + 1
        bytes_by_axis[c.bucket] = int(bytes_by_axis.get(c.bucket, 0)
                                      + c.wire_bytes)
        wire += c.wire_bytes
        if c.dtype in FLOAT_DTYPES:
            if c.op == "all-gather":
                ag += c.payload_bytes
            elif c.op == "reduce-scatter":
                rs += c.payload_bytes
            elif c.op == "all-reduce":
                ar += c.payload_bytes
    return {
        "mesh": f"{data_axis}x{model_axis}",
        "collective_count": len(cols),
        "ops": dict(sorted(ops.items())),
        "structure": dict(sorted(structure.items())),
        "bytes_by_axis": dict(sorted(bytes_by_axis.items())),
        "wire_bytes_per_device": int(wire),
        "all_gather_bytes": int(ag),
        "reduce_scatter_bytes": int(rs),
        "plain_all_reduce_bytes": int(ar),
    }


def hlo_text_of(compiled) -> Optional[str]:
    """Post-SPMD-partitioner HLO text of a compiled program (the only
    stage where collectives exist for auto-sharded jit programs); None
    when the backend exposes neither accessor."""
    try:
        modules = compiled.hlo_modules()
        if modules:
            return "\n".join(m.to_string() for m in modules)
    except Exception as e:  # noqa: BLE001 - accounting must never crash
        log.debug("hlo_modules unavailable: %s", e)
    try:
        return compiled.as_text()
    except Exception as e:  # noqa: BLE001
        log.debug("compiled.as_text unavailable: %s", e)
        return None


def comms_from_compiled(compiled, data_axis: int,
                        model_axis: int) -> Optional[dict]:
    """``summarize_collectives`` over a compiled program's HLO text;
    None when the backend reports no HLO."""
    text = hlo_text_of(compiled)
    if text is None:
        return None
    return summarize_collectives(text, data_axis, model_axis)


class CommsLedger:
    """Per-compiled-program comms entries, persisted per run.

    One entry per program key (the FlopsRegistry/MemoryLedger key
    spelling, so ``comms.json`` certifies the same programs as
    ``flops.json`` and ``memory.json``): the collective summary plus
    provenance and the predicted time-on-wire. ``<train_dir>/comms.json``
    is what perfwatch's sweep-comm series, the doctor and operators
    read back."""

    def __init__(self):
        self._entries: Dict[str, dict] = {}

    def register(self, key: str, summary: Optional[dict],
                 **extra) -> dict:
        entry = dict(summary) if summary else {"comms_source": "none"}
        if summary:
            entry["comms_source"] = "compiled_hlo"
        entry.update(extra)
        self._entries[key] = entry
        return entry

    def get(self, key: str) -> Optional[dict]:
        return self._entries.get(key)

    def keys(self) -> List[str]:
        return sorted(self._entries)

    def to_dict(self) -> dict:
        return {"format": 1, "entries": dict(self._entries)}

    def save(self, train_dir: str) -> Optional[str]:
        """Atomic ``<train_dir>/comms.json`` (tmp + rename, like every
        other run artifact)."""
        try:
            os.makedirs(train_dir, exist_ok=True)
            path = os.path.join(train_dir, LEDGER_FILE)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.to_dict(), f, indent=1)
            os.replace(tmp, path)
            return path
        except OSError as e:
            log.warning("could not write %s: %s", LEDGER_FILE, e)
            return None

    @classmethod
    def load(cls, train_dir: str) -> "CommsLedger":
        ledger = cls()
        try:
            with open(os.path.join(train_dir, LEDGER_FILE)) as f:
                payload = json.load(f)
            ledger._entries.update(payload.get("entries", {}))
        except (OSError, ValueError):
            pass
        return ledger


def predicted_time_on_wire(summary: Optional[dict],
                           device_kind: str) -> Optional[float]:
    """Predicted seconds-on-wire per step: per-device ring-model bytes
    over the chip's ICI bandwidth (:data:`ICI_BYTES_BY_KIND`,
    ``TPU_RESNET_ICI_BYTES`` override). None when either side is
    unknown — an unknown chip reports no number rather than a wrong
    one."""
    bw = ici_bytes_per_chip(device_kind)
    if not bw or not summary:
        return None
    return summary.get("wire_bytes_per_device", 0) / bw


def account_train_step(cfg, mesh, state, base_step,
                       per_replica_bn: bool = False,
                       stage_rows: int = 1, chunk_steps: int = 1,
                       variant: str = "single-step",
                       partitioner=None,
                       flops_per_step: Optional[float] = None,
                       ledger: Optional[CommsLedger] = None,
                       train_dir: Optional[str] = None) -> dict:
    """Measure and register the train step's comms budget for ``cfg``
    on ``mesh``. Called ONCE per run at first dispatch, inside the
    compile window: like the memory ledger this needs a COMPILED
    program (collectives only exist post-SPMD-partitioning) and the AOT
    path shares no cache with the jit dispatch — one extra XLA compile,
    gated by ``train.comms_ledger``, never a per-step cost.

    The probe compiles the program the run's input edge actually
    dispatches (``obs.memory.lower_train_step`` — the shared builder
    the memory accountant uses, donation and partitioner identical), so
    a ``comms.json`` entry can never describe a different program than
    the run executes. ``flops_per_step`` (the MFU accountant's number,
    when it ran) feeds ``predicted_comms_fraction`` = time-on-wire /
    (time-on-wire + peak-compute time) — the gauge that says whether
    the next scaling step is compute- or comms-bound before a pod is
    ever booked."""
    from tpu_resnet.obs.memory import lower_train_step
    from tpu_resnet.obs.mfu import peak_flops_per_chip, train_program_key

    ledger = ledger if ledger is not None else CommsLedger()
    key = train_program_key(cfg, dict(mesh.shape))
    lowered, variant = lower_train_step(
        cfg, mesh, state, base_step, per_replica_bn=per_replica_bn,
        stage_rows=stage_rows, chunk_steps=chunk_steps, variant=variant,
        partitioner=partitioner)
    shape = dict(mesh.shape)
    summary = comms_from_compiled(lowered.compile(),
                                  shape.get("data", 1),
                                  shape.get("model", 1))
    kind = mesh.devices.flat[0].device_kind
    extra = {"program_key": key, "program": variant,
             "device_kind": kind, "n_devices": int(mesh.size),
             "ici_bytes_per_chip": ici_bytes_per_chip(kind)}
    if partitioner is not None:
        extra["partition"] = partitioner.describe()
    t_wire = predicted_time_on_wire(summary, kind)
    if t_wire is not None:
        extra["predicted_time_on_wire_s"] = t_wire
        peak = peak_flops_per_chip(kind)
        if flops_per_step and peak:
            t_compute = flops_per_step / (peak * max(int(mesh.size), 1))
            extra["predicted_comms_fraction"] = round(
                t_wire / (t_wire + t_compute), 4) if (t_wire + t_compute) \
                else 0.0
    entry = ledger.register(key, summary, **extra)
    if train_dir:
        ledger.save(train_dir)
    return entry
