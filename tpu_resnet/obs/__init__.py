"""Unified observability — the subsystem the reference never had.

The reference's visibility into a run was three disconnected channels
(SURVEY.md §5: TensorBoard summaries, a console LoggingTensorHook, and
per-task log files); "is the input pipeline the bottleneck" and "which
pod host is straggling" were answered by grepping logs, if at all. The
MLPerf TPU-pod scaling work (arXiv:1909.09756) and the pjit TPUv4
training report (arXiv:2204.06514) both treat per-step timing
decomposition and pod-level health as *prerequisites* for scaling; this
package provides them as first-class artifacts of every run:

``breakdown``   StepBreakdown — where step time goes between log
                boundaries: ``data_wait`` (blocked in ``next(data_iter)``),
                ``dispatch`` (enqueueing the jitted chunk) and a sampled
                device backlog, plus one-shot ``compile_seconds``.
``spans``       SpanTracer — structured event spans (run, compile,
                checkpoint save/restore, eval pass, profiler trace
                window) appended to ``events.jsonl``.
``manifest``    ``manifest.json`` — resolved config, mesh topology,
                device kinds, process count, package version, git rev —
                written once at startup by the primary process.
``server``      a stdlib-only HTTP telemetry server per host exposing
                ``/healthz`` (liveness + heartbeat age) and ``/metrics``
                (Prometheus text: gauges + fixed-bucket histograms) so
                pods can be scraped and stragglers spotted without
                log-grepping.
``mfu``         first-class FLOPs/MFU accounting: per-device-kind peak
                table, per-compiled-program FLOPs registry (keyed like
                the golden-jaxpr entries), live ``model_flops_per_sec``
                / ``mfu`` gauges.
``memory``      the space twin of ``mfu``: compiled-program HBM ledger
                (``memory.json``, keyed like the FLOPs registry), live
                ``hbm_bytes_*`` gauges from ``device.memory_stats()``,
                OOM forensics (``oom_report.json`` with a live-array
                census) and the per-chip HBM capacity table.
``comms``       the wire twin of ``mfu``/``memory``: compiled-program
                collective summary (op multiset, analytic bytes-on-wire
                per mesh axis from the post-partitioner HLO) persisted
                to ``comms.json`` with the same program keys, predicted
                time-on-wire from the per-chip ICI-bandwidth table and
                a ``predicted_comms_fraction`` gauge.
``trace``       ``tpu_resnet trace-export`` — merge spans, breakdown
                samples, data-engine counters, eval and serve events
                into one Chrome-trace/Perfetto JSON correlated by the
                run's ``run_id``.

Importing this package stays jax-free (jax is imported lazily where a
device sync is needed) so stdlib-only consumers — ``tools/obs_scrape.py``,
the doctor's telemetry check — can use the scrape/parse helpers without
pulling in a backend.
"""

from tpu_resnet.obs import comms, memory, mfu
from tpu_resnet.obs.breakdown import StepBreakdown
from tpu_resnet.obs.manifest import (
    build_manifest,
    ensure_run_id,
    read_run_id,
    write_manifest,
)
from tpu_resnet.obs.server import (
    Histogram,
    TelemetryRegistry,
    TelemetryServer,
    histogram_quantile,
    parse_histograms,
    parse_prometheus,
    read_telemetry_port,
    scrape,
)
from tpu_resnet.obs.spans import SpanTracer

__all__ = [
    "Histogram",
    "StepBreakdown",
    "SpanTracer",
    "TelemetryRegistry",
    "TelemetryServer",
    "build_manifest",
    "comms",
    "ensure_run_id",
    "histogram_quantile",
    "memory",
    "mfu",
    "parse_histograms",
    "parse_prometheus",
    "read_run_id",
    "read_telemetry_port",
    "scrape",
    "write_manifest",
]
