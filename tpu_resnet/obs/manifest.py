"""Run manifest — one ``manifest.json`` per run, written at startup.

The reference scattered run provenance across shell scripts, flag dumps
and whatever the operator remembered to note (SURVEY.md §2.2's results
artifacts are bare CSVs with no config attached); reproducing a run meant
archaeology. The manifest pins everything needed to re-run or audit:

- the fully-resolved config (post-preset, post-overrides),
- mesh topology, device kinds/counts, process count,
- package + python + jax versions, git revision when available,
- hostname, argv and a wall-clock timestamp.

Written once by the primary process (the chief-only rule every other
writer follows, reference resnet_cifar_train.py:337), atomically (tmp +
rename) so a crash mid-write never leaves a torn manifest.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from typing import Optional

SCHEMA_VERSION = 1


def _git_rev() -> Optional[str]:
    """Best-effort git revision of the package checkout; None outside a
    work tree (installed wheel, bundled container)."""
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=here,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, timeout=5)
    except (OSError, subprocess.TimeoutExpired):
        return None
    rev = proc.stdout.strip()
    return rev if proc.returncode == 0 and rev else None


def build_manifest(cfg, mesh) -> dict:
    """Assemble the manifest dict (pure; no filesystem writes)."""
    import jax

    import tpu_resnet

    devices = list(mesh.devices.flat)
    return {
        "schema": SCHEMA_VERSION,
        "created_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": cfg.to_dict(),
        "mesh": {"shape": dict(mesh.shape),
                 "axis_names": list(mesh.axis_names)},
        "devices": {
            "count": len(devices),
            "kinds": sorted({d.device_kind for d in devices}),
            "platform": devices[0].platform if devices else None,
        },
        "processes": {"count": jax.process_count(),
                      "index": jax.process_index()},
        "versions": {
            "tpu_resnet": getattr(tpu_resnet, "__version__", None),
            "python": sys.version.split()[0],
            "jax": jax.__version__,
        },
        "git_rev": _git_rev(),
        "hostname": socket.gethostname(),
        "argv": list(sys.argv),
    }


def write_manifest(train_dir: str, cfg, mesh) -> Optional[str]:
    """Write ``<train_dir>/manifest.json`` (primary process only; atomic).
    Returns the path, or None on a non-primary process."""
    from tpu_resnet import parallel

    if not parallel.is_primary():
        return None
    os.makedirs(train_dir, exist_ok=True)
    path = os.path.join(train_dir, "manifest.json")
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(build_manifest(cfg, mesh), f, indent=1, default=list)
    os.replace(tmp, path)
    return path
