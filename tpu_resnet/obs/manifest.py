"""Run manifest — one ``manifest.json`` per run, written at startup.

The reference scattered run provenance across shell scripts, flag dumps
and whatever the operator remembered to note (SURVEY.md §2.2's results
artifacts are bare CSVs with no config attached); reproducing a run meant
archaeology. The manifest pins everything needed to re-run or audit:

- the fully-resolved config (post-preset, post-overrides),
- mesh topology, device kinds/counts, process count,
- package + python + jax versions, git revision when available,
- hostname, argv and a wall-clock timestamp.

Written once by the primary process (the chief-only rule every other
writer follows, reference resnet_cifar_train.py:337), atomically (tmp +
rename) so a crash mid-write never leaves a torn manifest.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import uuid
from typing import Optional

SCHEMA_VERSION = 2
RUN_ID_FILE = "run_id.json"


def ensure_run_id(train_dir: str, create: bool = True) -> Optional[str]:
    """The run's correlation id — one short hex token shared by every
    process that touches this train_dir (trainer, eval sidecar, serve,
    loadgen, supervise) so their artifacts can be laid on one timeline
    (obs/trace.py) and joined in logs.

    Persisted in ``<train_dir>/run_id.json`` and REUSED across resumes:
    a preempt/resume cycle is one run on one timeline, not three. With
    ``create=False`` (read-only consumers: eval sidecar, serve, tools)
    a missing file returns None instead of minting an id the trainer
    doesn't know about."""
    path = os.path.join(train_dir, RUN_ID_FILE)
    try:
        with open(path) as f:
            rid = json.load(f).get("run_id")
            if rid:
                return str(rid)
    except (OSError, ValueError):
        pass
    if not create:
        return None
    rid = uuid.uuid4().hex[:12]
    try:
        os.makedirs(train_dir, exist_ok=True)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"run_id": rid, "created_at": time.time(),
                       "hostname": socket.gethostname()}, f)
        os.replace(tmp, path)
    except OSError:
        pass  # correlation id is best-effort; the run must not die for it
    return rid


def read_run_id(train_dir: str) -> Optional[str]:
    """Read-only run_id lookup (sidecars/tools); None when the trainer
    hasn't created one."""
    return ensure_run_id(train_dir, create=False)


def _git_rev() -> Optional[str]:
    """Best-effort git revision of the package checkout; None outside a
    work tree (installed wheel, bundled container)."""
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=here,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, timeout=5)
    except (OSError, subprocess.TimeoutExpired):
        return None
    rev = proc.stdout.strip()
    return rev if proc.returncode == 0 and rev else None


def build_manifest(cfg, mesh, run_id: Optional[str] = None,
                   extra: Optional[dict] = None) -> dict:
    """Assemble the manifest dict (pure; no filesystem writes).
    ``extra`` top-level entries are merged in — e.g. the elastic-resume
    ``topology_change`` record (resilience/elastic.py), so a capacity
    reshape is auditable from the manifest alone."""
    import jax

    import tpu_resnet

    devices = list(mesh.devices.flat)
    manifest = {
        "schema": SCHEMA_VERSION,
        "run_id": run_id,
        "created_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": cfg.to_dict(),
        "mesh": {"shape": dict(mesh.shape),
                 "axis_names": list(mesh.axis_names)},
        "devices": {
            "count": len(devices),
            "kinds": sorted({d.device_kind for d in devices}),
            "platform": devices[0].platform if devices else None,
        },
        "processes": {"count": jax.process_count(),
                      "index": jax.process_index()},
        "versions": {
            "tpu_resnet": getattr(tpu_resnet, "__version__", None),
            "python": sys.version.split()[0],
            "jax": jax.__version__,
        },
        "git_rev": _git_rev(),
        "hostname": socket.gethostname(),
        "argv": list(sys.argv),
    }
    if extra:
        manifest.update(extra)
    return manifest


def write_manifest(train_dir: str, cfg, mesh,
                   run_id: Optional[str] = None,
                   extra: Optional[dict] = None) -> Optional[str]:
    """Write ``<train_dir>/manifest.json`` (primary process only; atomic).
    Returns the path, or None on a non-primary process."""
    from tpu_resnet import parallel

    if not parallel.is_primary():
        return None
    os.makedirs(train_dir, exist_ok=True)
    path = os.path.join(train_dir, "manifest.json")
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(build_manifest(cfg, mesh, run_id=run_id, extra=extra),
                  f, indent=1, default=list)
    os.replace(tmp, path)
    return path
