from tpu_resnet.evaluation.evaluator import (
    build_eval_step,
    evaluate,
    run_eval_pass,
)

__all__ = ["build_eval_step", "evaluate", "run_eval_pass"]
