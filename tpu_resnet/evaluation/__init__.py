from tpu_resnet.evaluation.evaluator import (
    build_eval_step,
    evaluate,
    run_eval_pass,
    train_and_eval,
)

__all__ = ["build_eval_step", "evaluate", "run_eval_pass", "train_and_eval"]
