"""Continuous checkpoint-polling evaluator — the reference's eval sidecar
(reference resnet_cifar_eval.py:85-143, resnet_imagenet_eval.py:169-230)
rebuilt: poll the train dir for a new checkpoint, restore, run the eval
split, write ``Precision`` / ``Best_Precision`` against the restored step,
sleep ``eval_interval_secs`` (60 s), repeat; ``eval_once`` evaluates the
latest checkpoint and exits (resnet_cifar_eval.py:140-143).

Deviations from the reference, on purpose:
- the full test split is evaluated (the reference samples 50×100 = 5000 of
  CIFAR's 10000 test images, resnet_cifar_eval.py:114-117);
- ``best_precision`` is persisted to ``best_precision.json`` so evaluator
  restarts don't reset the best curve (the reference loses it,
  README.md:33).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_resnet import parallel
from tpu_resnet.config import RunConfig
from tpu_resnet.data import augment as aug_lib
from tpu_resnet.models import build_model
from tpu_resnet.train import schedule as sched_lib
from tpu_resnet.train.checkpoint import (CheckpointManager, latest_step_in,
                                         partitioned_template,
                                         restore_with_retry)
from tpu_resnet.train.metrics_io import MetricsWriter
from tpu_resnet.train.state import init_state
from tpu_resnet.train.step import make_eval_step

log = logging.getLogger("tpu_resnet")


def _mesh_eval_batch(cfg: RunConfig, mesh) -> int:
    """Round the configured eval batch (reference default 100,
    resnet_cifar_eval.py) up to a multiple of lcm(data axis, process
    count); padded slots are masked out, so the rounding never changes
    results."""
    import math

    n_data = mesh.shape["data"]
    unit = n_data * jax.process_count() // math.gcd(n_data,
                                                    jax.process_count())
    bs = cfg.train.eval_batch_size
    return ((bs + unit - 1) // unit) * unit


def run_eval_pass(cfg: RunConfig, state, mesh, eval_step_fn
                  ) -> Tuple[float, float, int]:
    """One full pass over the eval split → (precision, mean_loss, count).

    Multi-host capable (the reference's eval sidecar is single-node,
    resnet_imagenet_eval.py:83-165): each process streams its own stripe
    of the split as *local* batches, the global batch is assembled with
    ``make_array_from_process_local_data``, and the jitted eval step's
    globally-reduced ``valid`` count doubles as the lockstep termination
    signal — stripes may differ in length, so an exhausted process keeps
    feeding all-padding batches, and every process stops after the first
    round whose global valid count is zero. No cross-host side channel is
    needed; the mesh collective IS the coordination.
    """
    import tpu_resnet.data as data_lib
    from tpu_resnet.data import pipeline

    sharding = parallel.batch_sharding(mesh)
    global_batch = _mesh_eval_batch(cfg, mesh)
    pc = jax.process_count()
    local_batch = global_batch // pc
    size = cfg.data.resolved_image_size
    pad_img = np.zeros((local_batch, size, size, 3), np.uint8)
    pad_lab = np.full((local_batch,), -1, np.int32)

    it = iter(data_lib.eval_split_batches(cfg.data, local_batch))
    correct = loss_sum = count = 0
    try:
        while True:
            nxt = next(it, None)
            img, lab = nxt if nxt is not None else (pad_img, pad_lab)
            gi, gl = pipeline.to_global_arrays((img, lab), sharding)
            c, ls, n = eval_step_fn(state, gi, gl)
            n = int(n)  # global valid count — identical on every process
            if n == 0:
                break
            correct += int(c)
            loss_sum += float(ls)
            count += n
    finally:
        # data.engine=process hands back a HostDataEngine: release its
        # workers and unlink the shared-memory ring even when the pass
        # dies mid-split (it also auto-closes at clean exhaustion).
        close = getattr(it, "close", None)
        if close is not None:
            close()
    return correct / max(count, 1), loss_sum / max(count, 1), count


def build_eval_step(cfg: RunConfig, mesh, state_sharding=None,
                    registry=None, state_template=None):
    """``state_sharding`` (a TrainState-shaped sharding tree, e.g. from
    the partitioned restore template) lets the eval step accept the
    run's partition layout directly — a zero1 state's sharded optimizer
    slots ride through untouched (eval reads only params/batch_stats,
    which every partition mode keeps replicated). None = the historical
    fully-replicated signature.

    ``registry`` (programs.ProgramRegistry) routes the program through
    the persistent AOT executable cache when enabled — a restarted eval
    sidecar re-reaches its compiled pass without re-paying XLA.
    ``state_template`` (the abstract restore template) supplies the
    state avals the cache path lowers over; both default to the
    historical plain-jit behavior."""
    model = build_model(cfg)
    _, eval_pre = aug_lib.get_augment_fns(cfg.data.dataset)
    step = make_eval_step(model, cfg.data.num_classes, eval_pre)
    jitted = jax.jit(step, in_shardings=(
        state_sharding if state_sharding is not None
        else parallel.replicated(mesh), parallel.batch_sharding(mesh),
        parallel.batch_sharding(mesh)))
    if registry is not None and registry.cache_enabled \
            and state_template is not None:
        gb = _mesh_eval_batch(cfg, mesh)
        size = cfg.data.resolved_image_size
        bsh = parallel.batch_sharding(mesh)
        jitted, _ = registry.wrap(
            registry.key("eval", batch=gb), jitted,
            (state_template,
             jax.ShapeDtypeStruct((gb, size, size, 3), "uint8",
                                  sharding=bsh),
             jax.ShapeDtypeStruct((gb,), "int32", sharding=bsh)))
    return model, jitted


def _template_state(cfg: RunConfig, model, mesh):
    """CONCRETE replicated state (multihost smoke workers run an eval
    pass on it directly); the evaluator's restore path uses the
    allocation-free abstract ``checkpoint.partitioned_template``."""
    schedule = sched_lib.build_schedule(cfg.optim, cfg.train)
    size = cfg.data.resolved_image_size
    state = init_state(model, cfg.optim, schedule, jax.random.PRNGKey(0),
                       jnp.zeros((1, size, size, 3)))
    return jax.device_put(state, parallel.replicated(mesh))


# Back-compat alias: the restore-retry logic moved to
# train/checkpoint.py so the serve hot-reload path shares it verbatim.
_restore_with_retry = restore_with_retry


def evaluate(cfg: RunConfig, mesh=None, stop_event=None) -> Optional[float]:
    """Continuous (or once) evaluation; returns last precision.

    ``stop_event`` (a ``threading.Event``) ends the polling loop early —
    used by train_and_eval to stop the in-process sidecar when training
    finishes (the reference runs the sidecar as a separate container/node,
    start-resnet-imagenet-main.sh tail, and kills it with stop.sh)."""
    if mesh is None:
        mesh = parallel.create_mesh(cfg.mesh)
    # Abstract restore template in the run's partition layout
    # (checkpoint.partitioned_template): no device allocation for the
    # template, and a zero1 checkpoint restores straight into its
    # optimizer-slot shards. The eval step accepts that same layout.
    template = partitioned_template(cfg, mesh)
    from tpu_resnet import programs
    model, eval_step_fn = build_eval_step(
        cfg, mesh,
        state_sharding=jax.tree_util.tree_map(lambda s: s.sharding,
                                              template),
        registry=programs.ProgramRegistry(cfg, mesh, context="eval"),
        state_template=template)

    eval_dir = os.path.join(cfg.train.train_dir, "eval")
    metrics = MetricsWriter(eval_dir, enabled=parallel.is_primary())
    # Eval-pass spans on the sidecar's own timeline file (the trainer owns
    # <train_dir>/events.jsonl; the evaluator may be a separate process).
    # The train run's run_id is stamped on every span so trace-export can
    # correlate the sidecar lane with the trainer it is polling; a
    # sidecar started before the trainer re-reads it on first restore.
    from tpu_resnet import obs
    run_id = obs.read_run_id(cfg.train.train_dir)
    spans = obs.SpanTracer(eval_dir, enabled=parallel.is_primary(),
                           run_id=run_id)
    if run_id:
        log.info("eval sidecar polling %s (train run_id=%s)",
                 cfg.train.train_dir, run_id)
    best_file = os.path.join(eval_dir, "best_precision.json")
    best = 0.0
    if os.path.exists(best_file):  # survive evaluator restarts (README.md:33)
        with open(best_file) as f:
            best = json.load(f)["best_precision"]

    ckpt = CheckpointManager(cfg.train.train_dir,
                             keep=cfg.train.keep_checkpoints)
    def _wait() -> bool:
        """Sleep one poll interval; True = keep going, False = stop."""
        if stop_event is not None:
            return not stop_event.wait(cfg.train.eval_interval_secs)
        time.sleep(cfg.train.eval_interval_secs)
        return True

    last_seen = None
    precision = None
    try:
        while True:
            step = latest_step_in(cfg.train.train_dir)
            if step is None:
                # Checkpoint not there yet — keep polling like the reference
                # (resnet_cifar_eval.py:100-109).
                log.info("no checkpoint yet in %s; sleeping",
                         cfg.train.train_dir)
                if cfg.train.eval_once:
                    return None
                if not _wait():
                    break
                continue
            if step != last_seen:
                if spans.run_id is None:
                    # Trainer started after us: pick up its run_id now so
                    # the remaining spans correlate.
                    spans.run_id = run_id = obs.read_run_id(
                        cfg.train.train_dir)
                    if run_id:
                        log.info("eval sidecar now polling train "
                                 "run_id=%s", run_id)
                state = restore_with_retry(
                    ckpt, template, step,
                    retries=cfg.resilience.eval_restore_retries,
                    backoff_sec=cfg.resilience.eval_restore_backoff_sec)
                if state is None:
                    # Skip-and-log, never crash the sidecar: mark the step
                    # seen so the poll doesn't spin on it; the next
                    # committed checkpoint evaluates normally.
                    log.error("skipping eval of checkpoint step %d — "
                              "restore failed repeatedly", step)
                    spans.event("eval_restore_failed", step=step)
                    last_seen = step
                    if cfg.train.eval_once:
                        break
                    if not _wait():
                        break
                    continue
                t0 = time.perf_counter()
                with spans.span("eval_pass", step=step) as span_attrs:
                    precision, loss, count = run_eval_pass(cfg, state, mesh,
                                                           eval_step_fn)
                    span_attrs.update(precision=round(precision, 6),
                                      examples=count)
                dt = time.perf_counter() - t0
                best = max(best, precision)
                if parallel.is_primary():
                    os.makedirs(eval_dir, exist_ok=True)
                    with open(best_file, "w") as f:
                        json.dump({"best_precision": best, "step": step}, f)
                metrics.write(step, {"Precision": precision,
                                     "Best_Precision": best,
                                     "eval_loss": loss})
                log.info("eval @ step %d: precision %.4f best %.4f "
                         "loss %.4f (%.1fs, %d examples)", step, precision,
                         best, loss, dt, count)
                last_seen = step
            if cfg.train.eval_once:
                break
            if not _wait():
                break
    finally:
        # Early returns (eval_once with no checkpoint yet) and torn-
        # checkpoint exceptions must still release the sidecar's jsonl
        # handles — both closers are idempotent.
        spans.close()
        metrics.close()
    return precision


def _last_eval(train_dir: str) -> Tuple[Optional[int], Optional[float]]:
    """(step, precision) of the newest eval record in <train_dir>/eval."""
    path = os.path.join(train_dir, "eval", "metrics.jsonl")
    step = precision = None
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail write from a live sidecar
                if "Precision" in rec:
                    step, precision = rec.get("step"), rec["Precision"]
    return step, precision


def train_and_eval(cfg: RunConfig, mesh=None) -> Optional[float]:
    """Train with an in-process eval sidecar — the reference's
    ``--mode=train_and_eval`` (resnet_cifar_main.py main dispatch; its
    ImageNet variant is broken, resnet_imagenet_main.py:528-529 calls
    train with an undefined ``server`` — SURVEY.md §2.1). Here both share
    one process and mesh: the sidecar thread polls/evaluates between
    training dispatches, and a final eval-once covers the last checkpoint
    when the sidecar didn't. Returns the final precision.

    Single-process only: with multiple processes, each host's sidecar
    would enqueue collectives interleaved differently with the training
    stream and deadlock the mesh — multi-host runs launch the evaluator
    as its own process/job like the reference's tf-eval container
    (start-resnet-imagenet-main.sh tail, run_dist_train_eval_daint.sh).
    """
    import copy
    import threading

    from tpu_resnet import parallel as par
    from tpu_resnet.train.loop import train as train_fn

    if jax.process_count() != 1:
        raise ValueError(
            "train_and_eval is single-process; in multi-host runs start "
            "`tpu_resnet eval` as a separate process/job instead")
    if mesh is None:
        mesh = par.create_mesh(cfg.mesh)

    eval_cfg = copy.deepcopy(cfg)
    eval_cfg.train.eval_once = False
    stop = threading.Event()
    sidecar = threading.Thread(
        target=evaluate, args=(eval_cfg,),
        kwargs=dict(mesh=mesh, stop_event=stop), daemon=True)
    sidecar.start()
    try:
        train_fn(cfg, mesh=mesh)
    finally:
        stop.set()
    sidecar.join(timeout=600)
    if sidecar.is_alive():
        log.warning("eval sidecar still mid-pass after 600s; skipping the "
                    "final eval to avoid concurrent device work")
        return _last_eval(cfg.train.train_dir)[1]

    seen_step, seen_precision = _last_eval(cfg.train.train_dir)
    if seen_step is not None and seen_step == latest_step_in(
            cfg.train.train_dir):
        return seen_precision  # sidecar already covered the last checkpoint

    final_cfg = copy.deepcopy(cfg)
    final_cfg.train.eval_once = True
    return evaluate(final_cfg, mesh=mesh)
