"""Actuation half of the autopilot — every side effect lives here.

The policy (policy.py) resolves *what* should happen; this module makes
it happen through contracts that already exist, none invented for the
autoscaler:

- **scale-up** spawns a replica from the ``autopilot.spawn_cmd``
  template, by default wrapped in ``tools/supervise.py --stop-codes 3``
  (crashes restart with decorrelated-jitter backoff; the PR 10
  colocation-admission verdict stays terminal). The child announces
  itself via ``serve.replica_name={name}`` discovery and the router's
  watch-discovery probation admits it on merit. Exit 3 ("no capacity
  here") surfaces as an ``admission_denied`` event — a policy input
  that arms the scale-up backoff, not a crash.
- **scale-down** drains via the router's ``/admin/drain`` rolling
  contract (``serve.router.request_drain``): quiesce in-flight, then
  SIGTERM — zero failed client requests by construction.
- **capacity handoff**: draining below peak frees device memory; the
  actuator grants it to a colocated trainer by atomically writing
  ``capacity_lease.json`` and revokes the lease BEFORE the next
  scale-up spawn, so the trainer and the new replica never both claim
  the headroom colocation admission meters.

Single-threaded by design: only the controller loop calls in here, so
there is no lock and nothing for a lock to protect — the controller's
telemetry threads read the registry, never the actuator.
Pure host code: stdlib only, no jax (jaxlint host-isolation scope).
"""

from __future__ import annotations

import json
import logging
import os
import shlex
import subprocess
import sys
import time
from typing import List, Optional

from tpu_resnet.config import RunConfig
from tpu_resnet.resilience import exitcodes

log = logging.getLogger("tpu_resnet")

CAPACITY_LEASE_FILE = "capacity_lease.json"


def _supervise_path() -> Optional[str]:
    """tools/supervise.py relative to the repo checkout; None when the
    package runs without the tools tree (spawns then go direct)."""
    import tpu_resnet

    root = os.path.dirname(os.path.dirname(
        os.path.abspath(tpu_resnet.__file__)))
    path = os.path.join(root, "tools", "supervise.py")
    return path if os.path.exists(path) else None


def read_capacity_lease(directory: str) -> Optional[dict]:
    try:
        with open(os.path.join(directory, CAPACITY_LEASE_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class _Spawn:
    """One launched replica: the Popen handle plus admission state."""

    def __init__(self, name: str, proc: subprocess.Popen,
                 started_wall: float, log_path: str):
        self.name = name
        self.proc = proc
        self.started_wall = started_wall
        self.log_path = log_path
        self.admitted = False   # seen healthy in a router snapshot
        self.done = False       # process reaped (any reason)


class Actuator:
    def __init__(self, cfg: RunConfig, directory: str, spans,
                 clock=time.time):
        self.cfg = cfg
        self.directory = directory
        self.spans = spans
        self._clock = clock
        self._spawns: List[_Spawn] = []
        self._ordinal = 0
        self._lease_granted = False

    # ------------------------------------------------------- spawning
    @property
    def observe_only(self) -> bool:
        """No spawn template = decisions are ledgered and gauged but
        nothing is spawned or drained (the dry-run deployment mode and
        the unit-test default)."""
        return not self.cfg.autopilot.spawn_cmd.strip()

    def pending_count(self) -> int:
        return sum(1 for s in self._spawns
                   if not s.admitted and not s.done)

    def live_spawn_names(self) -> List[str]:
        return [s.name for s in self._spawns if not s.done]

    def _build_argv(self, name: str, ordinal: int) -> List[str]:
        tokens = shlex.split(self.cfg.autopilot.spawn_cmd)
        argv = [t.replace("{python}", sys.executable)
                 .replace("{name}", name)
                 .replace("{i}", str(ordinal)) for t in tokens]
        if self.cfg.autopilot.spawn_supervised:
            sup = _supervise_path()
            if sup is not None:
                # --stop-codes 3: the colocation-admission denial ends
                # supervision and becomes the wrapper's own exit code,
                # which poll() reads as the policy input.
                argv = [sys.executable, sup, "--max-restarts", "2",
                        "--backoff-base", "0.5", "--stop-codes",
                        str(exitcodes.NO_CAPACITY), "--"] + argv
            else:  # pragma: no cover - installed-package layout
                log.warning("autopilot: tools/supervise.py not found; "
                            "spawning unsupervised")
        return argv

    def spawn_replica(self) -> Optional[dict]:
        """Launch one replica; returns {"name", "pid"} or None in
        observe-only mode."""
        if self.observe_only:
            return None
        name = f"{self.cfg.autopilot.replica_prefix}{self._ordinal}"
        argv = self._build_argv(name, self._ordinal)
        self._ordinal += 1
        log_path = os.path.join(self.directory,
                                f"autopilot_spawn_{name}.log")
        logf = open(log_path, "ab")
        try:
            proc = subprocess.Popen(argv, stdout=logf,
                                    stderr=subprocess.STDOUT)
        finally:
            logf.close()  # the child holds its own fd now
        spawn = _Spawn(name, proc, float(self._clock()), log_path)
        self._spawns.append(spawn)
        log.info("autopilot: spawned replica %s (pid %d): %s", name,
                 proc.pid, " ".join(argv))
        return {"name": name, "pid": proc.pid}

    def poll(self, snapshot) -> List[dict]:
        """Advance every in-flight spawn against the newest snapshot;
        returns lifecycle events for the controller to ledger/count:
        ``replica_ready`` (with the spawn->healthy latency the autoscale
        scenarios gate), ``admission_denied`` (exit 3 — arms the policy
        backoff), ``spawn_failed`` (crash or blown ready budget)."""
        events: List[dict] = []
        wall = float(getattr(snapshot, "wall", self._clock()))
        healthy_names = {
            r.get("name") for r in getattr(snapshot, "replicas", ())
            if r.get("state") == "closed" and not r.get("draining")
            and not r.get("pending")}
        for s in self._spawns:
            if s.done:
                continue
            rc = s.proc.poll()
            if not s.admitted and s.name in healthy_names:
                s.admitted = True
                events.append({
                    "kind": "replica_ready", "name": s.name,
                    "latency_ms":
                        round((wall - s.started_wall) * 1000.0, 1)})
                continue
            if rc is None:
                if (not s.admitted and wall - s.started_wall
                        > self.cfg.autopilot.ready_timeout_secs):
                    s.proc.terminate()
                    s.done = True
                    events.append({"kind": "spawn_failed",
                                   "name": s.name,
                                   "reason": "ready_timeout",
                                   "log": s.log_path})
                continue
            s.done = True
            if rc == exitcodes.NO_CAPACITY:
                events.append({"kind": "admission_denied",
                               "name": s.name, "rc": rc})
            elif rc == 0:
                # Drained (scale-down) or clean shutdown: expected end
                # of life, nothing to alarm about.
                events.append({"kind": "replica_gone", "name": s.name,
                               "rc": 0})
            else:
                events.append({"kind": "spawn_failed", "name": s.name,
                               "reason": f"exit {rc}", "rc": rc,
                               "log": s.log_path})
        return events

    # ------------------------------------------------------- draining
    def pick_drain_target(self, snapshot) -> Optional[str]:
        """LIFO over autopilot-owned replicas first (drain what we
        added, newest first), else the lexicographically-last healthy
        externally-managed replica."""
        healthy = [r.get("name")
                   for r in getattr(snapshot, "replicas", ())
                   if r.get("state") == "closed"
                   and not r.get("draining") and not r.get("pending")]
        owned = [s.name for s in self._spawns
                 if not s.done and s.name in healthy]
        if owned:
            return owned[-1]
        return sorted(healthy)[-1] if healthy else None

    def drain(self, snapshot, name: str) -> dict:
        """Rolling drain through the router's admin contract."""
        from tpu_resnet.serve.router import request_drain

        port = getattr(snapshot, "router_port", None)
        if port is None:
            return {"ok": False, "error": "router port unknown"}
        return request_drain(f"http://127.0.0.1:{port}", name,
                             timeout=self.cfg.route.drain_timeout_secs
                             + 10.0)

    # ------------------------------------------------ capacity lease
    @property
    def lease_granted(self) -> bool:
        return self._lease_granted

    def _write_lease(self, state: str, freed: int) -> None:
        path = os.path.join(self.directory, CAPACITY_LEASE_FILE)
        tmp = path + f".tmp.{os.getpid()}"
        body = {"state": state, "holder": "trainer",
                "freed_replicas": int(freed),
                "wall": round(float(self._clock()), 3)}
        try:
            with open(tmp, "w") as f:
                json.dump(body, f, indent=2)
            os.replace(tmp, path)
        except OSError as e:
            log.warning("autopilot: capacity lease write failed: %s", e)

    def grant_lease(self, freed: int) -> None:
        """Scale-down freed capacity: hand it to the colocated trainer
        (docs/AUTOPILOT.md "Capacity handoff")."""
        if not self.cfg.autopilot.capacity_lease:
            return
        self._write_lease("granted", freed)
        self._lease_granted = True

    def revoke_lease(self) -> None:
        """Reclaim BEFORE a spawn: the new replica's colocation
        admission must see the headroom the trainer was lent."""
        if not self._lease_granted:
            return
        self._write_lease("revoked", 0)
        self._lease_granted = False

    # ------------------------------------------------------ lifecycle
    def close(self, timeout: float = 10.0) -> None:
        """The autopilot owns the replicas it spawned: SIGTERM each
        live child (the serve drain contract exits 0) and reap — a
        scenario's conductor only knows ITS children, so leaking
        grandchildren here would outlive the drill."""
        for s in self._spawns:
            if s.done or s.proc.poll() is not None:
                continue
            s.proc.terminate()
        for s in self._spawns:
            if s.done:
                continue
            try:
                s.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:  # pragma: no cover
                s.proc.kill()
                s.proc.wait(timeout=5.0)
            s.done = True
