"""The autopilot control loop: snapshot → decide → actuate → ledger.

One loop thread owns the whole round: it collects a
:class:`~tpu_resnet.autopilot.signals.SignalSnapshot` (router /info +
fleetmon snapshot, no lock held), folds the actuator's spawn lifecycle
events into the policy state (a colocation-admission denial arms the
scale-up backoff; a replica turning healthy in the router closes the
scale-up-latency stopwatch), runs the pure policy, actuates, and then
writes three artifacts that can never disagree because they come from
the same round record:

- ``autopilot_events.jsonl`` — a span ledger with EVERY decision (holds
  included, with the band/streak/cooldown reason) plus each actuation
  and lifecycle event; trace-export renders it as its own lane.
- ``autopilot_*`` gauges on the controller's own telemetry port
  (AUTOPILOT_GAUGES, obs/server.py), announced in ``autopilot.json``.
- ``autopilot_status.json`` — the latest round as one atomic file
  (target, counters, policy state), the thing a scenario assertion or
  an operator's ``cat`` reads.

Concurrency shape (the PR 13 engine gates this file clean, no pragma):
the single lock guards in-memory state only — counters, policy state,
the integrators; every scrape, spawn, drain, and file write happens
with no lock held, and teardown is stop-Event + join before any writer
closes. The actuator is only ever touched from the loop thread.
Pure host code: stdlib only, no jax (jaxlint host-isolation scope).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Callable, Optional

from tpu_resnet.autopilot import signals
from tpu_resnet.autopilot.actuator import Actuator
from tpu_resnet.autopilot.policy import (Decision, PolicyState, decide,
                                         effective_slo,
                                         note_admission_denied)
from tpu_resnet.config import RunConfig
from tpu_resnet.obs.manifest import read_run_id
from tpu_resnet.obs.server import AUTOPILOT_GAUGES, TelemetryRegistry
from tpu_resnet.obs.spans import SpanTracer
from tpu_resnet.obs.trace import AUTOPILOT_EVENTS_FILE

log = logging.getLogger("tpu_resnet")

AUTOPILOT_DISCOVERY = "autopilot.json"
AUTOPILOT_STATUS_FILE = "autopilot_status.json"


class AutopilotController:
    """Drivable in-process (tests call :meth:`run_round` directly, with
    an injected ``collect_fn``/``actuator``) or as the ``tpu_resnet
    autopilot`` process (cli.py)."""

    def __init__(self, cfg: RunConfig,
                 registry: Optional[TelemetryRegistry] = None,
                 collect_fn: Optional[Callable[[], object]] = None,
                 actuator: Optional[Actuator] = None,
                 clock=time.time):
        self.cfg = cfg
        self.directory = (cfg.autopilot.discover_dir
                          or cfg.train.train_dir)
        if not self.directory:
            raise ValueError("autopilot needs autopilot.discover_dir "
                             "or train.train_dir")
        os.makedirs(self.directory, exist_ok=True)
        self._clock = clock
        self._collect = collect_fn if collect_fn is not None else (
            lambda: signals.collect(
                self.directory,
                timeout=cfg.autopilot.scrape_timeout_secs,
                now=clock))
        self.registry = registry if registry is not None else \
            TelemetryRegistry(gauges=AUTOPILOT_GAUGES)
        self.registry.mark_unhealthy("starting: no control round yet")
        self.run_id = read_run_id(self.directory)
        self.spans = SpanTracer(self.directory,
                                filename=AUTOPILOT_EVENTS_FILE,
                                run_id=self.run_id)
        self.actuator = actuator if actuator is not None else \
            Actuator(cfg, self.directory, self.spans, clock=clock)

        self._lock = threading.Lock()   # in-memory state ONLY
        self._state = PolicyState()
        self._target: Optional[int] = None
        self._last: Optional[Decision] = None
        self._last_wall: Optional[float] = None
        self._counters = dict(rounds=0, signal_errors=0, scale_ups=0,
                              scale_downs=0, holds=0, spawns=0,
                              spawn_failures=0, admission_denied=0,
                              drains=0)
        self._slo_violation_s = 0.0
        self._replica_s = 0.0
        self._scale_up_latency_ms = 0.0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="tpu-resnet-autopilot",
                                        daemon=True)

    # --------------------------------------------------------- one round
    def run_round(self) -> Decision:
        """One full control round; callable directly from tests."""
        cfg = self.cfg.autopilot
        snap = self._collect()                      # I/O, no lock
        # poll() BEFORE stamping replicas_pending: a spawn that is
        # healthy in THIS snapshot must not also count as pending, or
        # current = healthy + pending double-counts it for one round
        # and the above_max bound (which rightly bypasses cooldowns)
        # drains the replica the moment it is admitted — a flap loop.
        lifecycle = self.actuator.poll(snap)        # proc I/O, no lock
        snap = dataclasses.replace(
            snap, replicas_pending=self.actuator.pending_count())

        denied = [e for e in lifecycle
                  if e["kind"] == "admission_denied"]
        ready = [e for e in lifecycle if e["kind"] == "replica_ready"]
        failed = [e for e in lifecycle if e["kind"] == "spawn_failed"]

        with self._lock:
            state = self._state
            for _ in denied:
                state = note_admission_denied(state, snap.wall, cfg)
            decision, state = decide(snap, cfg, state)
            self._state = state
            c = self._counters
            c["rounds"] += 1
            if not snap.ok:
                c["signal_errors"] += 1
            c["admission_denied"] += len(denied)
            c["spawn_failures"] += len(failed)
            if ready:
                self._scale_up_latency_ms = ready[-1]["latency_ms"]
            key = {"scale_up": "scale_ups", "scale_down": "scale_downs",
                   "hold": "holds"}[decision.action]
            c[key] += 1
            if decision.target >= 0:
                self._target = decision.target
            # Integrators ride snapshot time, so a replayed trace
            # integrates identically.
            slo = effective_slo(snap, cfg) if snap.ok else 0.0
            if self._last_wall is not None and snap.ok:
                dt = max(0.0, snap.wall - self._last_wall)
                self._replica_s += snap.replicas_healthy * dt
                if (slo > 0 and snap.p99_ms is not None
                        and snap.p99_ms > slo):
                    self._slo_violation_s += dt
            if snap.ok:
                self._last_wall = snap.wall
            self._last = decision

        # ---- actuate + ledger: all I/O, no lock held ----
        for ev in lifecycle:
            self.spans.event(f"autopilot_{ev['kind']}",
                             **{k: v for k, v in ev.items()
                                if k != "kind"})
        self.spans.event(
            "autopilot_decision", action=decision.action,
            current=decision.current, target=decision.target,
            step=decision.step, reason=decision.reason,
            pressure=decision.pressure, ok=snap.ok,
            p99_ms=snap.p99_ms, slo_ms=effective_slo(snap, cfg),
            replicas_healthy=snap.replicas_healthy,
            replicas_pending=snap.replicas_pending,
            queue_depth=snap.queue_depth, shed_total=snap.shed_total,
            burn_fast=snap.burn_fast)

        if decision.action == "scale_up" and not self.actuator.observe_only:
            if self.actuator.lease_granted:
                # Reclaim the trainer's lease BEFORE the spawn: the new
                # replica's colocation admission must see the headroom.
                self.actuator.revoke_lease()
                self.spans.event("autopilot_capacity_revoke")
            spawned = 0
            for _ in range(decision.step):
                rec = self.actuator.spawn_replica()
                if rec is not None:
                    spawned += 1
                    self.spans.event("autopilot_spawn",
                                     name=rec["name"],
                                     pid_target=rec["pid"],
                                     reason=decision.reason)
            with self._lock:
                self._counters["spawns"] += spawned
        elif decision.action == "scale_down" \
                and not self.actuator.observe_only:
            drained = 0
            for _ in range(-decision.step):
                name = self.actuator.pick_drain_target(snap)
                if name is None:
                    break
                result = self.actuator.drain(snap, name)
                self.spans.event("autopilot_drain", name=name,
                                 ok=bool(result.get("ok")),
                                 error=result.get("error"))
                if result.get("ok"):
                    drained += 1
            if drained:
                self.actuator.grant_lease(drained)
                self.spans.event("autopilot_capacity_grant",
                                 freed_replicas=drained)
            with self._lock:
                self._counters["drains"] += drained

        self._publish(snap, decision)
        self._write_status(snap, decision)
        return decision

    # ------------------------------------------------------- publishing
    def _publish(self, snap, decision: Decision) -> None:
        with self._lock:
            c = dict(self._counters)
            target = self._target
            slo_violation = self._slo_violation_s
            replica_s = self._replica_s
            latency = self._scale_up_latency_ms
        util = (snap.requests_ok / replica_s) if replica_s > 0 else 0.0
        self.registry.update({
            "autopilot_rounds_total": c["rounds"],
            "autopilot_signal_errors_total": c["signal_errors"],
            "autopilot_scale_ups_total": c["scale_ups"],
            "autopilot_scale_downs_total": c["scale_downs"],
            "autopilot_holds_total": c["holds"],
            "autopilot_spawns_total": c["spawns"],
            "autopilot_spawn_failures_total": c["spawn_failures"],
            "autopilot_admission_denied_total": c["admission_denied"],
            "autopilot_drains_total": c["drains"],
            "autopilot_target_replicas":
                float(target if target is not None else -1),
            "autopilot_replicas_total": snap.replicas_total,
            "autopilot_replicas_healthy": snap.replicas_healthy,
            "autopilot_p99_ms": snap.p99_ms or 0.0,
            "autopilot_slo_ms": effective_slo(snap, self.cfg.autopilot),
            "autopilot_burn_rate_fast": snap.burn_fast or 0.0,
            "autopilot_scale_up_latency_ms": latency,
            "autopilot_slo_violation_seconds": round(slo_violation, 3),
            "autopilot_replica_seconds": round(replica_s, 3),
            "autopilot_utilization": round(util, 4),
            "autopilot_capacity_granted":
                1.0 if self.actuator.lease_granted else 0.0,
        })
        self.registry.heartbeat(c["rounds"])
        if snap.ok:
            self.registry.clear_unhealthy()
        else:
            self.registry.mark_unhealthy("; ".join(snap.errors)
                                         or "no signals")

    def _write_status(self, snap, decision: Decision) -> None:
        """Atomic latest-round record (the scenario-assertion and
        operator surface). Single writer: the loop thread."""
        status = self.status()
        status["decision"] = decision.to_dict()
        status["snapshot_ok"] = snap.ok
        path = os.path.join(self.directory, AUTOPILOT_STATUS_FILE)
        tmp = path + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(status, f, indent=2)
            os.replace(tmp, path)
        except OSError as e:  # pragma: no cover - fs-specific
            log.warning("autopilot: status write failed: %s", e)

    def status(self) -> dict:
        """Counters + policy state, thread-safe read."""
        with self._lock:
            return {"target": self._target,
                    "counters": dict(self._counters),
                    "state": self._state.to_dict(),
                    "slo_violation_seconds":
                        round(self._slo_violation_s, 3),
                    "replica_seconds": round(self._replica_s, 3),
                    "scale_up_latency_ms": self._scale_up_latency_ms,
                    "last_decision": (self._last.to_dict()
                                      if self._last else None)}

    # -------------------------------------------------------- lifecycle
    def _loop(self) -> None:
        interval = max(0.05, self.cfg.autopilot.poll_interval_secs)
        while not self._stop.is_set():
            try:
                self.run_round()
            except Exception:  # noqa: BLE001 - the controller outlives
                log.exception("autopilot: control round failed")
                with self._lock:
                    self._counters["signal_errors"] += 1
            self._stop.wait(interval)

    def start(self) -> "AutopilotController":
        self.spans.event(
            "autopilot_start", directory=self.directory,
            min_replicas=self.cfg.autopilot.min_replicas,
            max_replicas=self.cfg.autopilot.max_replicas,
            poll_interval_secs=self.cfg.autopilot.poll_interval_secs,
            observe_only=self.actuator.observe_only)
        self._thread.start()
        return self

    def close(self) -> None:
        """Stop and JOIN the loop before closing any writer the loop
        appends to, then reap the actuator's children."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=15.0)
        self.actuator.close()
        self.spans.event("autopilot_stop")
        self.spans.close()
