"""Fleet autopilot — the traffic-driven autoscaling control plane.

Closes the loop the ROADMAP calls "no idle chips, no blown SLO": the
router's SLO/queue/shed gauges (PR 11) and fleetmon's burn rates
(PR 14) become *inputs*, the supervise/discovery spawn path with PR 10
colocation admission and the router's rolling-drain contract become
*outputs*, and in between sits a deterministic policy whose every
decision is replayable from its ledger.

Layout (the resolve/act split of resilience/elastic.py):

``signals.py``     one frozen SignalSnapshot per round (router /info +
                   fleetmon's digest-verified fleet_snapshot.json).
``policy.py``      pure ``decide(snapshot, config, state)`` —
                   hysteresis bands, streaks, cooldowns, min/max
                   bounds, step limits, admission-denied backoff.
``actuator.py``    every side effect: supervised replica spawns,
                   router /admin/drain, the capacity lease handed to a
                   colocated trainer.
``controller.py``  the loop thread + ledger/gauges/status artifacts.
``cli.py``         ``python -m tpu_resnet autopilot``.

Every module here is in the jaxlint host-isolation scope: the control
plane must keep steering while the accelerator stack is the thing
that is melting.
"""

from tpu_resnet.autopilot.policy import (Decision, PolicyState, decide,
                                         note_admission_denied, replay)
from tpu_resnet.autopilot.signals import SignalSnapshot, collect

__all__ = ["Decision", "PolicyState", "SignalSnapshot", "collect",
           "decide", "note_admission_denied", "replay"]
