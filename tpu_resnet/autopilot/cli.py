"""``python -m tpu_resnet autopilot`` — the control-process entry.

Starts the controller loop plus its own telemetry server (the
AUTOPILOT_GAUGES registry on ``autopilot.port``, announced in
``<dir>/autopilot.json``), blocks on the flag-only
ShutdownCoordinator, and tears down in the safe order: loop joined,
actuator's children reaped, writers closed.
Pure host code: stdlib only, no jax (jaxlint host-isolation scope).
"""

from __future__ import annotations

import logging
from typing import Optional

from tpu_resnet.autopilot.controller import (AUTOPILOT_DISCOVERY,
                                             AutopilotController)
from tpu_resnet.config import RunConfig
from tpu_resnet.obs.server import TelemetryServer

log = logging.getLogger("tpu_resnet")


def write_autopilot_discovery(directory: str, port: int,
                              run_id: Optional[str] = None) -> None:
    """Atomic ``<dir>/autopilot.json`` — the fleetmon.json analog for
    the controller (doctor and obs_scrape dial from here)."""
    from tpu_resnet.serve.discovery import write_record

    write_record(directory, AUTOPILOT_DISCOVERY, port,
                 extra={"run_id": run_id, "kind": "autopilot"})


def read_autopilot_port(directory: str) -> Optional[int]:
    from tpu_resnet.serve.discovery import read_port

    return read_port(directory, AUTOPILOT_DISCOVERY)


def autopilot(cfg: RunConfig) -> int:
    """CLI entry: start the control loop + telemetry, announce
    autopilot.json, block until SIGTERM/SIGINT, exit 0."""
    from tpu_resnet.resilience import ShutdownCoordinator, exitcodes

    directory = cfg.autopilot.discover_dir or cfg.train.train_dir
    if not directory:
        log.error("autopilot: need autopilot.discover_dir=<dir with "
                  "route.json/serve*.json> or train.train_dir")
        return exitcodes.USAGE_ERROR
    coordinator = ShutdownCoordinator(
        enabled=cfg.resilience.graceful_shutdown,
        action_desc="stopping the autopilot loop (spawned replicas "
                    "terminated via their drain contract), then "
                    "exiting 0")
    ctl = AutopilotController(cfg)
    server = None
    with coordinator:
        ctl.start()
        if cfg.autopilot.port >= 0:
            server = TelemetryServer(ctl.registry, cfg.autopilot.port,
                                     cfg.autopilot.host)
            write_autopilot_discovery(directory, server.port,
                                      run_id=ctl.run_id)
            log.info(
                "autopilot: ready on :%d — steering %s every %.1fs "
                "(replicas %d..%d%s; /metrics; /healthz)", server.port,
                directory, cfg.autopilot.poll_interval_secs,
                cfg.autopilot.min_replicas, cfg.autopilot.max_replicas,
                "; OBSERVE-ONLY" if ctl.actuator.observe_only else "")
        try:
            while not coordinator.event.wait(0.5):
                pass
            log.info("autopilot: shutdown requested (%s)",
                     coordinator.signum)
        except KeyboardInterrupt:
            log.warning("autopilot: immediate abort requested")
        finally:
            if server is not None:
                server.close()
            ctl.close()
    log.info("autopilot: exited cleanly")
    return 0
