"""Deterministic target-replica policy — the autopilot's pure core.

:func:`decide` is a pure function of ``(snapshot, config, state)`` and
returns ``(decision, new_state)``. No clock reads, no randomness, no
I/O: the only notion of "now" is ``snapshot.wall``, so a recorded
signal trace replayed through the same config produces bit-identical
decisions (the property every policy-table test in
tests/test_autopilot.py leans on). The split mirrors
resilience/elastic.py: this module RESOLVES what should happen,
actuator.py makes it happen.

Anti-flap is two-staged, deliberately:

- **hysteresis bands**: p99 above ``slo * up_band`` is scale-up
  pressure, p99 below ``slo * down_band`` is scale-down pressure, and
  the corridor between the bands is a dead zone — a p99 oscillating
  around any single threshold lands in the corridor half the time and
  can never alternate up/down decisions.
- **streaks + cooldowns**: pressure must hold for ``up_rounds`` /
  ``down_rounds`` consecutive snapshots before acting, and an actuation
  in either direction starts its cooldown during which the same
  direction holds.

Bounds beat everything else: a fleet below ``min_replicas`` (a replica
SIGKILLed out from under us) is restored immediately — no streak, no
cooldown — because the floor is a capacity promise, not a tuning
signal. Only the colocation-admission backoff can delay the restore:
this host already said "no capacity here" (serve exit 3), and asking
again immediately would just be denied again.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from tpu_resnet.config import AutopilotConfig

ACTIONS = ("scale_up", "scale_down", "hold")


@dataclasses.dataclass(frozen=True)
class PolicyState:
    """Everything :func:`decide` carries between rounds. Frozen: every
    transition mints a new state, so a trace replay can check the whole
    state sequence, not just the decisions."""

    up_streak: int = 0
    down_streak: int = 0
    # Walls of the last actuation per direction (snapshot time), None =
    # never — cooldown anchors.
    last_up_wall: Optional[float] = None
    last_down_wall: Optional[float] = None
    # Scale-ups hold until this wall after a colocation-admission
    # denial (note_admission_denied).
    denied_until: float = 0.0
    # High-water mark of the router's cumulative shed counter; a raise
    # between rounds means requests were shed SINCE the last look.
    shed_seen: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "PolicyState":
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class Decision:
    """One round's verdict. ``target = current + step`` (step signed);
    ``pressure`` is the raw band verdict before streaks/cooldowns so a
    ledger reader can see WHY a hold held."""

    action: str                 # one of ACTIONS
    current: int                # healthy + in-flight spawns this round
    target: int
    step: int                   # replicas to add (+) / drain (-)
    reason: str
    pressure: str               # "up" | "down" | "none"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def effective_slo(snapshot, cfg: AutopilotConfig) -> float:
    """The SLO the bands anchor to: an explicit autopilot.slo_ms wins,
    else adopt the router's advertised route.slo_ms (the colocated
    default). 0 = no latency signal; only shed/queue/burn pressure
    remains and scale-down is disabled (never drain capacity on the
    strength of no signal)."""
    if cfg.slo_ms > 0:
        return float(cfg.slo_ms)
    return float(getattr(snapshot, "slo_ms", 0.0) or 0.0)


def note_admission_denied(state: PolicyState, wall: float,
                          cfg: AutopilotConfig) -> PolicyState:
    """A spawn exited with the colocation NO_CAPACITY code (3): arm the
    scale-up backoff. The denial is a policy INPUT, not a crash."""
    until = float(wall) + max(0.0, cfg.admission_backoff_secs)
    return dataclasses.replace(state, denied_until=max(
        state.denied_until, until), up_streak=0)


def _pressure(snapshot, cfg: AutopilotConfig, state: PolicyState,
              current: int, slo: float) -> Tuple[str, str]:
    """Raw band verdict for one snapshot: ("up"|"down"|"none", why)."""
    p99 = snapshot.p99_ms
    shed_delta = max(0.0, float(snapshot.shed_total) - state.shed_seen)
    per = max(1, current)
    queue_per = float(snapshot.queue_depth) / per
    burn = snapshot.burn_fast
    why = []
    if slo > 0 and p99 is not None and p99 > slo * cfg.up_band:
        why.append("p99")
    if shed_delta > 0:
        why.append("shed")
    if queue_per > cfg.queue_high:
        why.append("queue")
    if burn is not None and burn >= cfg.burn_high:
        why.append("burn")
    if why:
        return "up", "+".join(why)
    if (slo > 0 and p99 is not None and p99 < slo * cfg.down_band
            and shed_delta == 0 and queue_per <= cfg.queue_high / 2
            and (burn is None or burn < 1.0)):
        return "down", "p99_low"
    return "none", "in_band"


def decide(snapshot, cfg: AutopilotConfig,
           state: PolicyState) -> Tuple[Decision, PolicyState]:
    """One policy round. ``snapshot`` is a signals.SignalSnapshot (or
    anything with its fields — the tests hand in literals)."""
    lo = max(0, int(cfg.min_replicas))
    hi = max(lo, int(cfg.max_replicas))
    wall = float(snapshot.wall)

    if not snapshot.ok:
        # Blind round: never act on missing signals, and never let them
        # advance a streak either.
        new = dataclasses.replace(state, up_streak=0, down_streak=0)
        return Decision("hold", -1, -1, 0, "signals_unavailable",
                        "none"), new

    current = int(snapshot.replicas_healthy) + max(
        0, int(snapshot.replicas_pending))
    slo = effective_slo(snapshot, cfg)
    pressure, why = _pressure(snapshot, cfg, state, current, slo)
    up_streak = state.up_streak + 1 if pressure == "up" else 0
    down_streak = state.down_streak + 1 if pressure == "down" else 0
    new = dataclasses.replace(
        state, up_streak=up_streak, down_streak=down_streak,
        shed_seen=max(state.shed_seen, float(snapshot.shed_total)))

    step_up = max(1, int(cfg.max_step_up))
    step_down = max(1, int(cfg.max_step_down))

    # Bounds first: the floor/ceiling are promises, not signals.
    if current < lo:
        if wall < new.denied_until:
            return Decision("hold", current, current, 0,
                            "admission_backoff", pressure), new
        step = min(step_up, lo - current)
        new = dataclasses.replace(new, last_up_wall=wall, up_streak=0)
        return Decision("scale_up", current, current + step, step,
                        "below_min", pressure), new
    if current > hi:
        step = min(step_down, current - hi)
        new = dataclasses.replace(new, last_down_wall=wall,
                                  down_streak=0)
        return Decision("scale_down", current, current - step, -step,
                        "above_max", pressure), new

    if pressure == "up" and up_streak >= max(1, int(cfg.up_rounds)):
        if current >= hi:
            return Decision("hold", current, current, 0, "at_max",
                            pressure), new
        if wall < new.denied_until:
            return Decision("hold", current, current, 0,
                            "admission_backoff", pressure), new
        if (new.last_up_wall is not None
                and wall - new.last_up_wall
                < cfg.scale_up_cooldown_secs):
            return Decision("hold", current, current, 0, "up_cooldown",
                            pressure), new
        step = min(step_up, hi - current)
        new = dataclasses.replace(new, last_up_wall=wall, up_streak=0)
        return Decision("scale_up", current, current + step, step, why,
                        pressure), new

    if pressure == "down" and down_streak >= max(1, int(cfg.down_rounds)):
        if current <= lo:
            return Decision("hold", current, current, 0, "at_min",
                            pressure), new
        # Scale-down cools down against the LAST actuation in either
        # direction: capacity just added must prove itself for a full
        # cooldown before any of it is drained away.
        anchors = [w for w in (new.last_up_wall, new.last_down_wall)
                   if w is not None]
        if anchors and wall - max(anchors) < cfg.scale_down_cooldown_secs:
            return Decision("hold", current, current, 0,
                            "down_cooldown", pressure), new
        step = min(step_down, current - lo)
        new = dataclasses.replace(new, last_down_wall=wall,
                                  down_streak=0)
        return Decision("scale_down", current, current - step, -step,
                        why, pressure), new

    reason = ("steady" if pressure == "none"
              else f"pressure_{pressure}_building")
    return Decision("hold", current, current, 0, reason, pressure), new


def replay(snapshots, cfg: AutopilotConfig,
           state: Optional[PolicyState] = None):
    """Run a recorded snapshot trace through the policy; returns the
    decision list (the replay half of the determinism contract — two
    calls over the same trace must be equal)."""
    state = state if state is not None else PolicyState()
    out = []
    for snap in snapshots:
        decision, state = decide(snap, cfg, state)
        out.append(decision)
    return out, state
