"""Signal plane for the autopilot: one snapshot per control round.

:func:`collect` is the only I/O on the sensing side — it dials the
router's ``/info`` (rolling p99 vs the advertised ``route.slo_ms``,
shed/lane counters, per-replica queue depth and rotation state) and
reads fleetmon's digest-verified ``fleet_snapshot.json`` (true pooled
percentiles, multiwindow burn rates, per-endpoint health incl. HBM
gauges) into one frozen :class:`SignalSnapshot`. The policy never does
I/O and the collector never decides: a snapshot serialized into the
``autopilot_events.jsonl`` ledger can be rehydrated with
:meth:`SignalSnapshot.from_dict` and replayed bit-identically.

Degradation is explicit, never silent: an unreachable router makes the
snapshot ``ok=False`` (the policy holds on blind rounds); a missing or
digest-failing fleet snapshot just leaves the fleet fields ``None``
(router-only operation — fleetmon is an enrichment, not a dependency).
Pure host code: stdlib only, no jax (jaxlint host-isolation scope).
"""

from __future__ import annotations

import dataclasses
import json
import time
import urllib.request
from typing import Optional, Tuple

# Filenames owned by their writers (serve/router.py, obs/fleet.py);
# read via the discovery helpers so this module needs neither import at
# module scope.
ROUTE_DISCOVERY = "route.json"


@dataclasses.dataclass(frozen=True)
class SignalSnapshot:
    """One round of fleet signals, frozen at ``wall``."""

    wall: float
    ok: bool = False                      # router answered /info
    errors: Tuple[str, ...] = ()
    # ------------------------------------------------- router signals
    router_port: Optional[int] = None
    p99_ms: Optional[float] = None        # rolling router p99
    slo_ms: float = 0.0                   # advertised route.slo_ms
    requests_total: float = 0.0
    requests_ok: float = 0.0
    shed_total: float = 0.0               # cumulative 429s (all lanes)
    inflight: float = 0.0
    queue_depth: float = 0.0              # summed across replicas
    replicas_total: int = 0
    replicas_healthy: int = 0
    # In-flight spawns the controller already launched but the router
    # has not admitted yet — filled by the controller, not collect():
    # the policy must count capacity en route or it double-spawns.
    replicas_pending: int = 0
    # Per-replica rotation detail, one small dict per replica (name,
    # state, draining, pending, inflight, queue_depth).
    replicas: Tuple[dict, ...] = ()
    # ------------------------------------------------ fleetmon signals
    fleet_p99_ms: Optional[float] = None  # pooled, bucket-merged
    burn_fast: Optional[float] = None
    burn_slow: Optional[float] = None
    fleet_round: Optional[int] = None
    # name -> {"hbm_bytes_in_use": ..., "hbm_bytes_limit": ...} for
    # endpoints that export HBM gauges (the colocation headroom view).
    hbm: Tuple[Tuple[str, dict], ...] = ()

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["errors"] = list(self.errors)
        d["replicas"] = [dict(r) for r in self.replicas]
        d["hbm"] = {name: dict(v) for name, v in self.hbm}
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "SignalSnapshot":
        d = dict(d)
        d["errors"] = tuple(d.get("errors", ()))
        d["replicas"] = tuple(d.get("replicas", ()))
        hbm = d.get("hbm", {})
        if isinstance(hbm, dict):
            hbm = tuple(sorted(hbm.items()))
        d["hbm"] = tuple(hbm)
        return cls(**d)


def _get_json(url: str, timeout: float) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def replica_is_healthy(rec: dict) -> bool:
    """Rotation verdict from a router /info replica record — must match
    Replica.healthy (breaker closed, not draining, not in the
    watch-discovery probation)."""
    return (rec.get("state") == "closed" and not rec.get("draining")
            and not rec.get("pending"))


def collect(directory: str, timeout: float = 2.0,
            now=time.time) -> SignalSnapshot:
    """Scrape one snapshot from the fleet rooted at ``directory``."""
    from tpu_resnet.obs.fleet import read_fleet_snapshot
    from tpu_resnet.serve.discovery import read_port

    wall = float(now())
    errors = []

    port = read_port(directory, ROUTE_DISCOVERY)
    info = None
    if port is None:
        errors.append("no route.json — router not announced yet")
    else:
        try:
            info = _get_json(f"http://127.0.0.1:{port}/info", timeout)
        except (OSError, ValueError) as e:
            errors.append(f"router /info: {type(e).__name__}: {e}"[:160])

    fleet = read_fleet_snapshot(directory)

    if info is None:
        return SignalSnapshot(
            wall=wall, ok=False, errors=tuple(errors),
            router_port=port,
            fleet_p99_ms=(fleet or {}).get("fleet", {}).get("p99_ms"),
            burn_fast=(fleet or {}).get("burn_rate_fast"),
            burn_slow=(fleet or {}).get("burn_rate_slow"),
            fleet_round=(fleet or {}).get("round"))

    counters = info.get("counters", {})
    replicas = []
    for rec in info.get("replicas", []):
        replicas.append({
            "name": rec.get("name"), "state": rec.get("state"),
            "draining": bool(rec.get("draining")),
            "pending": bool(rec.get("pending")),
            "inflight": int(rec.get("inflight") or 0),
            "queue_depth": int(rec.get("queue_depth") or 0)})
    healthy = sum(1 for r in replicas if replica_is_healthy(r))

    hbm = {}
    for name, per in ((fleet or {}).get("per") or {}).items():
        if isinstance(per, dict) and "hbm_bytes_in_use" in per:
            hbm[name] = {"hbm_bytes_in_use": per["hbm_bytes_in_use"],
                         "hbm_bytes_limit":
                         per.get("hbm_bytes_limit", 0.0)}

    return SignalSnapshot(
        wall=wall, ok=True, errors=tuple(errors), router_port=port,
        p99_ms=float(info.get("p99_ms") or 0.0),
        slo_ms=float(info.get("slo_ms") or 0.0),
        requests_total=float(counters.get("requests", 0)),
        requests_ok=float(counters.get("ok", 0)),
        shed_total=float(counters.get("shed", 0)),
        inflight=float(sum(r["inflight"] for r in replicas)),
        queue_depth=float(sum(r["queue_depth"] for r in replicas)),
        replicas_total=len(replicas),
        replicas_healthy=healthy,
        replicas=tuple(replicas),
        fleet_p99_ms=(fleet or {}).get("fleet", {}).get("p99_ms"),
        burn_fast=(fleet or {}).get("burn_rate_fast"),
        burn_slow=(fleet or {}).get("burn_rate_slow"),
        fleet_round=(fleet or {}).get("round"),
        hbm=tuple(sorted(hbm.items())))
