"""Model export — the freeze_graph pipeline rebuilt for XLA.

The reference freezes a checkpoint into a GraphDef ``.pb`` with named
placeholder inputs and fetches (reference resnet_cifar_frozen_model.py:2-23:
rebuild eval graph on placeholders → export_meta_graph → freeze_graph →
load_graph + feed_dict), and serves it via feed-dict sessions
(resnet_cifar_predict_from_pd.py:66-105).

TPU-native equivalent: serialize the *compiled inference function* as
StableHLO via ``jax.export`` (weights baked in as constants — the exact
analog of freezing) next to a JSON manifest. The artifact is loadable
without any model code, like a ``.pb``:

    bundle = load_inference(path)
    logits = bundle(images_uint8)   # preprocessing is baked into the graph

Layout of an export directory:
    manifest.json      model/config metadata
    inference.stablehlo  serialized jax.export artifact
"""

from __future__ import annotations

import json
import os
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from tpu_resnet.config import RunConfig
from tpu_resnet.data import augment as aug_lib
from tpu_resnet.models import build_model

MANIFEST = "manifest.json"
ARTIFACT = "inference.stablehlo"


def make_inference_fn(cfg: RunConfig, params, batch_stats) -> Callable:
    """Pure fn: uint8 [B,H,W,3] → logits [B,classes]; eval preprocessing
    (standardization / mean subtraction) baked in, like the frozen graph's
    in-graph preprocessing (resnet_cifar_frozen_model.py:81-88)."""
    model = build_model(cfg)
    _, eval_pre = aug_lib.get_augment_fns(cfg.data.dataset)

    def infer(images):
        x = eval_pre(images)
        return model.apply({"params": params, "batch_stats": batch_stats},
                           x, train=False)

    return infer


def save_inference(cfg: RunConfig, params, batch_stats, out_dir: str,
                   batch_size: int = 0, step: int | None = None) -> str:
    """Freeze params into a serialized StableHLO artifact.

    ``batch_size=0`` exports with a symbolic (polymorphic) batch dimension;
    a fixed size pins it like the reference's placeholder shape. ``step``
    (when known — ``export_from_checkpoint`` passes the restored step)
    is recorded in the manifest so serving a frozen bundle can still
    report which training step it is (the ``serve_model_step`` gauge).
    """
    os.makedirs(out_dir, exist_ok=True)
    infer = make_inference_fn(cfg, params, batch_stats)
    size = cfg.data.resolved_image_size
    if batch_size:
        arg = jax.ShapeDtypeStruct((batch_size, size, size, 3), jnp.uint8)
    else:
        (b,) = jax_export.symbolic_shape("b")
        arg = jax.ShapeDtypeStruct((b, size, size, 3), jnp.uint8)
    exported = jax_export.export(jax.jit(infer))(arg)
    with open(os.path.join(out_dir, ARTIFACT), "wb") as f:
        f.write(exported.serialize())
    with open(os.path.join(out_dir, MANIFEST), "w") as f:
        json.dump({
            "format": "jax.export/stablehlo",
            "model": cfg.model.name,
            "resnet_size": cfg.model.resnet_size,
            "dataset": cfg.data.dataset,
            "num_classes": cfg.data.num_classes,
            "image_size": size,
            "batch_size": batch_size or "dynamic",
            "input": "uint8 NHWC, raw pixels (preprocessing baked in)",
            "output": "float32 logits",
            "step": step if step is not None else -1,
        }, f, indent=2)
    return out_dir


class InferenceBundle:
    """Loaded frozen model (the load_graph+feed analog,
    resnet_cifar_predict_from_pd.py:66-105)."""

    def __init__(self, exported, manifest: dict):
        self._exported = exported
        self.manifest = manifest

    def __call__(self, images: np.ndarray) -> np.ndarray:
        return np.asarray(self._exported.call(jnp.asarray(images, jnp.uint8)))

    def predict(self, images: np.ndarray) -> np.ndarray:
        return np.argmax(self(images), axis=-1)


def load_inference(out_dir: str) -> InferenceBundle:
    with open(os.path.join(out_dir, ARTIFACT), "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(os.path.join(out_dir, MANIFEST)) as f:
        manifest = json.load(f)
    return InferenceBundle(exported, manifest)


def export_from_checkpoint(cfg: RunConfig, out_dir: str,
                           step: int | None = None,
                           batch_size: int = 0) -> str:
    """checkpoint dir (cfg.train.train_dir) → frozen artifact — the 4-step
    freeze recipe (resnet_cifar_frozen_model.py:2-23) as one call."""
    from tpu_resnet import parallel
    from tpu_resnet.train.checkpoint import (CheckpointManager,
                                             partitioned_template)

    mesh = parallel.create_mesh(cfg.mesh)
    model = build_model(cfg)
    # Abstract template in the run's partition layout (no device
    # allocation; a zero1 run's checkpoint restores into its shards and
    # the replicated params/stats below are untouched by the mode).
    template = partitioned_template(cfg, mesh, model=model)
    ckpt = CheckpointManager(cfg.train.train_dir)
    state = ckpt.restore(template, step=step)
    return save_inference(cfg, jax.device_get(state.params),
                          jax.device_get(state.batch_stats), out_dir,
                          batch_size=batch_size,
                          step=int(jax.device_get(state.step)))
