"""Model export — the freeze_graph pipeline rebuilt for XLA.

The reference freezes a checkpoint into a GraphDef ``.pb`` with named
placeholder inputs and fetches (reference resnet_cifar_frozen_model.py:2-23:
rebuild eval graph on placeholders → export_meta_graph → freeze_graph →
load_graph + feed_dict), and serves it via feed-dict sessions
(resnet_cifar_predict_from_pd.py:66-105).

TPU-native equivalent: serialize the *compiled inference function* as
StableHLO via ``jax.export`` (weights baked in as constants — the exact
analog of freezing) next to a JSON manifest. The artifact is loadable
without any model code, like a ``.pb``:

    bundle = load_inference(path)
    logits = bundle(images_uint8)   # preprocessing is baked into the graph

Layout of an export directory:
    manifest.json      model/config metadata
    inference.stablehlo  serialized jax.export artifact
"""

from __future__ import annotations

import json
import os
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from tpu_resnet.config import RunConfig
from tpu_resnet.data import augment as aug_lib
from tpu_resnet.models import build_model
from tpu_resnet.ops import quant as quant_lib

MANIFEST = "manifest.json"
ARTIFACT = "inference.stablehlo"
WEIGHTS = "weights.npz"  # quantized bundles only: the int8 argument tree


def _flatten_tree(tree) -> dict:
    """Pytree of arrays → flat ``{"a/b/c": np.ndarray}`` (dict keys
    joined by "/"; param names never contain one). The npz-serializable
    form of the quantized argument tree."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = "/".join(str(getattr(k, "key", k)) for k in path)
        out[key] = np.asarray(leaf)
    return out


def _unflatten_tree(flat: dict) -> dict:
    out = {}
    for key, leaf in flat.items():
        parts = key.split("/")
        cur = out
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = leaf
    return out


def make_inference_fn(cfg: RunConfig, params, batch_stats) -> Callable:
    """Pure fn: uint8 [B,H,W,3] → logits [B,classes]; eval preprocessing
    (standardization / mean subtraction) baked in, like the frozen graph's
    in-graph preprocessing (resnet_cifar_frozen_model.py:81-88)."""
    model = build_model(cfg)
    _, eval_pre = aug_lib.get_augment_fns(cfg.data.dataset)

    def infer(images):
        x = eval_pre(images)
        return model.apply({"params": params, "batch_stats": batch_stats},
                           x, train=False)

    return infer


def save_inference(cfg: RunConfig, params, batch_stats, out_dir: str,
                   batch_size: int = 0, step: int | None = None,
                   calibration: dict | None = None) -> str:
    """Freeze params into a serialized StableHLO artifact.

    ``batch_size=0`` exports with a symbolic (polymorphic) batch dimension;
    a fixed size pins it like the reference's placeholder shape. ``step``
    (when known — ``export_from_checkpoint`` passes the restored step)
    is recorded in the manifest so serving a frozen bundle can still
    report which training step it is (the ``serve_model_step`` gauge).

    ``cfg.serve.quantize="int8"`` exports the QUANTIZED bundle instead:
    the serialized program is the live serve arm's weights-as-ARGUMENTS
    program (serve/infer.py — identical math, same `_q8` family), and
    the int8 argument tree lands beside it as ``weights.npz``. Baking
    the quantized tree in as constants would be a lie: trace-time
    constant folding materializes the dequantized fp32 weights into the
    artifact. As arguments the on-disk payload and the runtime argument
    footprint are genuinely ~0.25x, and ``calibration`` provenance
    (a serve/calibrate.py record; collected on the spot when None) is
    stamped into the manifest — quant mode, calibration digest, and the
    weight-tree bytes the serve backend reports.
    """
    os.makedirs(out_dir, exist_ok=True)
    quantize = getattr(cfg.serve, "quantize", "off")
    quant_lib.check_quantize_config(cfg)
    size = cfg.data.resolved_image_size
    if batch_size:
        arg = jax.ShapeDtypeStruct((batch_size, size, size, 3), jnp.uint8)
    else:
        (b,) = jax_export.symbolic_shape("b")
        arg = jax.ShapeDtypeStruct((b, size, size, 3), jnp.uint8)
    calibration_digest = ""
    if quantize == "int8":
        from tpu_resnet.serve.infer import make_serve_infer

        if calibration is None:
            from tpu_resnet.serve import calibrate

            calibration = calibrate.collect_ranges(cfg)
        calibration_digest = calibration["digest"]
        qvars = quant_lib.quantize_variables(
            {"params": params, "batch_stats": batch_stats},
            act_max=calibration["act_max"]["input"])
        # Round-trip through the flat npz form NOW, so the traced pytree
        # structure is exactly the one load_inference reconstructs.
        qflat = _flatten_tree(qvars)
        variables = _unflatten_tree(qflat)
        for top in ("params", "batch_stats", quant_lib.QSCALES_KEY,
                    quant_lib.QACT_KEY):
            variables.setdefault(top, {})
        var_avals = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), variables)
        exported = jax_export.export(make_serve_infer(cfg))(var_avals,
                                                            arg)
        np.savez(os.path.join(out_dir, WEIGHTS), **qflat)
    else:
        variables = {"params": params, "batch_stats": batch_stats}
        infer = make_inference_fn(cfg, params, batch_stats)
        exported = jax_export.export(jax.jit(infer))(arg)
    with open(os.path.join(out_dir, ARTIFACT), "wb") as f:
        f.write(exported.serialize())
    with open(os.path.join(out_dir, MANIFEST), "w") as f:
        json.dump({
            "format": "jax.export/stablehlo",
            "model": cfg.model.name,
            "resnet_size": cfg.model.resnet_size,
            "dataset": cfg.data.dataset,
            "num_classes": cfg.data.num_classes,
            "image_size": size,
            "batch_size": batch_size or "dynamic",
            "input": "uint8 NHWC, raw pixels (preprocessing baked in)",
            "output": "float32 logits",
            "step": step if step is not None else -1,
            "quantize": quantize,
            "calibration_digest": calibration_digest,
            "weights": WEIGHTS if quantize == "int8" else "",
            "weight_bytes": quant_lib.tree_argument_bytes(variables),
        }, f, indent=2)
    return out_dir


class InferenceBundle:
    """Loaded frozen model (the load_graph+feed analog,
    resnet_cifar_predict_from_pd.py:66-105). Quantized bundles carry
    their int8 weight tree separately (``weights.npz``) and feed it as
    the program's first argument on every call."""

    def __init__(self, exported, manifest: dict, qvars=None):
        self._exported = exported
        self.manifest = manifest
        self._qvars = qvars

    def __call__(self, images: np.ndarray) -> np.ndarray:
        images = jnp.asarray(images, jnp.uint8)
        if self._qvars is not None:
            return np.asarray(self._exported.call(self._qvars, images))
        return np.asarray(self._exported.call(images))

    def predict(self, images: np.ndarray) -> np.ndarray:
        return np.argmax(self(images), axis=-1)


def load_inference(out_dir: str) -> InferenceBundle:
    with open(os.path.join(out_dir, ARTIFACT), "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(os.path.join(out_dir, MANIFEST)) as f:
        manifest = json.load(f)
    qvars = None
    if manifest.get("quantize", "off") == "int8":
        with np.load(os.path.join(out_dir,
                                  manifest.get("weights") or WEIGHTS)) as z:
            qvars = _unflatten_tree({k: z[k] for k in z.files})
        for top in ("params", "batch_stats", quant_lib.QSCALES_KEY,
                    quant_lib.QACT_KEY):
            qvars.setdefault(top, {})
    return InferenceBundle(exported, manifest, qvars=qvars)


def export_from_checkpoint(cfg: RunConfig, out_dir: str,
                           step: int | None = None,
                           batch_size: int = 0) -> str:
    """checkpoint dir (cfg.train.train_dir) → frozen artifact — the 4-step
    freeze recipe (resnet_cifar_frozen_model.py:2-23) as one call."""
    from tpu_resnet import parallel
    from tpu_resnet.train.checkpoint import (CheckpointManager,
                                             partitioned_template)

    mesh = parallel.create_mesh(cfg.mesh)
    model = build_model(cfg)
    # Abstract template in the run's partition layout (no device
    # allocation; a zero1 run's checkpoint restores into its shards and
    # the replicated params/stats below are untouched by the mode).
    template = partitioned_template(cfg, mesh, model=model)
    ckpt = CheckpointManager(cfg.train.train_dir)
    state = ckpt.restore(template, step=step)
    calibration = None
    if getattr(cfg.serve, "quantize", "off") == "int8":
        # Calibration lives next to the checkpoints (load-or-collect),
        # so a quantized export and a quantized live replica of the same
        # train_dir stamp the SAME digest — the A/B provenance link.
        from tpu_resnet.serve import calibrate

        calibration = calibrate.ensure_calibration(cfg,
                                                   cfg.train.train_dir)
    return save_inference(cfg, jax.device_get(state.params),
                          jax.device_get(state.batch_stats), out_dir,
                          batch_size=batch_size,
                          step=int(jax.device_get(state.step)),
                          calibration=calibration)
