from tpu_resnet.export.serialize import (
    InferenceBundle,
    export_from_checkpoint,
    load_inference,
    make_inference_fn,
    save_inference,
)

__all__ = [
    "InferenceBundle",
    "export_from_checkpoint",
    "load_inference",
    "make_inference_fn",
    "save_inference",
]
