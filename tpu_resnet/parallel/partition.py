"""State partitioner — the single owner of every TrainState sharding
decision in the codebase.

Before this module, "where does each state leaf live on the mesh" was
decided five times over: the train loop device_put a replicated state,
the checkpoint restore template inherited whatever the caller built, the
serve backend attached its own replicated ShapeDtypeStructs, the sweep
harness replicated again, and the analysis engines (configmatrix /
memorybudget) re-spelled the same ``P()`` in their jit constructors.
Every one of those sites now asks a :class:`StatePartitioner` instead,
so a partitioning scheme is ONE declarative rule set validated once at
startup — not five code paths that can drift.

Two modes, selected by the ``mesh.partition`` config knob:

``replicated``  today's behavior and the default: every leaf ``P()``.
``zero1``       cross-replica optimizer-state sharding per "Automatic
                Cross-Replica Sharding of Weight Update in Data-Parallel
                Training" (arXiv:2004.13336): parameters and BN stats
                stay replicated (the forward/backward sees gathered
                weights), while every optimizer slot — and, inside the
                step, the weight update itself (tpu_resnet/parallel/
                zero.py) — is sharded over the mesh's ``data`` axis.
                Per-device optimizer HBM drops ~N× on an N-way data
                axis; the gradient all-reduce splits into a
                reduce-scatter (each replica reduces only its shard)
                plus an all-gather of the updated parameters.

The zero1 per-leaf rule (deliberately simple and inspectable):

- scalar leaves (optimizer step counts) stay replicated;
- every other optimizer-slot leaf is sharded along its FIRST axis whose
  size divides the data-axis size (conv kernels shard on channels, 1-D
  scale/bias on their only axis);
- a leaf with no divisible axis stays replicated when it is small
  (≤ :data:`ZERO1_SMALL_LEAF_BYTES` — e.g. a 10-class head bias on an
  8-way mesh), and is a startup ``ValueError`` naming the leaf, its
  shape and the mesh otherwise — a large indivisible slot silently
  replicated would quietly void the memory win the operator configured.

``validate()`` runs the rule set against the real state tree at startup
(the loop calls it before the first device_put), so a bad
(model × mesh × partition) combination dies with per-leaf messages
before any compile is paid. The same partitioner instance then hands
out ``jit`` in_shardings, ``device_put`` targets, and the abstract
(ShapeDtypeStruct) restore templates the checkpoint/eval/serve paths
use — a zero1 checkpoint restores straight into its sharded layout
without ever materializing a replicated copy.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from jax.sharding import NamedSharding, PartitionSpec as P

PARTITION_MODES = ("replicated", "zero1")

# zero1: an optimizer-slot leaf with no data-divisible axis stays
# replicated when its global size is at most this many bytes (head
# biases, odd scalar-ish slots); anything larger must shard — raise.
ZERO1_SMALL_LEAF_BYTES = 65536


def check_partition_mode(mode: str) -> str:
    """Fail-loud knob validation (same contract as fused_epilogue: a
    typo must not silently mean 'replicated')."""
    if mode not in PARTITION_MODES:
        raise ValueError(
            f"mesh.partition must be one of {PARTITION_MODES}, got "
            f"{mode!r}")
    return mode


def _leaf_bytes(leaf) -> int:
    import numpy as np

    size = 1
    for d in leaf.shape:
        size *= int(d)
    return size * np.dtype(leaf.dtype).itemsize


class StatePartitioner:
    """Maps every TrainState leaf to a PartitionSpec / NamedSharding.

    ``mesh`` may be a concrete ``jax.sharding.Mesh`` (loop, checkpoint,
    serve, memory budgets) or an ``AbstractMesh`` (the config-matrix
    abstract trace) — every spec-producing method works on both; only
    ``shard_state``/``abstract_state`` need a concrete mesh.
    """

    def __init__(self, mesh, mode: str = "replicated", axis: str = "data"):
        self.mesh = mesh
        self.mode = check_partition_mode(mode)
        self.axis = axis

    @property
    def data_size(self) -> int:
        return int(dict(self.mesh.shape)[self.axis])

    @property
    def is_sharded(self) -> bool:
        """True when the mode actually shards anything. zero1 on a
        1-way data axis is the identity — the compiled program is
        byte-identical to replicated (pinned by the config matrix's
        ``same_program_as`` twin), so callers take the replicated path
        and nothing recompiles differently."""
        return self.mode == "zero1" and self.data_size > 1

    # ------------------------------------------------------ per-leaf rules
    def slot_spec(self, shape: Tuple[int, ...],
                  nbytes: Optional[int] = None) -> Optional[P]:
        """zero1 spec for one optimizer-slot leaf: first data-divisible
        axis, or P() for small indivisible leaves, or None when the leaf
        is large AND indivisible (the caller raises with the leaf
        path)."""
        if not self.is_sharded:
            return P()
        if len(shape) == 0:
            return P()
        n = self.data_size
        for i, d in enumerate(shape):
            if d % n == 0 and d > 0:
                return P(*([None] * i + [self.axis]))
        if nbytes is not None and nbytes > ZERO1_SMALL_LEAF_BYTES:
            return None
        return P()

    def _opt_specs(self, opt_state, on_indivisible="raise"):
        import jax

        problems: List[str] = []

        def spec_of(path, leaf):
            nbytes = _leaf_bytes(leaf)
            spec = self.slot_spec(tuple(leaf.shape), nbytes)
            if spec is None:
                problems.append(
                    f"  opt_state{jax.tree_util.keystr(path)}: shape "
                    f"{tuple(leaf.shape)} ({nbytes:,} bytes) has no axis "
                    f"divisible by the {self.axis}-axis size "
                    f"{self.data_size}")
                return P()
            return spec

        specs = jax.tree_util.tree_map_with_path(spec_of, opt_state)
        if problems and on_indivisible == "raise":
            raise ValueError(
                f"mesh.partition=zero1 cannot shard "
                f"{len(problems)} optimizer-slot leaf/leaves over the "
                f"{self.data_size}-way '{self.axis}' axis:\n"
                + "\n".join(problems)
                + f"\n(leaves ≤ {ZERO1_SMALL_LEAF_BYTES} bytes stay "
                f"replicated automatically; pick a mesh whose "
                f"{self.axis} axis divides the slot shapes, or use "
                f"mesh.partition=replicated)")
        return specs

    # -------------------------------------------------------- state trees
    def state_specs(self, state) -> Any:
        """TrainState-shaped tree of PartitionSpecs for ``state`` (a
        concrete state, an aval tree from ``jax.eval_shape``, or a
        ShapeDtypeStruct tree — anything with .shape/.dtype leaves).
        Raises on indivisible large slots (the ``validate`` contract)."""
        import jax

        return state.replace(
            step=P(),
            params=jax.tree_util.tree_map(lambda _: P(), state.params),
            batch_stats=jax.tree_util.tree_map(lambda _: P(),
                                               state.batch_stats),
            opt_state=self._opt_specs(state.opt_state),
        )

    def state_shardings(self, state) -> Any:
        import jax

        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s),
            self.state_specs(state),
            is_leaf=lambda x: isinstance(x, P))

    def validate(self, state) -> None:
        """Must-raise gate: every zero1 rule applied to the real state
        tree, with a clear per-leaf message for anything unshardable.
        Run once at startup, before the first device_put/compile."""
        self.state_specs(state)

    def shard_state(self, state):
        """device_put the freshly-initialized state into its partition
        layout (the loop's replacement for the bare replicated put)."""
        import jax

        return jax.device_put(state, self.state_shardings(state))

    def abstract_state(self, state) -> Any:
        """Sharded ShapeDtypeStruct tree describing ``state``'s
        partition layout — the restore template for checkpoint/eval/
        serve: orbax restores each leaf straight into its shard, so a
        zero1 checkpoint never materializes a replicated optimizer
        copy on any single device."""
        import jax

        return jax.tree_util.tree_map(
            lambda leaf, sh: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                                  sharding=sh),
            state, self.state_shardings(state))

    # ------------------------------------------- step-internal constraints
    def constrain_slots(self, tree):
        """Pin a params-shaped tree (grads, updates) to the slot layout
        inside the step — the reduce-scatter half of the zero1 weight
        update (tpu_resnet/parallel/zero.py)."""
        import jax

        return jax.tree_util.tree_map(
            lambda leaf: jax.lax.with_sharding_constraint(
                leaf, NamedSharding(
                    self.mesh, self.slot_spec(tuple(leaf.shape)) or P())),
            tree)

    def constrain_opt_state(self, opt_state):
        import jax

        specs = self._opt_specs(opt_state, on_indivisible="replicate")
        return jax.tree_util.tree_map(
            lambda leaf, spec: jax.lax.with_sharding_constraint(
                leaf, NamedSharding(self.mesh, spec)),
            opt_state, specs)

    def constrain_replicated(self, tree):
        """Gather a tree back to replicated — the all-gather half of the
        zero1 update (new params visible to every replica's forward)."""
        import jax

        return jax.tree_util.tree_map(
            lambda leaf: jax.lax.with_sharding_constraint(
                leaf, NamedSharding(self.mesh, P())),
            tree)

    # ------------------------------------------------------------ reports
    def state_argument_bytes(self, state) -> dict:
        """Per-device argument bytes of each state component under this
        partition — the analytic breakdown the memory ledger and the
        golden memory budgets record next to XLA's aggregate
        ``argument_bytes``, so the zero1 optimizer-slot cut is a named,
        reviewable number instead of a delta buried in a total."""
        import jax

        shardings = self.state_shardings(state)

        def shard_bytes(leaf, sh) -> int:
            import numpy as np

            shape = tuple(int(d) for d in leaf.shape)
            try:
                shape = sh.shard_shape(shape)
            except Exception:  # AbstractMesh shardings: analytic split
                spec = sh.spec
                shape = list(shape)
                for i, ax in enumerate(spec):
                    if ax is not None:
                        shape[i] //= self.data_size
            size = 1
            for d in shape:
                size *= int(d)
            return size * np.dtype(leaf.dtype).itemsize

        out = {}
        for name in ("params", "opt_state", "batch_stats"):
            leaves = jax.tree_util.tree_leaves(getattr(state, name))
            shs = jax.tree_util.tree_leaves(
                getattr(shardings, name),
                is_leaf=lambda x: isinstance(x, NamedSharding))
            out[f"{name}_argument_bytes"] = sum(
                shard_bytes(leaf, sh) for leaf, sh in zip(leaves, shs))
        return out

    def describe(self) -> str:
        return self.mode


def make_partitioner(mesh_cfg, mesh) -> StatePartitioner:
    """Partitioner for a run: ``mesh.partition`` from the config
    (``mesh_cfg`` may be a MeshConfig or None → replicated) over the
    concrete/abstract mesh the caller built."""
    mode = getattr(mesh_cfg, "partition", "replicated") \
        if mesh_cfg is not None else "replicated"
    return StatePartitioner(mesh, mode)
