from tpu_resnet.parallel.mesh import (
    batch_sharding,
    check_divisible,
    create_mesh,
    fit_mesh,
    get_shard_map,
    local_batch_size,
    replicated,
    staged_batch_sharding,
)
from tpu_resnet.parallel.multihost import initialize, is_primary
from tpu_resnet.parallel.partition import (
    PARTITION_MODES,
    StatePartitioner,
    check_partition_mode,
    make_partitioner,
)

__all__ = [
    "batch_sharding",
    "check_divisible",
    "create_mesh",
    "fit_mesh",
    "get_shard_map",
    "local_batch_size",
    "replicated",
    "staged_batch_sharding",
    "initialize",
    "is_primary",
    "PARTITION_MODES",
    "StatePartitioner",
    "check_partition_mode",
    "make_partitioner",
]
