"""Multi-host runtime initialization — replaces the reference's two process
bring-up stacks (TF gRPC server per ps/worker task,
reference resnet_cifar_train.py:382-387; and mpirun/ssh + MPI rendezvous for
Horovod, start-resnet-cifar-horovod-train.sh:119-125).

On TPU the launcher's only topology job is "start one process per host and
point it at a coordinator" — ``jax.distributed.initialize`` does rendezvous
over DCN, after which every process sees the global device set and the same
SPMD program runs everywhere. No ps processes, no ssh mesh, no NCCL env
plumbing.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

log = logging.getLogger(__name__)


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Initialize multi-host JAX if a cluster is configured.

    Resolution order:
    1. explicit args,
    2. env vars ``TPU_COORDINATOR_ADDRESS`` / ``TPU_NUM_PROCESSES`` /
       ``TPU_PROCESS_ID`` (set by launch/ scripts — the analog of the
       reference's ``TF_PS_HOSTS``/``TF_WORKER_HOSTS`` env protocol,
       mkl-scripts/run_dist_tf_daint.sh:4-28),
    3. TPU-VM / Slurm auto-detection inside ``jax.distributed.initialize``.

    Single-process runs (no coordinator configured) are a no-op, matching the
    reference's serial branch (resnet_cifar_train.py:313-326).
    """
    coordinator_address = coordinator_address or os.environ.get(
        "TPU_COORDINATOR_ADDRESS")
    if num_processes is None and "TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["TPU_NUM_PROCESSES"])
    if process_id is None and "TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["TPU_PROCESS_ID"])

    if coordinator_address is None and num_processes is None:
        log.info("single-process run; skipping jax.distributed.initialize")
        return

    kwargs = {}
    # >1 process per node (launch/slurm_train_eval.sbatch
    # TPU_PROCS_PER_NODE): each process must claim a disjoint chip subset,
    # or all colocated processes fight over the same local devices. The
    # launcher exports the node-local rank; chips/node defaults to 4 (one
    # TPU-VM host) and is overridable via TPU_CHIPS_PER_NODE.
    procs_per_node = int(os.environ.get("TPU_PROCS_PER_NODE", "1"))
    if procs_per_node > 1 and "TPU_LOCAL_RANK" in os.environ:
        local_rank = int(os.environ["TPU_LOCAL_RANK"])
        chips = int(os.environ.get("TPU_CHIPS_PER_NODE", "4"))
        per_proc = chips // procs_per_node
        if per_proc < 1:
            raise ValueError(
                f"TPU_PROCS_PER_NODE={procs_per_node} exceeds "
                f"TPU_CHIPS_PER_NODE={chips}")
        kwargs["local_device_ids"] = list(
            range(local_rank * per_proc, (local_rank + 1) * per_proc))

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    log.info("multi-host initialized: process %d/%d, %d local / %d global devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())


def is_primary() -> bool:
    """True on the process that owns checkpointing/logging — the analog of
    the reference's chief worker / Horovod rank 0
    (resnet_cifar_main.py:328, resnet_cifar_train.py:334)."""
    return jax.process_index() == 0
