"""ZeRO-1 weight update — cross-replica sharding of the optimizer step.

Implements the scheme of "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training" (arXiv:2004.13336) in the pjit idiom
(arXiv:2204.06514): no manual collectives, only sharding annotations —
XLA's SPMD partitioner derives the communication. The replicated
data-parallel step computes

    grads (all-reduced, replicated) → tx.update (replicated slots)
    → apply_updates → new params (replicated)

so every device redundantly holds the full optimizer state and applies
the full update. The zero1 update instead pins the update computation to
the optimizer-slot layout owned by :class:`~tpu_resnet.parallel.
partition.StatePartitioner`:

    grads  ──wsc(slot specs)──►  each replica's shard of the gradient
                                 (the all-reduce becomes reduce-scatter)
    tx.update over SHARDED slots — momentum etc. touch only the shard a
                                 replica owns (1/N compute, 1/N HBM)
    updates ──wsc(slot specs)──► sharded weight delta
    apply_updates ──wsc(P())──►  all-gather: every replica gets the new
                                 replicated parameters for the next
                                 forward/backward

``with_sharding_constraint`` (wsc) is the whole mechanism: the paper's
"sharding annotations alone". The constraint ops are part of the traced
program, so the config-matrix verifier golden-pins the zero1 structure
exactly like any other program (analysis/configmatrix.py zero1 rows),
and the state-in/state-out layout is unchanged — donation still aliases
every slot buffer (the memory budgets assert alias_bytes holds).

Not supported with per-replica BN (``model.sync_bn=false``): that path
runs the step body inside ``shard_map``, where mesh-level sharding
constraints are unavailable by construction — ``check_step_config``
fails loudly on the combination (same rule style as fused kernels).
"""

from __future__ import annotations

import optax


def make_update_fn(tx: optax.GradientTransformation, partitioner=None):
    """``(grads, opt_state, params) -> (new_params, new_opt_state)``.

    With no partitioner (or a non-sharding one — replicated mode, or
    zero1 on a 1-way data axis) this returns the plain optax chain,
    tracing to EXACTLY the ops the step inlined before this module
    existed: the replicated golden jaxprs are unchanged by construction.
    """
    if partitioner is None or not partitioner.is_sharded:
        def plain_update(grads, opt_state, params):
            updates, new_opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt_state

        return plain_update

    def zero1_update(grads, opt_state, params):
        shard_grads = partitioner.constrain_slots(grads)
        updates, new_opt_state = tx.update(shard_grads, opt_state, params)
        updates = partitioner.constrain_slots(updates)
        new_opt_state = partitioner.constrain_opt_state(new_opt_state)
        new_params = optax.apply_updates(params, updates)
        return partitioner.constrain_replicated(new_params), new_opt_state

    return zero1_update
