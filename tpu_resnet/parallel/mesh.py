"""Device mesh construction — the TPU-native replacement for the reference's
entire cluster topology layer.

Where the reference assembles ``ps_hosts``/``worker_hosts`` strings, starts a
gRPC ``tf.train.Server`` per task and places variables on parameter servers
(reference resnet_cifar_train.py:371-403), a JAX program sees every chip in
the slice and expresses distribution as shardings over one
``jax.sharding.Mesh``. Gradient aggregation becomes an XLA all-reduce over
ICI — the single code path that subsumes the reference's PS-sync, async-PS
and Horovod modes (SURVEY.md §2.3).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def create_mesh(mesh_cfg=None, devices: Optional[Sequence[jax.Device]] = None
                ) -> Mesh:
    """Build a (data, model) mesh from MeshConfig.

    ``data=-1`` consumes all devices not claimed by other axes. Reference
    parity only needs the data axis; the model axis (default size 1) keeps
    tensor-style shardings expressible without redesign.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    model = getattr(mesh_cfg, "model", 1) if mesh_cfg is not None else 1
    data = getattr(mesh_cfg, "data", -1) if mesh_cfg is not None else -1
    if data == -1:
        if n % model:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model != n:
        raise ValueError(f"mesh {data}x{model} != {n} devices")
    axis_names = tuple(getattr(mesh_cfg, "axis_names", ("data", "model"))
                       if mesh_cfg is not None else ("data", "model"))
    dev_array = np.asarray(devices).reshape(data, model)
    return Mesh(dev_array, axis_names)


def fit_mesh(mesh_cfg, n_devices: int):
    """``(data, model, downsized)`` axis sizes that actually fit on
    ``n_devices`` — the elastic-resume primitive (resilience/elastic.py):
    a run that asked for ``mesh.data=8`` but restarted on a host with 4
    chips gets the 4-way mesh it CAN have instead of a dead ValueError.

    The ``model`` axis is a hard constraint (its sharded tensors cannot
    be re-divided without a different partition plan); the ``data`` axis
    is the elastic one: ``-1`` follows the hardware in both directions
    (a device count the model axis doesn't divide drops the remainder —
    7 devices at model=2 train on 6, reported as downsized), an explicit
    size that no longer fits shrinks to the largest whole multiple the
    devices support. Growth is never implicit for an explicit ``data``
    size — the operator asked for that many."""
    model = getattr(mesh_cfg, "model", 1) if mesh_cfg is not None else 1
    data = getattr(mesh_cfg, "data", -1) if mesh_cfg is not None else -1
    if model < 1 or n_devices < model:
        raise ValueError(
            f"mesh model axis {model} cannot fit on {n_devices} "
            f"device(s) — the model axis is not elastic")
    if data != -1 and data < 1:
        raise ValueError(
            f"mesh.data must be -1 (all remaining devices) or >= 1, "
            f"got {data}")
    avail = n_devices // model
    if data == -1:
        return avail, model, avail * model != n_devices
    if data <= avail:
        return data, model, False
    return avail, model, True


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) axis split over 'data'."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def staged_batch_sharding(mesh: Mesh) -> NamedSharding:
    """For (stage, batch, ...) superbatches: batch axis (axis 1) split over
    'data', stage axis replicated (pipeline.staged_device_prefetch)."""
    return NamedSharding(mesh, P(None, "data"))


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    """Per-process batch for the host input pipeline.

    The mesh carries the full divisibility story: the global batch must
    split evenly over the processes feeding it AND over the mesh's
    ``data`` axis consuming it — a batch that divides the process count
    but not the data axis would pass here and then die later inside jit
    with an opaque sharding error, so both are checked up front with the
    mesh named in the message."""
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(
            f"global batch {global_batch} not divisible by {n_proc} "
            f"processes (mesh {dict(mesh.shape)})")
    check_divisible(global_batch, mesh)
    return global_batch // n_proc


def check_divisible(global_batch: int, mesh: Mesh) -> None:
    n_data = mesh.shape["data"]
    if global_batch % n_data:
        raise ValueError(
            f"global batch {global_batch} not divisible by data axis {n_data}")


def get_shard_map():
    """(shard_map, replication-check-off kwargs) for the installed jax.

    jax >= 0.7 exports ``shard_map`` at top level and spells the
    replication check ``check_vma``; 0.4.x keeps it in
    ``jax.experimental.shard_map`` as ``check_rep``. Every shard_map call
    site that disables the check goes through here so the next API shift
    is a one-file fix.
    """
    try:
        from jax import shard_map
        return shard_map, {"check_vma": False}
    except ImportError:
        from jax.experimental.shard_map import shard_map
        return shard_map, {"check_rep": False}
