"""`jaxlint` — AST lints for the repo's JAX/TPU contracts.

Each rule encodes a contract this codebase already relies on but (before
this subsystem) only enforced dynamically, if at all:

jit-host-sync     No side effects or host syncs in jit-reachable code
                  (``train/step.py``, ``serve/infer.py`` — the serving
                  hot path — ``ops/*``, ``tools/sweep_measure.py`` —
                  the sweep harness's program assembly — and any
                  ``@jax.jit`` function anywhere): ``print``,
                  ``time.*`` clocks,
                  ``np.random``/``random`` (host RNG under trace runs
                  ONCE and bakes a constant into the program),
                  ``.item()``/``jax.device_get``/``.block_until_ready``
                  (device round-trip per call).
jit-static-args   ``jax.jit``/``nn.remat`` call sites: static_argnums/
                  static_argnames literals must be hashable ints/strs,
                  and bool/str-typed parameters of a jitted function must
                  be marked static (a traced bool either fails at the
                  first Python branch or silently retraces per value).
fork-safety       The modules a spawn'd decode worker imports
                  (``data/engine.py`` and its transitive module-scope
                  import closure) must stay jax-free — a worker that
                  imports jax pays seconds of spawn latency and hundreds
                  of MB RSS; today this is only a convention held up by
                  the lazy ``data/__init__``. Also: module-level locks /
                  file handles in that closure, and process creation
                  outside an explicit spawn context.
signal-safety     Handlers registered via ``signal.signal`` may only set
                  flags, log, and re-raise. Checkpoint saves, lock
                  acquisition, sleeps or jax/numpy work inside a handler
                  run at an arbitrary bytecode boundary of the
                  interrupted main thread (mid-save, mid-dispatch) and
                  deadlock or corrupt state.
host-isolation    The serving fleet's host-side control plane
                  (``serve/router.py``, ``serve/batcher.py``) must stay
                  importable with NO accelerator stack: the router keeps
                  answering when the accelerator runtime is the thing
                  that is broken, and stdlib-only consumers (loadgen,
                  the doctor probes, supervise) import these modules on
                  machines with no backend. A module-scope jax/flax/tf
                  import there breaks that contract silently — the same
                  class of rot fork-safety pins for the decode workers.
registry-scope    Compiled-program construction (``jax.jit``/``pjit``
                  call sites and decorators) inside the tpu_resnet
                  package is allowed only in the registry-owned modules
                  (``REGISTRY_SCOPE_FILES``): every production program
                  must route through ``programs/registry.py`` so its
                  key spelling, golden identity, donation contract and
                  the persistent AOT executable cache all see it. A new
                  code path jitting directly would silently bypass the
                  cold-start cache AND the check engines' coverage map.
guard-parity      Fail-loud guard parity (ADVICE r4): the validation in
                  ``models.build_model`` must also exist in the public
                  constructors (``cifar_resnet_v2``/``imagenet_resnet_v2``)
                  and in ``BlockLayer``'s fused dispatch, so direct calls
                  fail with the same clear message instead of an obscure
                  downstream tile error or silent per-replica BN.

The engine is pure ``ast`` — importing this module never imports jax, so
``tpu-resnet-check`` (lint-only) runs in well under a second.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Tuple

from tpu_resnet.analysis.findings import Finding, apply_pragmas

EXCLUDE_DIRS = {"tests", "docs", "launch", "__pycache__", ".git",
                ".jax_cache", "build", "dist"}

# jit-reachable modules linted wholesale (every function body).
# serve/infer.py is the serving hot path: its compiled inference fn runs
# per coalesced batch, so a host sync there multiplies into every
# request's latency (host-side serving code lives in serve/batcher.py
# and serve/server.py, which are NOT jit scope).
# tools/sweep_measure.py is the sweep harness's jit-program assembly —
# split from tools/sweep.py precisely so the measured programs sit in
# this scope while the timing loop (host clocks by design) stays out;
# ops/autotune.py inside the ops/ prefix is the deliberate exception
# (file-level pragma with justification: it IS the host-side prober).
JIT_SCOPE_FILES = ("tpu_resnet/train/step.py",
                   "tpu_resnet/serve/infer.py",
                   "tpu_resnet/tools/sweep_measure.py",
                   # the zero1 weight update and the constraint helpers
                   # it calls trace INSIDE the step program
                   "tpu_resnet/parallel/zero.py",
                   "tpu_resnet/parallel/partition.py",
                   # int8 quant/dequant math traces inside the serving
                   # program (the dequant fold in make_serve_infer) —
                   # already under the ops/ prefix, listed explicitly
                   # because it is a named serve-hot-path contract
                   "tpu_resnet/ops/quant.py")
JIT_SCOPE_PREFIXES = ("tpu_resnet/ops/",)

# Module-scope import closure of the spawn'd decode worker
# (data/engine.py runs as __main__-adjacent module in every worker; its
# parent packages' __init__ execute too).
FORK_ENTRY_FILES = ("tpu_resnet/data/engine.py",)
FORK_FORBIDDEN_ROOTS = {"jax", "jaxlib", "flax", "optax", "orbax",
                        "tensorflow", "torch"}

# Modules allowed to construct jitted programs (jax.jit / pjit sites).
# The registry (programs/registry.py) is the front door; the rest are
# the canonical constructors it routes — train/step.py (shard_step),
# data/device_data.py (staged chunk + resident shuffle), data/pipeline.py
# (the H2D staging take), serve/infer.py + evaluation/evaluator.py (the
# serving/eval programs), export/serialize.py (the frozen artifact),
# obs/memory.py + analysis/memorybudget.py (the ledger/golden engines,
# which deliberately compile the SAME constructors' programs),
# tools/analysis.py (the info CLI's one-off lowering) and
# ops/autotune.py (the A/B prober — compiles candidates by design).
# Scope is the tpu_resnet package: root-level tools/ and bench.py are
# measurement harnesses outside the production path.
REGISTRY_SCOPE_FILES = (
    "tpu_resnet/programs/registry.py",
    "tpu_resnet/train/step.py",
    "tpu_resnet/data/device_data.py",
    "tpu_resnet/data/pipeline.py",
    "tpu_resnet/serve/infer.py",
    "tpu_resnet/evaluation/evaluator.py",
    "tpu_resnet/export/serialize.py",
    "tpu_resnet/obs/memory.py",
    "tpu_resnet/analysis/memorybudget.py",
    "tpu_resnet/tools/analysis.py",
)
# The ops/ kernels may jit internally (custom-VJP reference arms, A/B
# probe candidates, parity helpers): those programs are either inlined
# into registry-routed traces or exist to be measured against them —
# kernel-internal, never a run-level dispatch path.
REGISTRY_SCOPE_PREFIXES = ("tpu_resnet/ops/",)

# Modules allowed to construct shardings (NamedSharding) or pin layouts
# (with_sharding_constraint). StatePartitioner (parallel/partition.py)
# is the single OWNER of state-layout decisions — the collectives
# engine's golden structure (analysis/collectives.py) is only a proof
# if no other code path can inject a sharding behind its back — with
# parallel/zero.py (the ZeRO update that applies the partitioner's
# constraints), parallel/mesh.py (the canonical batch/replicated
# sharding helpers everything else is supposed to call), train/step.py
# and data/device_data.py (the registry-scoped program constructors
# that pin their own argument layouts) as the documented call surface.
SHARDING_SCOPE_FILES = (
    "tpu_resnet/parallel/partition.py",
    "tpu_resnet/parallel/zero.py",
    "tpu_resnet/parallel/mesh.py",
    "tpu_resnet/train/step.py",
    "tpu_resnet/data/device_data.py",
)

# Host-isolated serving control plane: these modules must import with no
# accelerator stack present (router on a broken-runtime host; batcher in
# stdlib-only consumers). Direct module-scope imports only — unlike
# fork-safety there is no transitive closure walk, because the contract
# is per-module and the modules' own imports (server.py etc.) are the
# jax-aware layer by design.
HOST_ONLY_FILES = ("tpu_resnet/serve/router.py",
                   "tpu_resnet/serve/batcher.py",
                   "tpu_resnet/serve/discovery.py",
                   # The fleet aggregator is the control-plane sensor:
                   # it must keep scraping while the data plane's
                   # accelerator stack is the thing that is broken.
                   "tpu_resnet/obs/fleet.py",
                   # The scenario conductor drills hosts whose
                   # accelerator stack is the thing under test; only
                   # its CHILD processes may touch jax.
                   "tpu_resnet/scenario/__init__.py",
                   "tpu_resnet/scenario/assertions.py",
                   "tpu_resnet/scenario/catalog.py",
                   "tpu_resnet/scenario/cli.py",
                   "tpu_resnet/scenario/conductor.py",
                   "tpu_resnet/scenario/spec.py",
                   # The autoscaling control plane scales the fleet
                   # PRECISELY when the data plane is melting; a jax
                   # import here would tie the controller's fate to the
                   # stack it supervises.
                   "tpu_resnet/autopilot/__init__.py",
                   "tpu_resnet/autopilot/signals.py",
                   "tpu_resnet/autopilot/policy.py",
                   "tpu_resnet/autopilot/actuator.py",
                   "tpu_resnet/autopilot/controller.py",
                   "tpu_resnet/autopilot/cli.py")

HOST_SYNC_EXACT = {
    "print": "host I/O",
    "jax.device_get": "device→host transfer",
    "time.time": "host clock", "time.sleep": "host sleep",
    "time.perf_counter": "host clock", "time.monotonic": "host clock",
    "time.process_time": "host clock",
}
HOST_SYNC_PREFIXES = {
    "numpy.random": "host RNG (runs once at trace time — bakes a "
                    "constant into the compiled program)",
    "random": "host RNG (runs once at trace time — bakes a constant "
              "into the compiled program)",
}
HOST_SYNC_METHODS = {
    "item": "device sync per call",
    "block_until_ready": "device sync",
    # Compile introspection (obs/mfu.py accounting): .lower()/.compile()
    # .cost_analysis() re-traces and runs an HLO analysis pass — a
    # one-time host-side startup cost that must never land in the jitted
    # hot path (cost_analysis is the unambiguous marker; .lower/.compile
    # collide with str.lower/re.compile and are left to review).
    "cost_analysis": "XLA compile introspection (obs/mfu accounting) — "
                     "host-side only, once per program, never per step",
    # Memory introspection (obs/memory.py): device.memory_stats() is a
    # host RPC into the PJRT client and jax.live_arrays() walks every
    # live buffer — both are log-boundary/forensics calls that must
    # never creep into the jitted hot path. (memory_analysis, like
    # cost_analysis, only exists on AOT-compiled objects.)
    "memory_stats": "device-memory introspection (obs/memory gauges) — "
                    "host-side only, at log boundaries, never per step",
    "live_arrays": "live-buffer census (obs/memory OOM forensics) — "
                   "host-side only, crash handlers, never per step",
    "memory_analysis": "XLA compile introspection (obs/memory ledger) — "
                       "host-side only, once per program, never per step",
}

SIGNAL_DENY_PREFIXES = ("subprocess.", "jax.", "jax_", "numpy.",
                        "shutil.", "socket.", "os.system", "os.popen")
# os.kill: the ROUTER SIGTERM anti-pattern — cascading the drain signal
# to the replica fleet inline in the handler (the route() loop owns
# teardown; handlers only set the flag).
SIGNAL_DENY_EXACT = {"open", "time.sleep", "exec", "eval", "os.kill"}
# "drain"/"shutdown": the serve SIGTERM anti-pattern — draining the
# micro-batcher or tearing down the HTTP socket inline in the handler
# instead of setting a flag for the serve()/route() loop
# (serve/server.py, serve/router.py). "drain_replica": the router's
# rolling-drain method, which joins threads and signals processes.
SIGNAL_DENY_METHODS = {"save", "restore", "acquire", "join", "wait",
                       "sleep", "write", "flush", "dump", "drain",
                       "shutdown", "drain_replica"}
SIGNAL_LOG_ROOTS = {"log", "logger", "logging"}

# (file, qualname, requirement) — requirement is "calls:<fn>" (body must
# call <fn>) or "guard:<a>&<b>" (body must contain an If mentioning both
# identifiers whose branch raises).
GUARD_PARITY_REQS = (
    ("tpu_resnet/models/resnet.py", "cifar_resnet_v2",
     "calls:_check_fused_bn_axis",
     "sync-BN (bn_axis_name) + fused_blocks must raise, not silently "
     "compute per-replica BN (ADVICE r4)"),
    ("tpu_resnet/models/resnet.py", "cifar_resnet_v2",
     "guard:fused_blocks&width_multiplier",
     "the build_model width_multiplier guard must also fail direct "
     "constructor calls (ADVICE r4)"),
    ("tpu_resnet/models/resnet.py", "imagenet_resnet_v2",
     "calls:_check_fused_bn_axis",
     "sync-BN (bn_axis_name) + fused_blocks must raise, not silently "
     "compute per-replica BN (ADVICE r4)"),
    ("tpu_resnet/models/resnet.py", "BlockLayer.__call__",
     "calls:_check_fused_bn_axis",
     "the fused dispatch must re-check bn_axis_name at apply time — "
     "BlockLayer is constructible directly (ADVICE r4)"),
    ("tpu_resnet/models/__init__.py", "build_model",
     "guard:fused_blocks&width_multiplier",
     "the config-level guard that the constructor guards mirror"),
)


# ----------------------------------------------------------------- file set
def discover(root: str) -> List[str]:
    """Root-relative posix paths of every lintable .py file."""
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in EXCLUDE_DIRS
                             and not d.startswith("."))
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                out.append(rel.replace(os.sep, "/"))
    return out


class SourceTree:
    """Parsed view of the lintable files under a root."""

    def __init__(self, root: str, files: Optional[Iterable[str]] = None):
        self.root = os.path.abspath(root)
        self.sources: Dict[str, str] = {}
        self.trees: Dict[str, ast.AST] = {}
        for rel in (files if files is not None else discover(self.root)):
            path = os.path.join(self.root, rel)
            try:
                with open(path, encoding="utf-8") as fh:
                    src = fh.read()
                self.trees[rel] = ast.parse(src, filename=rel)
            except (OSError, SyntaxError) as e:
                # A file the toolchain can't parse is itself a finding —
                # surfaced by the engine, not swallowed.
                self.sources[rel] = ""
                self.trees[rel] = ast.Module(body=[], type_ignores=[])
                self.parse_errors = getattr(self, "parse_errors", [])
                self.parse_errors.append(Finding(
                    "parse", rel, getattr(e, "lineno", 0) or 0,
                    f"cannot parse: {e}", "error"))
                continue
            self.sources[rel] = src
        self.parse_errors = getattr(self, "parse_errors", [])

    def has(self, rel: str) -> bool:
        return rel in self.trees


# ------------------------------------------------------------- ast helpers
def _alias_map(tree: ast.AST) -> Dict[str, str]:
    """name-in-scope -> dotted module/attr it resolves to, from every
    import statement in the file (module or function scope)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.level == 0 \
                and node.module:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _resolved(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Dotted name with the leading alias expanded through the file's
    imports: ``np.random.x`` -> ``numpy.random.x``."""
    d = _dotted(node)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    full = aliases.get(head)
    if full is None:
        return d
    return f"{full}.{rest}" if rest else full


def _is_jax_jit(node: ast.AST, aliases: Dict[str, str]) -> bool:
    return _resolved(node, aliases) in ("jax.jit", "jax.api.jit")


def _identifiers(node: ast.AST) -> set:
    """All Name ids and Attribute attrs mentioned in an expression."""
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _module_scope_nodes(mod: ast.AST, node_types) -> List[ast.AST]:
    """Every node of ``node_types`` that executes at module import time:
    the whole module tree — including top-level try/if bodies (the
    optional-dependency pattern runs in every importer) — minus
    def/class/lambda subtrees (deferred execution)."""
    out: List[ast.AST] = []

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, node_types):
                out.append(child)
            visit(child)

    visit(mod)
    return out


def _module_scope_calls(mod: ast.AST) -> List[ast.Call]:
    return _module_scope_nodes(mod, ast.Call)


# =================================================================== rules
def rule_jit_host_sync(tree: SourceTree) -> List[Finding]:
    """host I/O, clocks, host RNG and device syncs in jit-reachable code."""
    findings = []
    seen = set()  # (rel, line, hazard): nested defs are walked twice
    for rel, mod in tree.trees.items():
        aliases = _alias_map(mod)
        in_scope_file = (rel in JIT_SCOPE_FILES
                         or rel.startswith(JIT_SCOPE_PREFIXES))
        for fn in ast.walk(mod):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            jitted = any(_is_jax_jit(dec, aliases)
                         or (isinstance(dec, ast.Call)
                             and _is_jax_jit(dec.func, aliases))
                         for dec in fn.decorator_list)
            if not (in_scope_file or jitted):
                continue
            where = (f"@jax.jit function '{fn.name}'" if jitted
                     else f"jit-reachable module function '{fn.name}'")
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                resolved = _resolved(call.func, aliases)
                hazard = None
                if resolved in HOST_SYNC_EXACT:
                    hazard = (resolved, HOST_SYNC_EXACT[resolved])
                elif resolved:
                    for pref, why in HOST_SYNC_PREFIXES.items():
                        if resolved == pref or \
                                resolved.startswith(pref + "."):
                            hazard = (resolved, why)
                            break
                if hazard is None and isinstance(call.func, ast.Attribute) \
                        and call.func.attr in HOST_SYNC_METHODS:
                    hazard = (f".{call.func.attr}()",
                              HOST_SYNC_METHODS[call.func.attr])
                if hazard and (rel, call.lineno, hazard[0]) not in seen:
                    seen.add((rel, call.lineno, hazard[0]))
                    findings.append(Finding(
                        "jit-host-sync", rel, call.lineno,
                        f"{hazard[0]} inside {where}: {hazard[1]} — "
                        f"hoist it out of the jitted path (or "
                        f"jax.debug.print / a traced PRNG key)"))
    return findings


def rule_jit_static_args(tree: SourceTree) -> List[Finding]:
    """hashable/complete static_argnums|argnames at jax.jit/remat sites."""
    findings = []
    for rel, mod in tree.trees.items():
        aliases = _alias_map(mod)
        # module-level defs/lambdas for call-form target resolution
        local_defs: Dict[str, ast.AST] = {}
        for node in mod.body:
            if isinstance(node, ast.FunctionDef):
                local_defs[node.name] = node
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Lambda):
                local_defs[node.targets[0].id] = node.value

        def check_static_kwargs(call: ast.Call, what: str):
            """Sub-check A: literal static_argnums/argnames hashability.
            Non-literal elements (names, attribute lookups) are legal —
            only provably-wrong literals are flagged; a symbolic element
            makes coverage unknowable, so sub-check B is skipped too."""
            covered_pos, covered_names = set(), set()
            resolvable = True
            for kw in call.keywords:
                if kw.arg not in ("static_argnums", "static_argnames"):
                    continue
                want_str = kw.arg == "static_argnames"
                v = kw.value
                if isinstance(v, (ast.Set, ast.Dict)):
                    findings.append(Finding(
                        "jit-static-args", rel, v.lineno,
                        f"{kw.arg} of {what} must be "
                        + ("a str or tuple of strs" if want_str
                           else "an int or tuple of ints")
                        + f", not a {type(v).__name__.lower()} literal "
                          f"(unhashable/wrong container)"))
                    continue
                elts = (v.elts if isinstance(v, (ast.Tuple, ast.List))
                        else [v] if isinstance(v, ast.Constant)
                        else None)
                if elts is None:       # wholly symbolic: can't evaluate
                    resolvable = False
                    continue
                for e in elts:
                    if not isinstance(e, ast.Constant):
                        resolvable = False  # symbolic element: unknowable
                        continue
                    ok = (isinstance(e.value, str) if want_str
                          else isinstance(e.value, int)
                          and not isinstance(e.value, bool))
                    if not ok:
                        findings.append(Finding(
                            "jit-static-args", rel, e.lineno,
                            f"{kw.arg} of {what} must be "
                            + ("a str or tuple of strs"
                               if want_str else "an int or tuple of ints")
                            + f", got {e.value!r}"))
                    elif want_str:
                        covered_names.add(e.value)
                    else:
                        covered_pos.add(e.value)
            return covered_pos, covered_names, resolvable

        def check_target(fn_node, covered_pos, covered_names, site_line,
                         what):
            """Sub-check B: bool/str-typed params must be static.
            Positional indices span posonlyargs + args (jax counts them
            together); keyword-only params are coverable by name only."""
            args_node = fn_node.args
            params = list(getattr(args_node, "posonlyargs", ())) \
                + list(args_node.args)
            defaults = [None] * (len(params) - len(args_node.defaults)) \
                + list(args_node.defaults)
            rows = [(i, p, d, i in covered_pos or p.arg in covered_names)
                    for i, (p, d) in enumerate(zip(params, defaults))]
            rows += [(None, p, d, p.arg in covered_names)
                     for p, d in zip(args_node.kwonlyargs,
                                     args_node.kw_defaults)]
            for _, p, default, covered in rows:
                name = p.arg
                if name in ("self", "cls") or covered:
                    continue
                bad_type = None
                ann = getattr(p, "annotation", None)
                if isinstance(ann, ast.Name) and ann.id in ("bool", "str"):
                    bad_type = ann.id
                elif isinstance(ann, ast.Constant) and ann.value in (
                        "bool", "str"):
                    bad_type = ann.value
                elif isinstance(default, ast.Constant) and isinstance(
                        default.value, (bool, str)):
                    bad_type = type(default.value).__name__
                if bad_type:
                    findings.append(Finding(
                        "jit-static-args", rel, site_line,
                        f"{bad_type}-typed parameter '{name}' of {what} is "
                        f"traced — a Python branch on it fails under jit "
                        f"(or silently retraces); add it to "
                        f"static_argnums/static_argnames"))

        for node in ast.walk(mod):
            # decorator form
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    if _is_jax_jit(dec, aliases):
                        check_target(node, set(), set(), node.lineno,
                                     f"jitted '{node.name}'")
                    elif isinstance(dec, ast.Call) and _is_jax_jit(
                            dec.func, aliases):
                        pos, names, resolvable = check_static_kwargs(
                            dec, f"@jax.jit '{node.name}'")
                        if resolvable:
                            check_target(node, pos, names, node.lineno,
                                         f"jitted '{node.name}'")
            # call form
            if isinstance(node, ast.Call):
                resolved = _resolved(node.func, aliases)
                if resolved in ("jax.jit",):
                    what = "jax.jit call"
                    pos, names, resolvable = check_static_kwargs(node, what)
                    if node.args and resolvable:
                        target = node.args[0]
                        fn_node = None
                        if isinstance(target, ast.Lambda):
                            fn_node = target
                        elif isinstance(target, ast.Name):
                            fn_node = local_defs.get(target.id)
                        if fn_node is not None and not isinstance(
                                fn_node, ast.ClassDef):
                            tname = getattr(target, "id", "<lambda>")
                            check_target(fn_node, pos, names, node.lineno,
                                         f"jitted '{tname}'")
                elif resolved in ("jax.checkpoint", "jax.remat",
                                  "flax.linen.remat", "nn.remat"):
                    check_static_kwargs(node, resolved or "remat")
    return findings


def rule_fork_safety(tree: SourceTree) -> List[Finding]:
    """spawn'd worker import closure stays jax-free; spawn context; no module-level locks."""
    entries = [e for e in FORK_ENTRY_FILES if tree.has(e)]
    if not entries:
        return []
    findings = []

    def rel_for_module(module: str) -> Optional[str]:
        base = module.replace(".", "/")
        for cand in (f"{base}.py", f"{base}/__init__.py"):
            if tree.has(cand):
                return cand
        return None

    def module_for_rel(rel: str) -> str:
        mod = rel[:-3] if rel.endswith(".py") else rel
        if mod.endswith("/__init__"):
            mod = mod[: -len("/__init__")]
        return mod.replace("/", ".")

    def parent_inits(rel: str) -> List[str]:
        out = []
        parts = rel.split("/")[:-1]
        for i in range(1, len(parts) + 1):
            init = "/".join(parts[:i]) + "/__init__.py"
            if tree.has(init):
                out.append(init)
        return out

    # BFS over module-scope imports, keeping one witness chain per module.
    chains: Dict[str, Tuple[str, ...]] = {}
    queue: List[str] = []
    for e in entries:
        for r in parent_inits(e) + [e]:
            if r not in chains:
                chains[r] = (e,) if r != e else ()
                queue.append(r)
    while queue:
        rel = queue.pop(0)
        mod = tree.trees[rel]
        pkg = module_for_rel(rel).rsplit(".", 1)[0] \
            if "." in module_for_rel(rel) else ""
        # Module-scope imports INCLUDING those inside top-level try/if
        # (the optional-dependency pattern executes in every worker too);
        # imports inside function bodies are lazy and exempt.
        for node in _module_scope_nodes(mod, (ast.Import, ast.ImportFrom)):
            targets: List[Tuple[str, int]] = []
            if isinstance(node, ast.Import):
                targets = [(a.name, node.lineno) for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base_parts = module_for_rel(rel).split(".")
                    # level=1 in a module means its own package
                    if not rel.endswith("__init__.py"):
                        base_parts = base_parts[:-1]
                    base_parts = base_parts[: len(base_parts)
                                            - (node.level - 1)]
                    base = ".".join(base_parts)
                    base = f"{base}.{node.module}" if node.module else base
                else:
                    base = node.module or ""
                targets = [(base, node.lineno)]
                targets += [(f"{base}.{a.name}", node.lineno)
                            for a in node.names if a.name != "*"]
            for module, lineno in targets:
                root_name = module.split(".")[0]
                if root_name in FORK_FORBIDDEN_ROOTS:
                    chain = " -> ".join(chains[rel] + (rel,))
                    findings.append(Finding(
                        "fork-safety", rel, lineno,
                        f"spawn'd decode workers transitively import "
                        f"'{module}' at module scope (chain: {chain}): "
                        f"each worker pays the full jax import (seconds "
                        f"of spawn latency, 100s of MB RSS) — import it "
                        f"lazily inside the function that needs it"))
                    continue
                sub = rel_for_module(module)
                if sub is None:
                    continue
                for r in parent_inits(sub) + [sub]:
                    if r not in chains:
                        chains[r] = chains[rel] + (rel,)
                        queue.append(r)
        _ = pkg  # (kept for clarity; relative imports resolved above)

    # module-level locks / file handles + non-spawn process creation
    for rel in chains:
        mod = tree.trees[rel]
        aliases = _alias_map(mod)
        for node in ast.walk(mod):
            if not isinstance(node, ast.Call):
                continue
            resolved = _resolved(node.func, aliases)
            if resolved in ("multiprocessing.get_context",):
                ok = (node.args and isinstance(node.args[0], ast.Constant)
                      and node.args[0].value == "spawn")
                if not ok:
                    findings.append(Finding(
                        "fork-safety", rel, node.lineno,
                        "worker processes must use get_context('spawn') — "
                        "fork after jax/XLA init duplicates runtime "
                        "threads and locks into a broken child"))
            elif resolved in ("multiprocessing.Process",):
                findings.append(Finding(
                    "fork-safety", rel, node.lineno,
                    "bare multiprocessing.Process uses the platform "
                    "default start method (fork on Linux) — use "
                    "get_context('spawn').Process"))
        # Resource creation that runs at import time: every Call in the
        # module scope, including inside top-level try/if bodies, but
        # NOT inside def/class/lambda bodies (deferred execution). A
        # pruned recursion — ast.walk can't skip subtrees, and breaking
        # out of it on the first nested def would silently skip sibling
        # calls in the same compound statement.
        for call in _module_scope_calls(mod):
            resolved = _resolved(call.func, aliases)
            if resolved in ("open", "threading.Lock", "threading.RLock",
                            "threading.Condition", "multiprocessing.Lock"):
                findings.append(Finding(
                    "fork-safety", rel, call.lineno,
                    f"module-level {resolved}() in a "
                    f"worker-imported module: created at import "
                    f"time in every spawned worker; handles/locks "
                    f"captured this way are a deadlock hazard"))
    return findings


def rule_signal_safety(tree: SourceTree) -> List[Finding]:
    """signal handlers only set flags, log and re-raise."""
    findings = []
    for rel, mod in tree.trees.items():
        aliases = _alias_map(mod)
        # registration sites: signal.signal(sig, handler)
        module_fns = {n.name: n for n in mod.body
                      if isinstance(n, ast.FunctionDef)}
        classes = {n.name: n for n in mod.body
                   if isinstance(n, ast.ClassDef)}

        def enclosing_class(node) -> Optional[ast.ClassDef]:
            for cls in classes.values():
                for sub in ast.walk(cls):
                    if sub is node:
                        return cls
            return None

        for node in ast.walk(mod):
            if not (isinstance(node, ast.Call)
                    and _resolved(node.func, aliases) == "signal.signal"
                    and len(node.args) == 2):
                continue
            handler = node.args[1]
            cls = enclosing_class(node)
            target: Optional[ast.FunctionDef] = None
            owner = None
            hd = _dotted(handler)
            if hd and hd.startswith("self.") and cls is not None:
                owner = cls
                target = next((m for m in cls.body
                               if isinstance(m, ast.FunctionDef)
                               and m.name == hd.split(".", 1)[1]), None)
            elif isinstance(handler, ast.Name):
                target = module_fns.get(handler.id)
            if target is None:
                continue  # dynamic handler (restore loops etc.)

            # intra-module transitive walk from the handler
            seen = set()
            stack = [(target, (target.name,))]
            while stack:
                fn, chain = stack.pop()
                if fn.name in seen:
                    continue
                seen.add(fn.name)
                for call in ast.walk(fn):
                    if not isinstance(call, ast.Call):
                        continue
                    d = _dotted(call.func) or ""
                    resolved = _resolved(call.func, aliases) or ""
                    root_name = d.split(".")[0] if d else ""
                    if root_name in SIGNAL_LOG_ROOTS:
                        continue
                    hazard = None
                    if resolved in SIGNAL_DENY_EXACT:
                        hazard = resolved
                    elif resolved.startswith(SIGNAL_DENY_PREFIXES):
                        hazard = resolved
                    elif isinstance(call.func, ast.Attribute) \
                            and call.func.attr in SIGNAL_DENY_METHODS:
                        hazard = d or f".{call.func.attr}"
                    if hazard:
                        via = " -> ".join(chain)
                        findings.append(Finding(
                            "signal-safety", rel, call.lineno,
                            f"signal handler reaches '{hazard}' (via "
                            f"{via}): handlers run at an arbitrary "
                            f"bytecode boundary of the interrupted main "
                            f"thread — only set flags, log, and re-raise "
                            f"(the loop does the real work at the next "
                            f"chunk boundary)"))
                        continue
                    # recurse into same-module callees
                    callee = None
                    if d.startswith("self.") and owner is not None:
                        callee = next(
                            (m for m in owner.body
                             if isinstance(m, ast.FunctionDef)
                             and m.name == d.split(".", 1)[1]), None)
                    elif isinstance(call.func, ast.Name):
                        callee = module_fns.get(call.func.id)
                    if callee is not None and callee.name not in seen:
                        stack.append((callee, chain + (callee.name,)))
    return findings


def rule_host_isolation(tree: SourceTree) -> List[Finding]:
    """serving control-plane modules stay jax-free at module scope."""
    findings = []
    for rel in HOST_ONLY_FILES:
        if not tree.has(rel):
            continue
        mod = tree.trees[rel]
        for node in _module_scope_nodes(mod, (ast.Import, ast.ImportFrom)):
            if isinstance(node, ast.Import):
                modules = [(a.name, node.lineno) for a in node.names]
            else:
                if node.level:  # relative: stays inside tpu_resnet
                    continue
                modules = [(node.module or "", node.lineno)]
            for module, lineno in modules:
                if module.split(".")[0] in FORK_FORBIDDEN_ROOTS:
                    findings.append(Finding(
                        "host-isolation", rel, lineno,
                        f"module-scope import of '{module}' in a "
                        f"host-isolated serving module: the router/"
                        f"batcher must come up on a machine whose "
                        f"accelerator stack is broken, and stdlib-only "
                        f"consumers (loadgen, doctor, supervise) import "
                        f"this module backend-free — import it lazily "
                        f"inside the function that needs it"))
    return findings


def rule_registry_scope(tree: SourceTree) -> List[Finding]:
    """jax.jit/pjit construction only in registry-owned modules."""
    findings = []
    jit_names = ("jax.jit", "jax.api.jit", "pjit", "jax.pjit",
                 "jax.experimental.pjit.pjit")
    for rel, mod in tree.trees.items():
        if not rel.startswith("tpu_resnet/") \
                or rel in REGISTRY_SCOPE_FILES \
                or rel.startswith(REGISTRY_SCOPE_PREFIXES):
            continue
        aliases = _alias_map(mod)
        sites = []
        for node in ast.walk(mod):
            if isinstance(node, ast.Call) \
                    and _resolved(node.func, aliases) in jit_names:
                sites.append(node.lineno)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    if _resolved(target, aliases) in jit_names:
                        sites.append(dec.lineno)
        for lineno in sorted(set(sites)):
            findings.append(Finding(
                "registry-scope", rel, lineno,
                "direct jax.jit/pjit construction outside the "
                "registry-owned modules: route the program through "
                "tpu_resnet/programs/registry.py (or one of the "
                "canonical constructors in REGISTRY_SCOPE_FILES, "
                "analysis/jaxlint.py) so its key spelling, golden "
                "identity, donation contract and the persistent AOT "
                "executable cache all see it — a bypassed program "
                "re-pays cold-start XLA compiles on every restart and "
                "is invisible to `tpu-resnet check` (docs/CHECKS.md)"))
    return findings


def rule_sharding_scope(tree: SourceTree) -> List[Finding]:
    """NamedSharding/with_sharding_constraint only in partitioner-owned
    modules."""
    findings = []
    target_names = ("jax.sharding.NamedSharding", "NamedSharding",
                    "jax.lax.with_sharding_constraint",
                    "with_sharding_constraint",
                    "jax.experimental.pjit.with_sharding_constraint")
    for rel, mod in tree.trees.items():
        if not rel.startswith("tpu_resnet/") \
                or rel in SHARDING_SCOPE_FILES:
            continue
        aliases = _alias_map(mod)
        sites = []
        for node in ast.walk(mod):
            if isinstance(node, ast.Call) \
                    and _resolved(node.func, aliases) in target_names:
                sites.append(node.lineno)
        for lineno in sorted(set(sites)):
            findings.append(Finding(
                "sharding-scope", rel, lineno,
                "NamedSharding construction / with_sharding_constraint "
                "outside the partitioner-owned modules: sharding "
                "decisions belong to parallel.StatePartitioner and the "
                "documented scope (SHARDING_SCOPE_FILES, "
                "analysis/jaxlint.py) — a sharding injected from "
                "anywhere else changes the compiled program's "
                "collective structure behind the golden comms ledgers' "
                "back (analysis/collectives.py), exactly the drift "
                "check engine 5 exists to catch (docs/CHECKS.md)"))
    return findings


def rule_guard_parity(tree: SourceTree) -> List[Finding]:
    """build_model validation mirrored into public constructors (ADVICE r4)."""
    findings = []

    def find_fn(mod: ast.AST, qualname: str) -> Optional[ast.FunctionDef]:
        parts = qualname.split(".")
        scope = mod.body
        node = None
        for i, part in enumerate(parts):
            node = next((n for n in scope
                         if isinstance(n, (ast.FunctionDef, ast.ClassDef))
                         and n.name == part), None)
            if node is None:
                return None
            scope = getattr(node, "body", [])
        return node if isinstance(node, ast.FunctionDef) else None

    for rel, qualname, req, why in GUARD_PARITY_REQS:
        if not tree.has(rel):
            continue
        fn = find_fn(tree.trees[rel], qualname)
        if fn is None:
            findings.append(Finding(
                "guard-parity", rel, 0,
                f"'{qualname}' not found — the guard-parity contract "
                f"names it ({why}); update analysis/jaxlint.py if it "
                f"moved intentionally"))
            continue
        kind, _, arg = req.partition(":")
        ok = False
        if kind == "calls":
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    d = _dotted(node.func) or ""
                    if d == arg or d.endswith("." + arg):
                        ok = True
                        break
        elif kind == "guard":
            idents = set(arg.split("&"))
            for node in ast.walk(fn):
                if isinstance(node, ast.If) \
                        and idents <= _identifiers(node.test) \
                        and any(isinstance(s, ast.Raise)
                                for s in ast.walk(node)):
                    ok = True
                    break
        if not ok:
            need = (f"a call to {arg}()" if kind == "calls"
                    else f"an If over {arg.replace('&', ' and ')} that "
                         f"raises")
            findings.append(Finding(
                "guard-parity", rel, fn.lineno,
                f"'{qualname}' is missing {need}: {why}"))
    return findings


RULES = {
    "jit-host-sync": rule_jit_host_sync,
    "jit-static-args": rule_jit_static_args,
    "fork-safety": rule_fork_safety,
    "signal-safety": rule_signal_safety,
    "host-isolation": rule_host_isolation,
    "registry-scope": rule_registry_scope,
    "sharding-scope": rule_sharding_scope,
    "guard-parity": rule_guard_parity,
}


def run_jaxlint(root: str, select: Optional[Iterable[str]] = None,
                files: Optional[Iterable[str]] = None,
                tree: Optional["SourceTree"] = None) -> List[Finding]:
    """Run the AST rules over ``root``; pragma suppression applied.

    ``select`` limits to a subset of rule ids; ``files`` limits the file
    set (root-relative paths); ``tree`` reuses a pre-parsed SourceTree
    (the CLI parses once and shares it across the AST engines)."""
    tree = tree if tree is not None else SourceTree(root, files=files)
    selected = set(select) if select else set(RULES)
    unknown = selected - set(RULES)
    if unknown:
        raise ValueError(f"unknown rule(s) {sorted(unknown)}; "
                         f"have {sorted(RULES)}")
    findings = list(tree.parse_errors)
    for rule_id in sorted(selected):
        findings.extend(RULES[rule_id](tree))
    return apply_pragmas(findings, tree.sources)
