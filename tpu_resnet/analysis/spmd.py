"""`spmd` — SPMD-divergence lint, engine 4b of `tpu-resnet check`.

ROADMAP item 1 moves this repo to one process per host on a pod-scale
``("batch", "model")`` mesh. On a pod, every process must execute the
SAME program in the same order: control flow that diverges by
``process_index`` around a compile, a registry dispatch or a collective
is no longer an exception on one host — it is a silent all-host HANG
(process 0 sits in a collective the other processes never entered). The
GSPMD/pjit literature (PAPERS: "GSPMD", "Scalable Training of Language
Models using JAX pjit and TPUv4") kills this class by construction:
single program, sharding annotations only, host-divergent work limited
to I/O. This engine makes that discipline a checked rule before any pod
exists.

Rules (each with a seeded fixture in tests/fixtures/analysis/):

process-divergent-dispatch  an ``if`` conditioned on process identity
                            (``process_index()``/``is_primary()``/
                            ``process_id``) whose gated branch builds or
                            dispatches a compiled program (``jax.jit``/
                            ``pjit``/``make_jaxpr``/the repo's canonical
                            step constructors/the program registry) or
                            runs a collective (``jax.lax.psum``-family,
                            ``multihost_utils``). Host-side primary-only
                            work (logging, metrics files, checkpoint
                            bookkeeping) is exactly what the guard is
                            FOR and stays silent.
primary-only-write          the shared ``train_dir`` artifacts
                            (manifest.json, topology.json, …) each have
                            ONE canonical atomic, primary-only writer
                            (``obs/manifest.write_manifest``,
                            ``resilience/elastic.write_topology``, …).
                            Any other function that opens one of them
                            for writing is a finding — on a shared
                            train_dir, N processes writing the same file
                            is a torn-record generator, and the helper
                            discipline (tmp + os.replace + is_primary)
                            is the established fix. The allowlist is
                            verified against the tree, so a renamed
                            helper fails loudly instead of silently
                            un-protecting its artifact.
unordered-iteration-to-program  iteration over a ``set`` literal /
                            ``set()``/``frozenset()`` value (or an
                            unsorted ``os.listdir``/``glob.glob``)
                            inside the program-construction modules.
                            Python set order varies across processes
                            (PYTHONHASHSEED); feeding it into program
                            construction or key spelling makes two
                            hosts build different programs — the same
                            divergence class, one layer down. Wrap the
                            iterable in ``sorted(...)``.

Pure ``ast`` — never imports jax; same Finding/pragma/baseline machinery
as jaxlint and the concurrency engine.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from tpu_resnet.analysis.findings import Finding, apply_pragmas
from tpu_resnet.analysis.jaxlint import (SourceTree, _alias_map, _dotted,
                                         _identifiers, _resolved)

# Identifiers in an `if` test that mark process-divergent control flow.
PROCESS_IDENTITY = {"process_index", "is_primary", "process_id"}

# Program construction / dispatch / collective markers.
_DISPATCH_EXACT = {
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit", "jax.make_jaxpr",
    "jax.distributed.initialize",
}
_DISPATCH_PREFIXES = ("jax.experimental.multihost_utils",)
# jax.lax collectives + multihost utils, matched as attribute/function
# names (psum through an alias, multihost_utils.sync_global_devices...).
_COLLECTIVE_NAMES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "reduce_scatter", "sync_global_devices",
    "process_allgather", "broadcast_one_to_all",
}
# The repo's canonical compiled-program constructors (train/step.py,
# data/device_data.py, programs/registry.py): gating any of these on
# process identity diverges the compiled-program set across hosts.
_REPO_CONSTRUCTORS = {
    "shard_step", "staged_chunk_jit", "compile_staged_stream_steps",
    "compile_resident_steps", "make_train_step", "make_eval_step",
    "build_eval_step", "wrap_train_step", "staged_chunk_hook",
}
# Registry dispatch: `<...registry...>.wrap(...)`.
_REGISTRY_METHODS = {"wrap"}

# One canonical writer per shared train_dir artifact. Writes of these
# filenames anywhere else in the package are findings; the topology.json
# / manifest.json discipline (atomic tmp+rename, primary-only) becomes a
# rule instead of a convention. export/serialize.py owns the *export
# bundle's* manifest.json (a different directory, same basename).
SHARED_ARTIFACTS: Dict[str, Tuple[Tuple[str, str], ...]] = {
    "manifest.json": (("tpu_resnet/obs/manifest.py", "write_manifest"),
                      ("tpu_resnet/export/serialize.py", "save_inference")),
    "topology.json": (("tpu_resnet/resilience/elastic.py",
                       "write_topology"),),
    "telemetry.json": (("tpu_resnet/obs/server.py",
                        "TelemetryServer.maybe_start"),),
    "flops.json": (("tpu_resnet/obs/mfu.py", "FlopsRegistry.save"),),
    "memory.json": (("tpu_resnet/obs/memory.py", "MemoryLedger.save"),),
    "autotune.json": (("tpu_resnet/ops/autotune.py", "dump"),),
    "oom_report.json": (("tpu_resnet/obs/memory.py", "write_oom_report"),),
}

# Program-construction / key-spelling modules: set-order feeding these
# is the cross-host divergence hazard the third rule pins.
PROGRAM_SCOPE_FILES = (
    "tpu_resnet/programs/registry.py",
    "tpu_resnet/programs/__init__.py",
    "tpu_resnet/train/step.py",
    "tpu_resnet/data/device_data.py",
    "tpu_resnet/analysis/configmatrix.py",
    "tpu_resnet/analysis/memorybudget.py",
    "tpu_resnet/tools/sweep_measure.py",
    "tpu_resnet/obs/mfu.py",
    "tpu_resnet/obs/memory.py",
    "tpu_resnet/parallel/partition.py",
    "tpu_resnet/parallel/zero.py",
)


def _functions(mod: ast.AST):
    """(qualname, node) for module functions and class methods."""
    for node in mod.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield f"{node.name}.{sub.name}", sub


def _dispatch_marker(call: ast.Call, aliases) -> Optional[str]:
    resolved = _resolved(call.func, aliases) or ""
    if resolved in _DISPATCH_EXACT:
        return resolved
    if resolved.startswith(_DISPATCH_PREFIXES):
        return resolved
    tail = resolved.rsplit(".", 1)[-1] if resolved else ""
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr in _COLLECTIVE_NAMES:
            return f".{attr}()"
        if attr in _REGISTRY_METHODS:
            recv = _dotted(call.func.value) or ""
            if "registry" in recv.lower():
                return f"{recv}.{attr}()"
    if tail in _REPO_CONSTRUCTORS or (
            isinstance(call.func, ast.Name)
            and call.func.id in _REPO_CONSTRUCTORS):
        return tail or call.func.id
    if tail in _COLLECTIVE_NAMES:
        return tail
    return None


def rule_process_divergent_dispatch(tree: SourceTree) -> List[Finding]:
    """process-identity-gated jit/registry dispatch or collective."""
    findings = []
    for rel, mod in tree.trees.items():
        if not rel.startswith("tpu_resnet/"):
            continue
        aliases = _alias_map(mod)
        for node in ast.walk(mod):
            if not isinstance(node, ast.If):
                continue
            idents = _identifiers(node.test)
            if not (idents & PROCESS_IDENTITY):
                continue
            for branch, stmts in (("then", node.body),
                                  ("else", node.orelse)):
                for stmt in stmts:
                    for call in ast.walk(stmt):
                        if not isinstance(call, ast.Call):
                            continue
                        marker = _dispatch_marker(call, aliases)
                        if marker is None:
                            continue
                        findings.append(Finding(
                            "process-divergent-dispatch", rel,
                            call.lineno,
                            f"{marker} runs only on some processes "
                            f"(gated by "
                            f"{'/'.join(sorted(idents & PROCESS_IDENTITY))} "
                            f"at line {node.lineno}, {branch} branch): on "
                            f"a multi-host mesh every process must build "
                            f"and dispatch the same program in the same "
                            f"order — a process-divergent collective or "
                            f"compile is an all-host HANG, not an error. "
                            f"Run it unconditionally and gate only the "
                            f"host-side I/O (docs/PARALLELISM.md)"))
                        break  # one finding per call-site is enough;
                        #        keep walking remaining stmts
    return findings


def _expr_artifacts(node: ast.AST, tainted: Dict[str, set]) -> set:
    """Artifact names an expression's value may name: exact string
    constants plus names already tainted by such a constant."""
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and \
                sub.value in SHARED_ARTIFACTS:
            out.add(sub.value)
        elif isinstance(sub, ast.Name) and sub.id in tainted:
            out |= tainted[sub.id]
    return out


def _artifact_writers(mod: ast.AST, rel: str, aliases):
    """(artifact, qualname, line) for every function in ``rel`` that
    opens a shared artifact FOR WRITING. The artifact must flow into
    the write call's path expression — exact string constants (the
    ``os.path.join(dir, "manifest.json")`` idiom; substrings would
    false-positive on docstrings and cousin filenames like
    golden_memory.json) propagated through local assignments (``path =
    join(...); tmp = path + ".tmpN"; open(tmp, "w")``). A function that
    merely READS an artifact while writing some unrelated file is not a
    writer."""
    for qualname, fn in _functions(mod):
        # local taint: name -> artifact set, two passes for the
        # path-then-tmp chain.
        tainted: Dict[str, set] = {}
        for _ in range(2):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                arts = _expr_artifacts(node.value, tainted)
                if not arts:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        tainted.setdefault(t.id, set()).update(arts)
        hits: Dict[str, int] = {}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            resolved = _resolved(node.func, aliases) or ""
            target = None
            if resolved == "open" and len(node.args) >= 2 and \
                    isinstance(node.args[1], ast.Constant) and \
                    str(node.args[1].value).startswith(("w", "a")):
                target = node.args[0]
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("write_text", "write_bytes"):
                target = node.func.value
            elif resolved == "os.replace" and len(node.args) == 2:
                target = node.args[1]
            if target is None:
                continue
            for artifact in _expr_artifacts(target, tainted):
                hits.setdefault(artifact, node.lineno)
        for artifact, line in sorted(hits.items()):
            yield artifact, qualname, line


# Diagnostic harnesses whose artifact writes land only in scratch dirs
# they own (doctor drills fabricate/inspect artifacts in tempdirs) —
# exempt from the shared-train_dir writer discipline.
_DIAGNOSTIC_FILES = ("tpu_resnet/tools/doctor.py",)


def rule_primary_only_write(tree: SourceTree) -> List[Finding]:
    """shared train_dir artifacts only through their canonical writers."""
    findings = []
    for rel, mod in tree.trees.items():
        if not rel.startswith("tpu_resnet/") or rel in _DIAGNOSTIC_FILES:
            continue
        aliases = _alias_map(mod)
        for artifact, qualname, line in _artifact_writers(mod, rel,
                                                          aliases):
            allowed = SHARED_ARTIFACTS[artifact]
            if (rel, qualname) in allowed:
                continue
            canonical = ", ".join(f"{p}::{q}" for p, q in allowed)
            findings.append(Finding(
                "primary-only-write", rel, line,
                f"'{qualname}' writes the shared train_dir artifact "
                f"'{artifact}' directly — on a shared directory every "
                f"process would race this write (torn/clobbered "
                f"records). Route it through the canonical atomic, "
                f"primary-only writer ({canonical}), or add the new "
                f"writer to analysis/spmd.py SHARED_ARTIFACTS with the "
                f"same tmp+os.replace+is_primary discipline"))
    # The allowlist must stay anchored to real code: a renamed canonical
    # writer is reported (like guard-parity does), never silently
    # un-protecting its artifact.
    for artifact, pairs in sorted(SHARED_ARTIFACTS.items()):
        for rel, qualname in pairs:
            if not tree.has(rel):
                continue
            if not any(q == qualname for q, _ in _functions(tree.trees[rel])):
                findings.append(Finding(
                    "primary-only-write", rel, 0,
                    f"canonical writer '{qualname}' of '{artifact}' not "
                    f"found in {rel} — the primary-only-write contract "
                    f"names it; update analysis/spmd.py SHARED_ARTIFACTS "
                    f"if it moved intentionally"))
    return findings


def _unordered_iterable(node: ast.AST, aliases) -> Optional[str]:
    if isinstance(node, ast.Set):
        return "set literal"
    if isinstance(node, ast.SetComp):
        return "set comprehension"
    if isinstance(node, ast.Call):
        resolved = _resolved(node.func, aliases) or ""
        if resolved in ("set", "frozenset"):
            return f"{resolved}()"
        if resolved in ("os.listdir", "glob.glob", "glob.iglob"):
            return resolved
    return None


def rule_unordered_iteration(tree: SourceTree) -> List[Finding]:
    """set/listdir-order feeding program construction or key spelling."""
    findings = []
    for rel in PROGRAM_SCOPE_FILES:
        if not tree.has(rel):
            continue
        mod = tree.trees[rel]
        aliases = _alias_map(mod)
        iter_sites: List[Tuple[ast.AST, str]] = []
        for node in ast.walk(mod):
            if isinstance(node, ast.For):
                kind = _unordered_iterable(node.iter, aliases)
                if kind:
                    iter_sites.append((node.iter, kind))
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                   ast.DictComp, ast.SetComp)):
                for gen in node.generators:
                    kind = _unordered_iterable(gen.iter, aliases)
                    if kind:
                        iter_sites.append((gen.iter, kind))
        for site, kind in iter_sites:
            findings.append(Finding(
                "unordered-iteration-to-program", rel, site.lineno,
                f"iteration over an unordered {kind} in a "
                f"program-construction module: set/scan order varies "
                f"across processes (PYTHONHASHSEED, filesystem), so two "
                f"hosts can build programs or spell registry keys in "
                f"different orders — wrap it in sorted(...) "
                f"(docs/PARALLELISM.md)"))
    return findings


SPMD_RULES = {
    "process-divergent-dispatch": rule_process_divergent_dispatch,
    "primary-only-write": rule_primary_only_write,
    "unordered-iteration-to-program": rule_unordered_iteration,
}


def run_spmd(root: str, select: Optional[Iterable[str]] = None,
             files: Optional[Iterable[str]] = None,
             tree: Optional[SourceTree] = None) -> List[Finding]:
    """Run the SPMD-divergence rules over ``root``; pragma suppression
    applied. Same contract as ``run_jaxlint``. ``tree`` reuses a
    pre-parsed SourceTree; parse failures are findings here too (see
    run_concurrency)."""
    tree = tree if tree is not None else SourceTree(root, files=files)
    selected = set(select) if select else set(SPMD_RULES)
    unknown = selected - set(SPMD_RULES)
    if unknown:
        raise ValueError(f"unknown rule(s) {sorted(unknown)}; "
                         f"have {sorted(SPMD_RULES)}")
    findings: List[Finding] = list(tree.parse_errors)
    for rule_id in sorted(selected):
        findings.extend(SPMD_RULES[rule_id](tree))
    return apply_pragmas(findings, tree.sources)
