"""Golden memory budgets — the HBM twin of the golden-jaxpr verifier.

The config-matrix verifier (configmatrix.py) pins WHAT program each
supported configuration compiles to; this engine pins what that program
COSTS in device memory. For every traced matrix entry it compiles the
real train-step program on a concrete CPU mesh — the same
``shard_step`` / staged-chunk constructors the loop uses, donation
included — extracts ``compiled.memory_analysis()`` into a budget
(argument / output / temp / alias / generated-code bytes) and compares
it against ``analysis/golden_memory.json`` inside a tolerance band:

- a change that silently doubles temp HBM fails ``tpu-resnet check``
  exactly like a jaxpr drift (temp is what remat/fusion decisions move);
- a broken donation collapses ``alias_bytes`` to ~0 — caught as its own
  named finding, because an undonated state double-buffers every
  parameter and optimizer slot on every step;
- the future ZeRO-style optimizer-sharding PR (arXiv:2004.13336) proves
  its ~N× per-device optimizer-state cut as a reviewable golden diff
  instead of a claim.

Budgets are defined over the CPU compile (the tier-1/CI environment,
same rule as the jaxpr goldens): absolute bytes differ on TPU, but the
*shape* of the budget — donation credit, temp growth, layout changes —
drifts identically, and CPU is where the merge gate runs. Off-CPU the
compare is skipped with a warning. Unlike the abstract jaxpr trace this
engine pays real XLA compiles (~minutes for the full matrix), so the
CLI exposes ``--skip-memory`` and the tier-1 suite checks a fast subset
with the full set in the slow tier (docs/CHECKS.md).

Regenerate intentionally with ``python -m tpu_resnet check
--update-golden`` and say why in the PR.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from tpu_resnet.analysis.configmatrix import MATRIX, MatrixEntry
from tpu_resnet.analysis.findings import Finding
from tpu_resnet.obs.comms import hlo_text_of
from tpu_resnet.obs.memory import BUDGET_COMPONENTS, budget_from_compiled

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden_memory.json")
GOLDEN_FORMAT = 1
# Relative band per component: XLA's buffer assignment is deterministic
# for a fixed version, but minor releases shuffle temp layouts by a few
# percent — 10% is wide enough to survive that and far too narrow to
# hide a doubled temp arena or a dropped donation. Small components also
# get an absolute slack so a 4 KiB scratch move can't fail a check.
DEFAULT_TOLERANCE = 0.10
SLACK_BYTES = 65536


def _compile_serve_budget(entry: MatrixEntry) -> Tuple[dict, object]:
    """Serve rows compile the bucket inference program instead — the
    exact ``make_serve_infer`` jit the CheckpointBackend warms, over the
    exact argument avals it wraps (the int8 quantized tree for
    ``quantize="int8"`` rows). The analytic headline here is
    ``weight_argument_bytes`` — the weight-side argument footprint
    (ops/quant.py tree arithmetic, exact compare) — which is what the
    quantized/f32 twin gate in tests/test_quant.py reads: the int8 arm
    must land at ≤0.30x of its f32 twin, the memory acceptance artifact
    of the quantization PR (same pattern as the ZeRO-1 opt-slot twin)."""
    import jax
    import jax.numpy as jnp

    from tpu_resnet.models import build_model
    from tpu_resnet.ops import quant as quant_lib
    from tpu_resnet.serve.infer import make_serve_infer

    cfg = entry.to_config()
    quant_lib.check_quantize_config(cfg, entry.data_axis)
    model = build_model(cfg)
    size = cfg.data.resolved_image_size
    sample = jnp.zeros((1, size, size, 3), jnp.float32)

    def init_vars(rng):
        v = model.init(rng, sample, train=False)
        return {"params": v["params"],
                "batch_stats": v.get("batch_stats", {})}

    var_sds = jax.eval_shape(init_vars, jax.random.PRNGKey(0))
    if cfg.serve.quantize == "int8":
        var_sds = jax.eval_shape(quant_lib.quantize_variables, var_sds)
    imgs = jax.ShapeDtypeStruct((entry.batch, size, size, 3), jnp.uint8)
    compiled = make_serve_infer(cfg).lower(var_sds, imgs).compile()
    budget = budget_from_compiled(compiled)
    if budget is None:
        raise RuntimeError("backend reported no memory analysis for the "
                           "compiled program")
    budget["partition"] = entry.partition
    budget["weight_argument_bytes"] = quant_lib.tree_argument_bytes(var_sds)
    return budget, compiled


def _compile_train_budget(entry: MatrixEntry) -> Tuple[dict, object]:
    """Compile the entry's REAL train program on a concrete mesh (the
    loop's own constructors, donation on) and return ``(budget,
    compiled)``. Needs ``data_axis * model_axis`` local devices — the
    caller skips otherwise."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from tpu_resnet.data import augment as aug_lib
    from tpu_resnet.data.device_data import staged_chunk_jit
    from tpu_resnet.models import build_model
    from tpu_resnet.train import schedule as sched_lib
    from tpu_resnet.train.state import init_state
    from tpu_resnet.train.step import (check_step_config, make_train_step,
                                       shard_step)

    cfg = entry.to_config()
    check_step_config(cfg, entry.data_axis)
    model = build_model(cfg)
    schedule = sched_lib.build_schedule(cfg.optim, cfg.train)
    size = cfg.data.resolved_image_size
    sample = jnp.zeros((1, size, size, 3), jnp.float32)
    state_sds = jax.eval_shape(
        lambda r: init_state(model, cfg.optim, schedule, r, sample),
        jax.random.PRNGKey(0))
    n = entry.data_axis * entry.model_axis
    mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(
        entry.data_axis, entry.model_axis), ("data", "model"))
    per_replica = (not cfg.model.sync_bn) and entry.data_axis > 1
    augment_fn, _ = aug_lib.get_augment_fns(cfg.data.dataset)
    from tpu_resnet.parallel.partition import StatePartitioner

    partitioner = StatePartitioner(mesh, entry.partition)
    state_sharding = (partitioner.state_shardings(state_sds)
                      if partitioner.is_sharded else None)
    base = make_train_step(model, cfg.optim, schedule,
                           cfg.data.num_classes, augment_fn,
                           base_rng=jax.random.PRNGKey(0), mesh=mesh,
                           grad_axis="data" if per_replica else None,
                           partitioner=partitioner)
    imgs = jax.ShapeDtypeStruct((entry.batch, size, size, 3), jnp.uint8)
    labels = jax.ShapeDtypeStruct((entry.batch,), jnp.int32)
    if entry.builder == "staged-chunk":
        # The fused chunk program the streaming/double-buffered H2D
        # input edges dispatch, donation on — built by the one canonical
        # constructor the loop uses (device_data.staged_chunk_jit), so
        # this engine compiles EXACTLY the runtime's program.
        jitted = staged_chunk_jit(base, mesh, entry.chunk_steps,
                                  per_replica_bn=per_replica,
                                  state_sharding=state_sharding)
        gi = jax.ShapeDtypeStruct(
            (entry.stage_rows, entry.batch, size, size, 3), jnp.uint8)
        gl = jax.ShapeDtypeStruct((entry.stage_rows, entry.batch),
                                  jnp.int32)
        off = jax.ShapeDtypeStruct((), jnp.int32)
        compiled = jitted.lower(state_sds, gi, gl, off).compile()
    else:
        jitted = shard_step(base, mesh, per_replica_bn=per_replica,
                            state_sharding=state_sharding)
        compiled = jitted.lower(state_sds, imgs, labels).compile()
    budget = budget_from_compiled(compiled)
    if budget is None:
        raise RuntimeError("backend reported no memory analysis for the "
                           "compiled program")
    # Analytic per-component argument bytes under this entry's partition
    # (partitioner.state_argument_bytes): the zero1 optimizer-slot cut
    # becomes a NAMED golden number — the headline acceptance artifact —
    # instead of a delta buried in XLA's aggregate argument_bytes.
    # Deterministic arithmetic, so it rides in the golden entry next to
    # the XLA components (tests gate the zero1/replicated twin ratio).
    budget["partition"] = entry.partition
    budget.update(partitioner.state_argument_bytes(state_sds))
    return budget, compiled


# One compile per entry per process, shared by the memory and
# collectives engines: `tpu-resnet check` runs both over the same
# matrix, and the XLA compile (not the compare) is the whole cost.
# Keyed by entry name; the budget is returned BY COPY so a caller (or a
# golden write) can never mutate the cached truth.
_ARTIFACTS: Dict[str, dict] = {}


def entry_artifacts(entry: MatrixEntry) -> dict:
    """Compile ``entry``'s real program once and return every artifact
    the check engines extract from it: ``budget`` (the golden-memory
    dict) and ``hlo_text`` (the post-SPMD-partitioner HLO the
    collectives engine parses). Cached per entry name for the life of
    the process."""
    art = _ARTIFACTS.get(entry.name)
    if art is None:
        if getattr(entry, "builder", "config") == "serve":
            budget, compiled = _compile_serve_budget(entry)
        else:
            budget, compiled = _compile_train_budget(entry)
        art = {"budget": budget, "hlo_text": hlo_text_of(compiled)}
        _ARTIFACTS[entry.name] = art
    return {"budget": dict(art["budget"]), "hlo_text": art["hlo_text"]}


def compile_entry_budget(entry: MatrixEntry) -> dict:
    """The entry's memory budget (compiling at most once per process —
    see :func:`entry_artifacts`). Serve rows compile the bucket
    inference program, everything else the train step."""
    return entry_artifacts(entry)["budget"]


# The partitioner's analytic breakdown is deterministic arithmetic, so
# it compares EXACTLY (no band): a partial rule regression that shifts
# XLA's aggregate by less than the slack still moves these.
ANALYTIC_COMPONENTS = ("params_argument_bytes", "opt_state_argument_bytes",
                       "batch_stats_argument_bytes",
                       # Serve rows only (0 == 0 elsewhere): the weight-
                       # argument footprint of the bucket program — the
                       # int8/f32 twin-gate numerator/denominator.
                       "weight_argument_bytes")


def _compare(name: str, want: dict, got: dict,
             tolerance: float) -> List[Finding]:
    path = f"<golden-memory>/{name}"
    findings: List[Finding] = []
    for comp in ANALYTIC_COMPONENTS:
        w = int(want.get(comp, 0) or 0)
        g = int(got.get(comp, 0) or 0)
        if g != w:
            findings.append(Finding(
                "golden-memory-drift", path, 0,
                f"{comp} drifted {w:,} -> {g:,} bytes: the state "
                f"partitioner's per-leaf layout for this program changed "
                f"(parallel/partition.py rule set or the state tree "
                f"itself). If intended, regenerate via `python -m "
                f"tpu_resnet check --update-golden` and say why in the "
                f"PR — this component is exact arithmetic, so any drift "
                f"is a real layout change, never compiler noise"))
    for comp in BUDGET_COMPONENTS:
        w = int(want.get(comp, 0) or 0)
        g = int(got.get(comp, 0) or 0)
        if abs(g - w) <= max(tolerance * max(w, g), SLACK_BYTES):
            continue
        if comp == "alias_bytes" and g < w:
            findings.append(Finding(
                "golden-memory-drift", path, 0,
                f"donation-credited (aliased) bytes collapsed "
                f"{w:,} -> {g:,}: state donation broke for this program "
                f"— every step now double-buffers the parameters and "
                f"optimizer slots in HBM. If the donation change is "
                f"intended, regenerate via `python -m tpu_resnet check "
                f"--update-golden` and say why in the PR"))
        else:
            ratio = g / w if w else float("inf")
            findings.append(Finding(
                "golden-memory-drift", path, 0,
                f"{comp} drifted {w:,} -> {g:,} bytes ({ratio:.2f}x), "
                f"outside the ±{tolerance:.0%} band — the compiled "
                f"program's HBM budget changed. If intended (new fusion, "
                f"remat change, layout work), regenerate via `python -m "
                f"tpu_resnet check --update-golden` and say why; if not, "
                f"this is a silent memory regression caught at review "
                f"time"))
    return findings


def load_golden(path: str = GOLDEN_PATH) -> dict:
    try:
        with open(path) as fh:
            return json.load(fh)
    except FileNotFoundError:
        return {"format": GOLDEN_FORMAT, "entries": {}}


def save_golden(golden: dict, path: str = GOLDEN_PATH) -> None:
    golden["entries"] = dict(sorted(golden["entries"].items()))
    with open(path, "w") as fh:
        json.dump(golden, fh, indent=1)
        fh.write("\n")


def verify_memory(entries: Optional[Tuple[MatrixEntry, ...]] = None,
                  update_golden: bool = False,
                  golden_path: str = GOLDEN_PATH,
                  tolerance: Optional[float] = None,
                  progress=None) -> Tuple[List[Finding], dict]:
    """Compile every supported matrix entry and verify (or, with
    ``update_golden``, rewrite) its golden memory budget. Returns
    ``(findings, stats)``. The compare tolerance is recorded in the
    golden file so regeneration and verification always agree."""
    import jax

    entries = MATRIX if entries is None else entries
    golden = load_golden(golden_path)
    tol = (tolerance if tolerance is not None
           else float(golden.get("tolerance", DEFAULT_TOLERANCE)))
    on_cpu = jax.default_backend() == "cpu"
    findings: List[Finding] = []
    stats = {"compiled": 0, "compared": 0, "updated": [],
             "skipped_devices": 0, "failed": 0}

    if not on_cpu:
        # Compare AND regeneration are CPU-only: goldens written from a
        # TPU compile would fail every CI run.
        findings.append(Finding(
            "golden-memory-drift", "<golden-memory>", 0,
            f"golden memory {'update' if update_golden else 'compare'} "
            f"skipped on backend '{jax.default_backend()}' (budgets are "
            f"defined over the CPU compile, like the jaxpr goldens)",
            "warning"))
        return findings, stats

    for entry in entries:
        if entry.expect_error is not None or entry.builder == "ctor-bn-axis":
            continue
        if progress:
            progress(entry.name)
        path = f"<golden-memory>/{entry.name}"
        need = entry.data_axis * entry.model_axis
        if len(jax.devices()) < need:
            stats["skipped_devices"] += 1
            continue
        try:
            budget = compile_entry_budget(entry)
        except Exception as e:  # one broken entry must not cost the rest
            stats["failed"] += 1
            findings.append(Finding(
                "memory-budget", path, 0,
                f"supported combination FAILED to compile for its memory "
                f"budget: {type(e).__name__}: {e}"))
            continue
        stats["compiled"] += 1
        if update_golden:
            golden["entries"][entry.name] = budget
            stats["updated"].append(entry.name)
            continue
        want = golden["entries"].get(entry.name)
        if want is None:
            findings.append(Finding(
                "golden-memory-drift", path, 0,
                "no golden memory budget recorded for this entry — run "
                "`python -m tpu_resnet check --update-golden` and commit "
                "the regenerated analysis/golden_memory.json"))
            continue
        stats["compared"] += 1
        findings.extend(_compare(entry.name, want, budget, tol))

    if update_golden:
        # Prune renamed/removed entries: the golden mirrors MATRIX
        # exactly (must-raise and ctor rows never compile).
        live = {e.name for e in entries
                if e.expect_error is None and e.builder != "ctor-bn-axis"}
        golden["entries"] = {k: v for k, v in golden["entries"].items()
                             if k in live}
        golden["format"] = GOLDEN_FORMAT
        golden["tolerance"] = tol
        golden["jax"] = jax.__version__
        save_golden(golden, golden_path)
    return findings, stats
