"""`concurrency` — thread/lock race detector, engine 4a of `tpu-resnet check`.

Ten modules in this repo spawn threads (micro-batcher, router, prober,
DoubleBufferedH2D, data-engine workers, restore thread, watchdog,
checkpoint poller consumers, telemetry HTTP servers) and their races
have historically been caught only by manual review passes: the PR 5
admission race (a submit racing the drain flip hung its client for the
full wait timeout), the PR 11 hedge attribution bugs (breaker
bookkeeping charged from racing hedge-leg threads), the PR 11 swap-lock
gap (close() tearing the checkpoint manager down under a mid-flight
hot-reload restore). This engine encodes the discipline those fixes
established as checkable rules, ThreadSanitizer-style but static.

Model — the **thread-context graph**, built per class:

- *entry points*: methods (or nested functions) handed to
  ``threading.Thread(target=…)`` / ``threading.Timer`` /
  ``ThreadPoolExecutor.submit``, or referenced in a Thread's ``args``;
  ``do_*`` methods of ``BaseHTTPRequestHandler`` subclasses (each HTTP
  request runs on its own server thread); handlers registered via
  ``signal.signal``.
- *contexts*: each thread entry is one context; each public method is a
  caller context of its own (a class that spawns threads is, by
  construction, driven from more than one thread — the batcher's
  ``submit`` runs on HTTP handler threads while ``drain`` runs on the
  main thread); ``__init__`` is the construction context
  (happens-before every thread start, so its writes are exempt);
  signal handlers interleave with — but never run in parallel to — the
  main thread, so they form a non-concurrent context.
- *shared state*: ``self.*`` attribute accesses, with the lexical
  ``with self._lock:`` guard stack tracked per access. Only attribute
  REBINDS count as writes (item assignment into a dict/list under the
  GIL is atomic; rebind + check-then-act is where the races were).

Rules (each with a seeded fixture in tests/fixtures/analysis/):

unguarded-shared-write   an attribute with an unguarded non-init write
                         that another concurrent context also touches
                         unguarded. Exemptions prove the model honest:
                         channel attributes (``queue.Queue``, ``Event``,
                         locks — their methods are the synchronization),
                         immutable-after-start attributes (written only
                         in ``__init__``), and the atomic-publish
                         pattern (ALL writes guarded → a bare read of
                         the reference is the documented lock-free
                         consumer, e.g. the serve backend's
                         ``_variables``).
inconsistent-guard       the same attribute written under a lock at one
                         site and bare at another — the discipline
                         drifted; one of the two sites is wrong.
lock-order-cycle         the lock-acquisition graph (lexical nesting +
                         one level of intra-class/module calls) has a
                         cycle — the classic ABBA deadlock — or a
                         non-reentrant ``Lock`` is re-acquired on a path
                         that already holds it.
blocking-under-lock      ``join``/queue ``get``/``put``/event ``wait``/
                         ``time.sleep``/socket/urlopen/subprocess inside
                         a ``with lock:`` body — every other acquirer of
                         that lock now waits on the blocked operation
                         (the shape of the PR 5 drain hang).
daemon-shared-teardown   a ``close()``-like method frees state (rebinds
                         it to None or ``.close()``/``.unlink()``s it)
                         that a daemon-thread context still uses, without
                         stopping that thread first (no join/stop-event/
                         shutdown in the method) and without the
                         lock-serialized teardown idiom the serve
                         backend's ``_swap_lock`` established.

Pure ``ast`` — never imports jax; rides the same Finding/pragma/baseline
machinery as jaxlint, so the lint-only CLI stays sub-second.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tpu_resnet.analysis.findings import Finding, apply_pragmas
from tpu_resnet.analysis.jaxlint import (SourceTree, _alias_map, _dotted,
                                         _resolved)

# Types whose construction marks an attribute as a lock (guard), a
# channel (synchronization object — exempt shared state), or a thread
# handle. Resolved through the file's import aliases.
LOCK_TYPES = {"threading.Lock", "threading.RLock", "multiprocessing.Lock"}
RLOCK_TYPES = {"threading.RLock"}
CONDITION_TYPES = {"threading.Condition"}
CHANNEL_TYPES = {
    "queue.Queue", "queue.PriorityQueue", "queue.LifoQueue",
    "queue.SimpleQueue", "collections.deque",
    "threading.Event", "threading.Semaphore", "threading.BoundedSemaphore",
    "threading.Barrier", "threading.Condition",
    "multiprocessing.Event", "multiprocessing.Queue",
}
THREAD_TYPES = {"threading.Thread", "threading.Timer",
                "multiprocessing.Process"}

# Channel construction via a spawn context (``ctx.Event()``/
# ``ctx.Queue()``): matched on the method name when the receiver is not
# an import-resolvable module.
_CHANNEL_CTX_METHODS = {"Queue", "Event", "Value", "JoinableQueue"}

PUBLIC_DUNDERS = {"__next__", "__iter__", "__call__", "__enter__",
                  "__exit__", "__del__"}
TEARDOWN_NAMES = {"close", "shutdown", "stop", "unlink", "drain",
                  "__exit__", "__del__"}
# Calls that count as "this teardown stops its threads first".
_STOP_MARKERS = {"join", "shutdown", "terminate", "cancel", "set",
                 "server_close", "kill"}
_FREE_CALL_METHODS = {"close", "unlink", "server_close", "release"}

# blocking-under-lock deny sets.
_BLOCKING_EXACT = {
    "time.sleep": "host sleep",
    "open": "file open (disk/NFS latency)",
    "urllib.request.urlopen": "network request",
    "socket.create_connection": "network connect",
    "subprocess.run": "child process wait",
    "subprocess.check_output": "child process wait",
    "subprocess.check_call": "child process wait",
    "subprocess.Popen": "child process spawn",
    "os.system": "child process wait",
}
_QUEUE_BLOCKING_METHODS = {"get", "put", "join"}
_EVENT_BLOCKING_METHODS = {"wait"}
_THREAD_BLOCKING_METHODS = {"join"}

# Context kinds. "init" and "signal" never run in parallel with the
# others ("init" happens-before thread start; CPython delivers signals
# on the main thread between bytecodes).
_NONCONCURRENT = ("init", "signal")


def _is_concurrent_pair(a: str, b: str) -> bool:
    if a == b:
        return False
    if a in _NONCONCURRENT or b in _NONCONCURRENT:
        return False
    return True


@dataclasses.dataclass
class Access:
    attr: str
    kind: str                  # "write" | "read"
    line: int
    guards: frozenset          # lock attr names lexically held
    func: str                  # defining function key
    wrote_none: bool = False   # write whose value is (or contains) None


@dataclasses.dataclass
class FuncInfo:
    """One method or method-nested function of a class."""

    key: str                   # "method" or "method.nested"
    node: ast.AST
    calls: Set[str] = dataclasses.field(default_factory=set)
    contexts: Set[str] = dataclasses.field(default_factory=set)
    accesses: List[Access] = dataclasses.field(default_factory=list)
    # (lock, line) acquisitions and, per acquisition, what runs inside.
    acquires: List[Tuple[str, int]] = dataclasses.field(
        default_factory=list)
    # (held-lock, acquired-lock, line): a With acquiring `acquired`
    # while `held` was already on the guard stack — the lock-order
    # rule's edge events, recorded in the one _walk_func pass.
    acquire_edges: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list)
    # (Call node, held locks) for every call made with >= 1 lock held —
    # consumed by blocking-under-lock and the lock-order callee
    # propagation, so neither rule re-implements the guard-stack walk.
    guarded_calls: List[Tuple[ast.Call, Tuple[str, ...]]] = \
        dataclasses.field(default_factory=list)


def _call_name(node: ast.Call, aliases) -> Optional[str]:
    return _resolved(node.func, aliases)


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for ``self.x``; None for deeper chains or other receivers."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _assign_targets(node) -> List[Tuple[str, bool]]:
    """(self-attr, value-is/contains-None) pairs rebound by an
    assignment statement, matching tuple targets positionally."""
    out: List[Tuple[str, bool]] = []

    def value_is_none(v) -> bool:
        return isinstance(v, ast.Constant) and v.value is None

    if isinstance(node, ast.Assign):
        values = node.value
        for target in node.targets:
            if isinstance(target, ast.Tuple) and \
                    isinstance(values, ast.Tuple) and \
                    len(target.elts) == len(values.elts):
                for t, v in zip(target.elts, values.elts):
                    a = _self_attr(t)
                    if a:
                        out.append((a, value_is_none(v)))
            else:
                for t in ast.walk(target):
                    a = _self_attr(t)
                    if a and isinstance(t.ctx, ast.Store):
                        out.append((a, value_is_none(values)))
    elif isinstance(node, ast.AugAssign):
        a = _self_attr(node.target)
        if a:
            out.append((a, False))
    elif isinstance(node, ast.AnnAssign) and node.value is not None:
        a = _self_attr(node.target)
        if a:
            out.append((a, value_is_none(node.value)))
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            a = _self_attr(t)
            if a:
                out.append((a, True))
    return out


class ClassModel:
    """Thread-context graph + shared-state map for one class."""

    def __init__(self, rel: str, cls: ast.ClassDef, aliases: Dict[str, str],
                 module_locks: Set[str]):
        self.rel = rel
        self.cls = cls
        self.aliases = aliases
        self.module_locks = module_locks
        self.lock_attrs: Set[str] = set()
        self.plain_lock_attrs: Set[str] = set()   # non-reentrant Lock()
        self.channel_attrs: Set[str] = set()
        self.thread_attrs: Set[str] = set()
        self.funcs: Dict[str, FuncInfo] = {}
        # entry key -> daemon?
        self.thread_entries: Dict[str, bool] = {}
        self.signal_handlers: Set[str] = set()
        self.is_http_handler = any(
            _dotted(b) in ("BaseHTTPRequestHandler",
                           "http.server.BaseHTTPRequestHandler")
            or (isinstance(b, ast.Attribute)
                and b.attr == "BaseHTTPRequestHandler")
            for b in cls.bases)
        self._collect_funcs()
        self._classify_attrs()
        self._find_entries()
        self._assign_contexts()
        self._collect_accesses()

    # ------------------------------------------------------------- structure
    def _collect_funcs(self) -> None:
        for node in self.cls.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            self.funcs[node.name] = FuncInfo(node.name, node)
            for sub in ast.walk(node):
                if sub is node or not isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                self.funcs[f"{node.name}.{sub.name}"] = FuncInfo(
                    f"{node.name}.{sub.name}", sub)
        # intra-class call edges: self.m() and bare calls to sibling
        # nested functions.
        for key, info in self.funcs.items():
            method = key.split(".")[0]
            for call in ast.walk(info.node):
                if not isinstance(call, ast.Call):
                    continue
                a = _self_attr(call.func)
                if a and a in self.funcs:
                    info.calls.add(a)
                elif isinstance(call.func, ast.Name):
                    nested = f"{method}.{call.func.id}"
                    if nested in self.funcs:
                        info.calls.add(nested)

    def _classify_attrs(self) -> None:
        for node in ast.walk(self.cls):
            if not isinstance(node, ast.Assign):
                continue
            attrs = [a for t in node.targets
                     for a in [_self_attr(t)] if a]
            if not attrs or not isinstance(node.value, ast.Call):
                continue
            resolved = _call_name(node.value, self.aliases) or ""
            method = (node.value.func.attr
                      if isinstance(node.value.func, ast.Attribute) else "")
            for a in attrs:
                if resolved in LOCK_TYPES or resolved in CONDITION_TYPES:
                    self.lock_attrs.add(a)
                    if resolved not in RLOCK_TYPES:
                        self.plain_lock_attrs.add(a)
                if resolved in CHANNEL_TYPES or \
                        method in _CHANNEL_CTX_METHODS:
                    self.channel_attrs.add(a)
                if resolved in THREAD_TYPES:
                    self.thread_attrs.add(a)
        # Condition/locks are also channels in the exemption sense.
        self.channel_attrs |= self.lock_attrs

    def _thread_call_entries(self, call: ast.Call, method: str,
                             ) -> List[str]:
        """Entry keys referenced by one Thread/Timer/submit call."""
        out: List[str] = []

        def entry_for(expr) -> Optional[str]:
            a = _self_attr(expr)
            if a and a in self.funcs:
                return a
            if isinstance(expr, ast.Name):
                nested = f"{method}.{expr.id}"
                if nested in self.funcs:
                    return nested
            return None

        resolved = _call_name(call, self.aliases) or ""
        is_submit = (isinstance(call.func, ast.Attribute)
                     and call.func.attr == "submit")
        if resolved in THREAD_TYPES:
            for kw in call.keywords:
                if kw.arg == "target":
                    e = entry_for(kw.value)
                    if e:
                        out.append(e)
                elif kw.arg == "args" and isinstance(
                        kw.value, (ast.Tuple, ast.List)):
                    # nested callbacks handed INTO a worker (e.g. a
                    # counter-add closure) execute on that thread too.
                    for elt in kw.value.elts:
                        e = entry_for(elt)
                        if e:
                            out.append(e)
            if resolved == "threading.Timer" and len(call.args) >= 2:
                e = entry_for(call.args[1])
                if e:
                    out.append(e)
        elif is_submit and call.args:
            e = entry_for(call.args[0])
            if e:
                out.append(e)
        return out

    def _find_entries(self) -> None:
        for key, info in self.funcs.items():
            method = key.split(".")[0]
            for call in ast.walk(info.node):
                if not isinstance(call, ast.Call):
                    continue
                resolved = _call_name(call, self.aliases) or ""
                for entry in self._thread_call_entries(call, method):
                    daemon = any(
                        kw.arg == "daemon"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                        for kw in call.keywords)
                    self.thread_entries[entry] = (
                        self.thread_entries.get(entry, False) or daemon)
                if resolved == "signal.signal" and len(call.args) == 2:
                    a = _self_attr(call.args[1])
                    if a and a in self.funcs:
                        self.signal_handlers.add(a)
        # ``t.daemon = True`` on a stored thread attr marks every entry
        # of this class daemon (conservative; one-thread classes are the
        # norm here).
        for node in ast.walk(self.cls):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    node.value.value is True:
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == "daemon":
                        for e in self.thread_entries:
                            self.thread_entries[e] = True

    @property
    def analyzed(self) -> bool:
        """Shared-state rules run only on classes that demonstrably run
        in more than one context: they spawn threads or serve HTTP."""
        return bool(self.thread_entries) or self.is_http_handler

    def _reach(self, roots: Iterable[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = list(roots)
        while stack:
            key = stack.pop()
            if key in seen or key not in self.funcs:
                continue
            seen.add(key)
            stack.extend(self.funcs[key].calls)
        return seen

    def _assign_contexts(self) -> None:
        for entry, _ in self.thread_entries.items():
            for key in self._reach([entry]):
                self.funcs[key].contexts.add(f"thread:{entry}")
        for handler in self.signal_handlers:
            for key in self._reach([handler]):
                self.funcs[key].contexts.add("signal")
        if "__init__" in self.funcs:
            for key in self._reach(["__init__"]):
                self.funcs[key].contexts.add("init")
        for key, info in self.funcs.items():
            if "." in key:
                continue
            public = (not key.startswith("_")) or key in PUBLIC_DUNDERS
            if self.is_http_handler and key.startswith("do_"):
                for k in self._reach([key]):
                    self.funcs[k].contexts.add(f"handler:{key}")
            elif public and key != "__init__" and \
                    key not in self.thread_entries:
                for k in self._reach([key]):
                    self.funcs[k].contexts.add(f"caller:{key}")
        # A method-nested function with no context of its own (a callback
        # not handed to a thread) runs wherever its definer runs.
        for key, info in self.funcs.items():
            if "." in key and not info.contexts:
                definer = key.split(".")[0]
                info.contexts |= self.funcs[definer].contexts
        for info in self.funcs.values():
            if not info.contexts:
                # private, never called intra-class: reachable only from
                # outside (a callback wired to another object) — its own
                # caller context.
                info.contexts.add(f"caller:{info.key}")

    # --------------------------------------------------------------- access
    def _guard_name(self, item: ast.AST) -> Optional[str]:
        a = _self_attr(item)
        if a and a in self.lock_attrs:
            return a
        if isinstance(item, ast.Name) and item.id in self.module_locks:
            return f"<module>.{item.id}"
        return None

    def _collect_accesses(self) -> None:
        for key, info in self.funcs.items():
            self._walk_func(info)

    def _walk_func(self, info: FuncInfo) -> None:
        own = info.node

        def process(node: ast.AST, guards: Tuple[str, ...]) -> None:
            """Node-first traversal: each node is classified ITSELF
            before recursion, so arbitrarily nested ``with`` statements
            extend the guard stack correctly."""
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not own:
                return  # deferred execution: separate FuncInfo
            if isinstance(node, ast.With):
                names = [self._guard_name(item.context_expr)
                         for item in node.items]
                acquired = tuple(n for n in names if n)
                for n in acquired:
                    info.acquires.append((n, node.lineno))
                    for h in guards:
                        if h != n:
                            info.acquire_edges.append((h, n, node.lineno))
                    if n in guards:
                        info.accesses.append(Access(
                            n, "reacquire", node.lineno,
                            frozenset(guards), info.key))
                for item in node.items:
                    process(item.context_expr, guards)
                held = guards + acquired
                for stmt in node.body:
                    process(stmt, held)
                return
            if isinstance(node, ast.Call) and guards:
                info.guarded_calls.append((node, guards))
            self._scan_stmt(info, node, guards)
            for child in ast.iter_child_nodes(node):
                process(child, guards)

        for stmt in own.body:
            process(stmt, ())

    def _scan_stmt(self, info: FuncInfo, node: ast.AST,
                   guards: Tuple[str, ...]) -> None:
        """Record the accesses introduced by ONE node (non-recursive for
        writes — assignment statements; recursive walks happen in
        ``visit`` which calls this per child)."""
        g = frozenset(guards)
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                             ast.Delete)):
            for attr, none in _assign_targets(node):
                info.accesses.append(Access(attr, "write", node.lineno, g,
                                            info.key, wrote_none=none))
        if isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Load):
            a = _self_attr(node)
            if a:
                info.accesses.append(Access(a, "read", node.lineno, g,
                                            info.key))
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _FREE_CALL_METHODS:
            a = _self_attr(node.func.value)
            if a:
                info.accesses.append(Access(a, "free", node.lineno, g,
                                            info.key))


# ------------------------------------------------------------------- rules
def _iter_classes(tree: SourceTree) -> List[Tuple[str, ClassModel]]:
    """ClassModels for every package class — built ONCE per SourceTree
    and memoized on it: five rules share the models (the context/access
    walk is ~4x the cost of the rules themselves), the same way the CLI
    shares one parsed tree across the three AST engines."""
    cached = getattr(tree, "_concurrency_models", None)
    if cached is not None:
        return cached
    models: List[Tuple[str, ClassModel]] = []
    for rel, mod in tree.trees.items():
        if not rel.startswith("tpu_resnet/"):
            continue
        aliases = _alias_map(mod)
        module_locks = {
            t.id
            for node in mod.body if isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and (_call_name(node.value, aliases) or "") in LOCK_TYPES
            for t in node.targets if isinstance(t, ast.Name)}
        for node in ast.walk(mod):
            if isinstance(node, ast.ClassDef):
                models.append((rel, ClassModel(rel, node, aliases,
                                               module_locks)))
    tree._concurrency_models = models
    return models


def _attr_sites(model: ClassModel):
    """attr -> list of (Access, contexts) across the class."""
    out: Dict[str, List[Tuple[Access, Set[str]]]] = {}
    for info in model.funcs.values():
        for acc in info.accesses:
            out.setdefault(acc.attr, []).append((acc, info.contexts))
    return out


def rule_unguarded_shared_write(tree: SourceTree) -> List[Finding]:
    """multi-context attr with an unguarded write and no consistent lock."""
    findings = []
    for rel, model in _iter_classes(tree):
        if not model.analyzed:
            continue
        for attr, sites in sorted(_attr_sites(model).items()):
            if attr in model.channel_attrs:
                continue
            writes = [(a, c) for a, c in sites if a.kind == "write"]
            noninit_writes = [(a, c) for a, c in writes
                              if c - {"init", "signal"}]
            if not noninit_writes:
                continue  # immutable-after-start (or signal-flag only)
            unguarded_writes = [(a, c) for a, c in noninit_writes
                                if not a.guards]
            if not unguarded_writes:
                continue  # consistently guarded; bare reads are the
                #           atomic-publish pattern (documented exempt)
            # evidence: an unguarded access in a context concurrent with
            # some unguarded write's context.
            unguarded_accesses = [(a, c) for a, c in sites if not a.guards]
            per_context: Dict[str, List[Access]] = {}
            for acc, ctxs in unguarded_writes:
                hit = False
                for other, octxs in unguarded_accesses:
                    if other is acc:
                        continue
                    if other.func == acc.func and not any(
                            c.startswith(("thread:", "handler:"))
                            for c in ctxs | octxs):
                        # One function reachable from several public
                        # roots races only with itself — assumed
                        # serialized unless it actually runs on a
                        # thread/handler context.
                        continue
                    if any(_is_concurrent_pair(x, y)
                           for x in ctxs - set(_NONCONCURRENT)
                           for y in octxs - set(_NONCONCURRENT)):
                        hit = True
                        break
                if hit:
                    ctx_key = ",".join(sorted(ctxs - {"init"})) or "caller"
                    per_context.setdefault(ctx_key, []).append(acc)
            for ctx_key, accs in sorted(per_context.items()):
                first = min(accs, key=lambda a: a.line)
                others = sorted({
                    f"{a.func}:{a.line}" for a, c in sites
                    if a is not first and not a.guards})[:4]
                findings.append(Finding(
                    "unguarded-shared-write", rel, first.line,
                    f"'{model.cls.name}.{attr}' is written without a lock "
                    f"in context [{ctx_key}] "
                    f"({first.func}:{first.line}) while other concurrent "
                    f"contexts touch it unguarded (e.g. "
                    f"{', '.join(others) if others else 'elsewhere'}) — "
                    f"hold one consistent lock at every site, publish "
                    f"through a queue/Event channel, or make the "
                    f"attribute immutable after __init__ "
                    f"(docs/CHECKS.md concurrency)"))
    return findings


def rule_inconsistent_guard(tree: SourceTree) -> List[Finding]:
    """attr written under a lock at one site and bare at another."""
    findings = []
    for rel, model in _iter_classes(tree):
        if not model.analyzed:
            continue
        for attr, sites in sorted(_attr_sites(model).items()):
            if attr in model.channel_attrs:
                continue
            noninit_writes = [a for a, c in sites if a.kind == "write"
                              and c - {"init", "signal"}]
            guarded = [a for a in noninit_writes if a.guards]
            bare = [a for a in noninit_writes if not a.guards]
            if not guarded or not bare:
                continue
            locks = sorted({lk for a in guarded for lk in a.guards})
            first = min(bare, key=lambda a: a.line)
            findings.append(Finding(
                "inconsistent-guard", rel, first.line,
                f"'{model.cls.name}.{attr}' is written under "
                f"{'/'.join(locks)} at "
                f"{', '.join(sorted(f'{a.func}:{a.line}' for a in guarded))} "
                f"but bare at "
                f"{', '.join(sorted(f'{a.func}:{a.line}' for a in bare))} "
                f"— one of the two disciplines is wrong; guard every "
                f"write site with the same lock"))
    return findings


def rule_lock_order_cycle(tree: SourceTree) -> List[Finding]:
    """acquisition-graph cycles (ABBA deadlock) + Lock re-acquisition.

    The graph spans CLASSES within a module: lock nodes are
    ``Class.lockattr`` and a ``with self._lock:`` body calling a method
    of a sibling class (resolved by unique method name, the
    Router→Replica shape) adds cross-class edges — two objects taking
    each other's locks in opposite orders is the deadlock review cannot
    see from either class alone."""
    findings = []
    by_module: Dict[str, List[ClassModel]] = {}
    for rel, model in _iter_classes(tree):
        by_module.setdefault(rel, []).append(model)

    for rel, models in by_module.items():
        # Per-function transitive lock sets per class (one fixpoint pass
        # is enough at the call-graph depths in this codebase), plus a
        # unique-method-name index for cross-class call resolution.
        trans: Dict[Tuple[str, str], Set[str]] = {}
        method_owner: Dict[str, Optional[ClassModel]] = {}
        for model in models:
            cname = model.cls.name
            t = {k: {f"{cname}.{lk}" if not lk.startswith("<module>")
                     else lk for lk, _ in f.acquires}
                 for k, f in model.funcs.items()}
            for _ in range(4):
                changed = False
                for k, f in model.funcs.items():
                    for callee in f.calls:
                        extra = t.get(callee, set()) - t[k]
                        if extra:
                            t[k] |= extra
                            changed = True
                if not changed:
                    break
            for k, v in t.items():
                trans[(cname, k)] = v
            for k in model.funcs:
                if "." in k:
                    continue
                if k in method_owner and method_owner[k] is not model:
                    method_owner[k] = None  # ambiguous: never resolved
                else:
                    method_owner[k] = model

        edges: Dict[str, Set[str]] = {}
        edge_lines: Dict[Tuple[str, str], Tuple[str, int]] = {}

        def note_edge(a: str, b: str, func: str, line: int) -> None:
            edges.setdefault(a, set()).add(b)
            edge_lines.setdefault((a, b), (func, line))

        for model in models:
            cname = model.cls.name

            def qual(lk: str, cname=cname) -> str:
                return lk if lk.startswith("<module>") else f"{cname}.{lk}"

            for key, info in model.funcs.items():
                for acc in info.accesses:
                    if acc.kind == "reacquire" and \
                            acc.attr in model.plain_lock_attrs:
                        findings.append(Finding(
                            "lock-order-cycle", rel, acc.line,
                            f"'{cname}.{acc.attr}' is a "
                            f"non-reentrant threading.Lock re-acquired "
                            f"on a path that already holds it "
                            f"({acc.func}:{acc.line}) — self-deadlock"))

            for key, info in model.funcs.items():
                # direct lexical nesting edges (recorded in _walk_func's
                # single guard-stack pass)
                for held_lk, acq_lk, line in info.acquire_edges:
                    note_edge(qual(held_lk), qual(acq_lk), key, line)
                # calls made with locks held: propagate the callee's
                # transitive acquisitions (intra-class by name, sibling
                # classes by unique method name — the Router↔Replica
                # shape).
                for call, held in info.guarded_calls:
                    callee_locks: Set[str] = set()
                    callee_name = None
                    a = _self_attr(call.func)
                    if a and a in model.funcs:
                        callee_name = a
                        callee_locks = trans.get((cname, a), set())
                    elif isinstance(call.func, ast.Name):
                        nested = f"{key.split('.')[0]}.{call.func.id}"
                        if nested in model.funcs:
                            callee_name = nested
                            callee_locks = trans.get((cname, nested),
                                                     set())
                    elif isinstance(call.func, ast.Attribute) and not a:
                        owner = method_owner.get(call.func.attr)
                        if owner is not None and owner is not model:
                            callee_name = (f"{owner.cls.name}."
                                           f"{call.func.attr}")
                            callee_locks = trans.get(
                                (owner.cls.name, call.func.attr), set())
                    for lk in callee_locks:
                        for h in (qual(x) for x in held):
                            if h != lk:
                                note_edge(h, lk, key, call.lineno)
                            elif lk.split(".")[-1] in \
                                    model.plain_lock_attrs and \
                                    lk.startswith(cname + "."):
                                findings.append(Finding(
                                    "lock-order-cycle", rel,
                                    call.lineno,
                                    f"'{lk}' (non-reentrant Lock) "
                                    f"is held at {key}:{call.lineno} "
                                    f"while calling "
                                    f"'{callee_name}', which "
                                    f"acquires it again — "
                                    f"self-deadlock"))

        # cycle detection over the module-wide acquisition edges
        seen_cycles = set()
        for start in edges:
            stack = [(start, (start,))]
            while stack:
                cur, path = stack.pop()
                for nxt in edges.get(cur, ()):
                    if nxt == start and len(path) > 1:
                        cyc = tuple(sorted(path))
                        if cyc in seen_cycles:
                            continue
                        seen_cycles.add(cyc)
                        fn, line = edge_lines[(cur, nxt)]
                        findings.append(Finding(
                            "lock-order-cycle", rel, line,
                            f"lock acquisition cycle: "
                            f"{' -> '.join(path + (start,))} — two "
                            f"threads taking these locks in opposite "
                            f"orders deadlock; pick one global order "
                            f"(docs/CHECKS.md concurrency)"))
                    elif nxt not in path:
                        stack.append((nxt, path + (nxt,)))
    return findings


def rule_blocking_under_lock(tree: SourceTree) -> List[Finding]:
    """join/queue-get/IO inside a ``with lock:`` body."""
    findings = []
    for rel, model in _iter_classes(tree):
        for key, info in model.funcs.items():
            for call, held in info.guarded_calls:
                hazard = _blocking_hazard(call, model)
                if hazard:
                    what, why = hazard
                    findings.append(Finding(
                        "blocking-under-lock", rel, call.lineno,
                        f"{what} inside a `with "
                        f"{'/'.join(held)}:` body "
                        f"({key}:{call.lineno}): {why} — every "
                        f"other acquirer of the lock now waits "
                        f"on it (the PR 5 drain-hang shape); "
                        f"move the blocking operation outside "
                        f"the critical section"))
    return findings


def _blocking_hazard(call: ast.Call, model: ClassModel
                     ) -> Optional[Tuple[str, str]]:
    resolved = _call_name(call, model.aliases) or ""
    if resolved in _BLOCKING_EXACT:
        return resolved, _BLOCKING_EXACT[resolved]
    if resolved.startswith(("socket.", "subprocess.")):
        return resolved, "blocking system call"
    if not isinstance(call.func, ast.Attribute):
        return None
    method = call.func.attr
    recv = _self_attr(call.func.value)
    nonblocking = any(
        kw.arg in ("block",) and isinstance(kw.value, ast.Constant)
        and kw.value.value is False for kw in call.keywords) or \
        any(kw.arg == "timeout" and isinstance(kw.value, ast.Constant)
            and kw.value.value in (0, 0.0) for kw in call.keywords)
    if recv in model.channel_attrs and recv not in model.lock_attrs:
        if method in _QUEUE_BLOCKING_METHODS and not nonblocking:
            return (f"self.{recv}.{method}()",
                    "blocking queue operation (waits for a peer that "
                    "may itself need this lock)")
        if method in _EVENT_BLOCKING_METHODS and not nonblocking:
            return (f"self.{recv}.{method}()",
                    "event wait (the setter may need this lock)")
    if recv in model.thread_attrs and method in _THREAD_BLOCKING_METHODS:
        return (f"self.{recv}.join()",
                "thread join (the joined thread may need this lock)")
    if method == "sleep" and resolved == "time.sleep":
        return resolved, _BLOCKING_EXACT["time.sleep"]
    return None


def rule_daemon_shared_teardown(tree: SourceTree) -> List[Finding]:
    """close() frees state a still-running daemon thread uses."""
    findings = []
    for rel, model in _iter_classes(tree):
        daemon_entries = [e for e, d in model.thread_entries.items() if d]
        if not daemon_entries:
            continue
        daemon_ctxs = {f"thread:{e}" for e in daemon_entries}
        # attrs a daemon context touches, with the guards of each touch
        daemon_uses: Dict[str, List[Access]] = {}
        for info in model.funcs.values():
            if not (info.contexts & daemon_ctxs):
                continue
            for acc in info.accesses:
                if acc.kind in ("read", "write"):
                    daemon_uses.setdefault(acc.attr, []).append(acc)
        for name in TEARDOWN_NAMES:
            info = model.funcs.get(name)
            if info is None:
                continue
            stops = _has_stop_marker(info, model)
            frees: List[Tuple[str, int, frozenset]] = []
            for acc in info.accesses:
                if (acc.kind == "write" and acc.wrote_none) or \
                        acc.kind == "free":
                    frees.append((acc.attr, acc.line, acc.guards))
            for attr, line, guards in frees:
                uses = daemon_uses.get(attr)
                if not uses or attr in model.channel_attrs:
                    continue
                if stops:
                    continue  # thread stopped/joined before the free
                # swap-lock idiom: free AND every daemon use under one
                # common lock serializes teardown against the thread.
                common = guards.intersection(
                    *[u.guards for u in uses]) if uses else frozenset()
                if guards and common:
                    continue
                findings.append(Finding(
                    "daemon-shared-teardown", rel, line,
                    f"'{model.cls.name}.{name}()' frees 'self.{attr}' "
                    f"({name}:{line}) while daemon thread context(s) "
                    f"{sorted(daemon_ctxs)} still use it (e.g. "
                    f"{uses[0].func}:{uses[0].line}) and nothing stops "
                    f"the thread first — join/stop-event the thread "
                    f"before freeing, or serialize both sides under one "
                    f"lock (the serve backend's _swap_lock idiom)"))
    return findings


def _has_stop_marker(info: FuncInfo, model: ClassModel) -> bool:
    for call in ast.walk(info.node):
        if isinstance(call, ast.Call) and \
                isinstance(call.func, ast.Attribute) and \
                call.func.attr in _STOP_MARKERS:
            return True
    return False


CONCURRENCY_RULES = {
    "unguarded-shared-write": rule_unguarded_shared_write,
    "inconsistent-guard": rule_inconsistent_guard,
    "lock-order-cycle": rule_lock_order_cycle,
    "blocking-under-lock": rule_blocking_under_lock,
    "daemon-shared-teardown": rule_daemon_shared_teardown,
}


def run_concurrency(root: str, select: Optional[Iterable[str]] = None,
                    files: Optional[Iterable[str]] = None,
                    tree: Optional[SourceTree] = None) -> List[Finding]:
    """Run the concurrency rules over ``root``; pragma suppression
    applied. Same contract as ``run_jaxlint``. ``tree`` reuses a
    pre-parsed SourceTree (the CLI builds one and shares it across the
    AST engines). Parse failures are findings here too — an engine that
    analyzed an unparseable file as an empty module would report the
    very file it exists to check as clean."""
    tree = tree if tree is not None else SourceTree(root, files=files)
    selected = set(select) if select else set(CONCURRENCY_RULES)
    unknown = selected - set(CONCURRENCY_RULES)
    if unknown:
        raise ValueError(f"unknown rule(s) {sorted(unknown)}; "
                         f"have {sorted(CONCURRENCY_RULES)}")
    findings: List[Finding] = list(tree.parse_errors)
    for rule_id in sorted(selected):
        findings.extend(CONCURRENCY_RULES[rule_id](tree))
    return apply_pragmas(findings, tree.sources)
