"""Finding model shared by both check engines (jaxlint + config matrix).

A finding is one structured violation: rule id, file:line, severity,
message. Everything downstream — the human text report, the JSON output,
the ``# check: disable=<rule>`` pragma filter and the checked-in baseline
file — operates on this one shape, so a new rule only has to emit
findings and gets suppression/reporting for free.

Suppression layers (both designed for incremental adoption, docs/CHECKS.md):

- pragma: ``# check: disable=rule-a,rule-b`` on the flagged line silences
  those rules for that line; ``# check: disable-file=rule-a`` anywhere in
  a file silences the rule for the whole file. Pragmas live next to the
  code they excuse, so review sees them.
- baseline: a checked-in JSON list of finding fingerprints that are
  accepted-for-now. Fingerprints hash (rule, path, message) — not the
  line number — so unrelated edits above a baselined finding don't churn
  the file. Stale entries (baselined findings that no longer fire) are
  reported so the baseline only ever shrinks.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from typing import Dict, Iterable, List, Sequence, Tuple

SEVERITIES = ("error", "warning")

_PRAGMA_LINE = re.compile(r"#\s*check:\s*disable=([\w\-,\s]+)")
_PRAGMA_FILE = re.compile(r"#\s*check:\s*disable-file=([\w\-,\s]+)")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str            # root-relative, '/'-separated
    line: int            # 1-based; 0 = whole-file/whole-config finding
    message: str
    severity: str = "error"

    def fingerprint(self) -> str:
        """Line-insensitive identity used by the baseline file."""
        key = f"{self.rule}:{self.path}:{self.message}"
        return hashlib.sha256(key.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint()
        return d

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: {self.severity}: {self.message} [{self.rule}]"


def _parse_rules(csv: str) -> List[str]:
    return [r.strip() for r in csv.split(",") if r.strip()]


def pragma_sets(source: str) -> Tuple[Dict[int, set], set]:
    """(line -> disabled rules, file-level disabled rules) for a source
    file. Lines are 1-based to match ``ast`` node locations.

    Only actual COMMENT tokens count: pragma-shaped text inside a
    docstring or string literal (e.g. documentation that *mentions* the
    pragma syntax) must not disable anything, so the scan tokenizes
    instead of regexing raw lines."""
    import io
    import tokenize

    per_line: Dict[int, set] = {}
    whole_file: set = set()
    try:
        tokens = [(tok.start[0], tok.string) for tok in
                  tokenize.generate_tokens(io.StringIO(source).readline)
                  if tok.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # Unparseable file: no comments recoverable, nothing disabled
        # (the engine reports the parse failure as its own finding).
        return per_line, whole_file
    for lineno, text in tokens:
        m = _PRAGMA_FILE.search(text)
        if m:
            whole_file.update(_parse_rules(m.group(1)))
            continue
        m = _PRAGMA_LINE.search(text)
        if m:
            per_line.setdefault(lineno, set()).update(
                _parse_rules(m.group(1)))
    return per_line, whole_file


def apply_pragmas(findings: Sequence[Finding],
                  sources: Dict[str, str]) -> List[Finding]:
    """Drop findings whose line (or file) carries a disable pragma for
    their rule. ``sources`` maps root-relative path -> file text."""
    cache: Dict[str, Tuple[Dict[int, set], set]] = {}
    kept = []
    for f in findings:
        src = sources.get(f.path)
        if src is None:
            kept.append(f)
            continue
        if f.path not in cache:
            cache[f.path] = pragma_sets(src)
        per_line, whole_file = cache[f.path]
        disabled = per_line.get(f.line, set()) | whole_file
        if f.rule not in disabled and "all" not in disabled:
            kept.append(f)
    return kept


# ------------------------------------------------------------------ baseline
def load_baseline(path: str) -> List[dict]:
    try:
        with open(path) as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return []
    if not isinstance(data, list):
        raise ValueError(f"baseline {path} must be a JSON list")
    return data


def save_baseline(path: str, findings: Sequence[Finding],
                  keep_entries: Iterable[dict] = ()) -> None:
    """Write findings as the new baseline. ``keep_entries`` are existing
    entries preserved verbatim (partial runs pass the entries of engines
    that didn't run); deduped by fingerprint."""
    entries = [{"fingerprint": f.fingerprint(), "rule": f.rule,
                "path": f.path, "message": f.message}
               for f in findings]
    seen = {e["fingerprint"] for e in entries}
    entries += [e for e in keep_entries
                if e.get("fingerprint") not in seen]
    entries.sort(key=lambda e: (e.get("path", ""), e.get("rule", ""),
                                e.get("message", "")))
    with open(path, "w") as fh:
        json.dump(entries, fh, indent=1)
        fh.write("\n")


def apply_baseline(findings: Sequence[Finding], baseline: Iterable[dict]
                   ) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """Split findings against a baseline.

    Returns ``(new, suppressed, stale)``: findings not in the baseline,
    findings silenced by it, and baseline entries that no longer fire
    (candidates for deletion — the baseline only ever shrinks)."""
    fps = {e.get("fingerprint") for e in baseline}
    new = [f for f in findings if f.fingerprint() not in fps]
    suppressed = [f for f in findings if f.fingerprint() in fps]
    live = {f.fingerprint() for f in findings}
    stale = [e for e in baseline if e.get("fingerprint") not in live]
    return new, suppressed, stale


# ------------------------------------------------------------------- report
def render_report(findings: Sequence[Finding], *, suppressed: int = 0,
                  stale: Sequence[dict] = (), checked: str = "") -> str:
    lines = [f.format() for f in
             sorted(findings, key=lambda f: (f.severity != "error",
                                             f.path, f.line, f.rule))]
    errors = sum(1 for f in findings if f.severity == "error")
    warnings = len(findings) - errors
    tail = (f"check: {errors} error(s), {warnings} warning(s)"
            + (f", {suppressed} baselined" if suppressed else "")
            + (f" [{checked}]" if checked else ""))
    for e in stale:
        lines.append(f"stale baseline entry (no longer fires, delete it): "
                     f"{e.get('rule')} {e.get('path')} — {e.get('message')}")
    lines.append(tail)
    return "\n".join(lines)
