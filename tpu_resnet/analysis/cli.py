"""CLI for the static-analysis suite.

    python -m tpu_resnet check                 # lints + concurrency +
                                               #   spmd + config matrix
                                               #   + golden memory budgets
                                               #   + golden collectives
    python -m tpu_resnet check --skip-matrix   # AST engines only
                                               #   (seconds, no jax)
    python -m tpu_resnet check --skip-memory   # skip the XLA-compile-
                                               #   backed memory engine
    python -m tpu_resnet check --skip-collectives
                                               # skip the collective-
                                               #   communication engine
    python -m tpu_resnet check --skip-concurrency --skip-spmd
                                               # PR-4-era engine set
    python -m tpu_resnet check --update-golden # intentional regeneration
                                               #   (jaxprs, memory AND
                                               #   collectives, one pass)
    tpu-resnet-check                           # console-script alias

Exit code 0 = clean (after pragmas + baseline), 1 = error findings (or a
stale baseline entry — the baseline only ever shrinks), 2 = usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpu_resnet.analysis.concurrency import (CONCURRENCY_RULES,
                                             run_concurrency)
from tpu_resnet.analysis.findings import (apply_baseline, load_baseline,
                                          render_report, save_baseline)
from tpu_resnet.analysis.jaxlint import RULES, run_jaxlint
from tpu_resnet.analysis.spmd import SPMD_RULES, run_spmd

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def _default_root() -> str:
    import tpu_resnet

    return os.path.dirname(os.path.dirname(os.path.abspath(
        tpu_resnet.__file__)))


def _default_files(root: str):
    """File set for the default root. A source checkout (pyproject.toml
    or .git beside the package) lints wholesale; an installed package's
    parent is site-packages — walking/linting the entire environment
    there would take minutes and flag code the user doesn't own, so the
    scan is pinned to the tpu_resnet package itself (rel paths keep
    their 'tpu_resnet/' prefix so path-scoped rules still apply)."""
    from tpu_resnet.analysis.jaxlint import discover

    if any(os.path.exists(os.path.join(root, m))
           for m in ("pyproject.toml", ".git")):
        return None  # full checkout: let the engine discover
    pkg = os.path.join(root, "tpu_resnet")
    return ["tpu_resnet/" + rel for rel in discover(pkg)]


def _prepare_jax_env() -> None:
    """The config matrix is defined over the CPU abstract trace with an
    8-way virtual mesh. When jax is not yet imported, pin that
    environment (a TPU/GPU backend would only skip the golden compare
    and slow tracing down); once imported it's too late — the verifier
    then degrades gracefully."""
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-resnet-check",
        description="JAX/TPU-aware static analysis: AST lints + "
                    "config-matrix abstract verifier (docs/CHECKS.md)")
    p.add_argument("--root", default=None,
                   help="repo root to lint (default: the checkout "
                        "containing the tpu_resnet package)")
    p.add_argument("--rules", default="",
                   help=f"comma-separated AST-rule subset of "
                        f"{sorted(RULES) + sorted(CONCURRENCY_RULES) + sorted(SPMD_RULES)}")
    p.add_argument("--skip-lint", action="store_true")
    p.add_argument("--skip-concurrency", action="store_true",
                   help="skip the thread/lock race-detector engine "
                        "(analysis/concurrency.py)")
    p.add_argument("--skip-spmd", action="store_true",
                   help="skip the SPMD-divergence lint "
                        "(analysis/spmd.py)")
    p.add_argument("--skip-matrix", action="store_true",
                   help="AST engines only (lint + concurrency + spmd) "
                        "— never imports jax, seconds not minutes "
                        "(also skips the memory-budget engine, which "
                        "rides on the matrix entries)")
    p.add_argument("--skip-memory", action="store_true",
                   help="skip the golden memory-budget engine (it pays "
                        "real XLA compiles — minutes for the full "
                        "matrix; the jaxpr trace stays)")
    p.add_argument("--skip-collectives", action="store_true",
                   help="skip the collective-communication engine "
                        "(analysis/collectives.py; shares the memory "
                        "engine's compiles, so skipping it saves "
                        "compile time only when --skip-memory is also "
                        "set)")
    p.add_argument("--update-golden", action="store_true",
                   help="rewrite analysis/golden_jaxprs.json, "
                        "analysis/golden_memory.json AND "
                        "analysis/golden_collectives.json from the "
                        "current programs in one coherent pass "
                        "(intentional program changes; commit the diff "
                        "and say why)")
    p.add_argument("--golden", default=None,
                   help="alternate golden_jaxprs.json path")
    p.add_argument("--golden-memory", default=None,
                   help="alternate golden_memory.json path")
    p.add_argument("--golden-collectives", default=None,
                   help="alternate golden_collectives.json path")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline file of accepted findings "
                        "(default: analysis/baseline.json)")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept all current findings into the baseline")
    p.add_argument("--json", dest="json_out", default="",
                   help="also write findings as JSON to this path "
                        "('-' = stdout)")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rules in (RULES, CONCURRENCY_RULES, SPMD_RULES):
            for rule_id, fn in sorted(rules.items()):
                doc = (fn.__doc__ or "").strip().splitlines()
                print(f"{rule_id:18s} {doc[0] if doc else ''}")
        print("config-matrix      abstract-eval structural checks "
              "(configmatrix.py)")
        print("registry-coverage  every traced matrix entry resolves "
              "through programs.spell_entry; one key = one program "
              "(configmatrix.py)")
        print("golden-jaxpr-drift compiled-program drift vs "
              "golden_jaxprs.json")
        print("golden-memory-drift compiled-program HBM budget drift vs "
              "golden_memory.json (memorybudget.py)")
        print("memory-budget      memory-budget engine failures "
              "(entry failed to compile)")
        print("golden-collectives-drift compiled-program collective "
              "structure/bytes-on-wire drift vs golden_collectives.json "
              "(collectives.py)")
        print("stray-gather       replicated-mode program all-gathers "
              "parameter-scale payloads (collectives.py)")
        print("axis-confinement   2-D mesh collective spans both mesh "
              "axes without covering the full mesh (collectives.py)")
        print("collective-free-serve serve-bucket program contains a "
              "collective (collectives.py)")
        print("zero1-exchange     zero1 reduce-scatter/all-gather "
              "exchange missing or not replacing the gradient "
              "all-reduce (collectives.py)")
        print("collectives-budget collectives engine failures "
              "(entry failed to compile)")
        return 0

    root = args.root or _default_root()
    files = None if args.root else _default_files(root)
    select = [r.strip() for r in args.rules.split(",") if r.strip()] or None
    if select:
        known = set(RULES) | set(CONCURRENCY_RULES) | set(SPMD_RULES)
        unknown = set(select) - known
        if unknown:
            print(f"unknown rule(s) {sorted(unknown)}; "
                  f"have {sorted(known)}", file=sys.stderr)
            return 2
    # Partial runs (--skip-*/--rules) see only a subset of findings:
    # they can neither judge baseline entries stale nor rewrite the
    # baseline wholesale without deleting the other engines' entries.
    full_run = not (args.skip_lint or args.skip_matrix
                    or args.skip_memory or args.skip_collectives
                    or args.skip_concurrency or args.skip_spmd or select)

    def _subset(rules):
        """--rules subset owned by one AST engine (None = all of it;
        empty list = the engine has nothing selected and is skipped)."""
        if select is None:
            return None
        return [r for r in select if r in rules]

    findings = []
    checked = []
    # One parsed SourceTree shared by the three AST engines: the
    # "<2s, no jax" path must not read+parse every file three times.
    # Each engine also surfaces tree.parse_errors (an unparseable file
    # must never count as clean just because lint was skipped); the
    # dedup below collapses the copies when several engines run.
    ast_tree = None
    if not (args.skip_lint and args.skip_concurrency and args.skip_spmd):
        from tpu_resnet.analysis.jaxlint import SourceTree

        ast_tree = SourceTree(root, files=files)
    lint_select = _subset(RULES)
    if not args.skip_lint and lint_select != []:
        findings += run_jaxlint(root, select=lint_select, tree=ast_tree)
        checked.append("lint")
    conc_select = _subset(CONCURRENCY_RULES)
    if not args.skip_concurrency and conc_select != []:
        findings += run_concurrency(root, select=conc_select,
                                    tree=ast_tree)
        checked.append("concurrency")
    spmd_select = _subset(SPMD_RULES)
    if not args.skip_spmd and spmd_select != []:
        findings += run_spmd(root, select=spmd_select, tree=ast_tree)
        checked.append("spmd")
    findings = list({(f.rule, f.path, f.line, f.message): f
                     for f in findings}.values())
    stats = {}
    if not args.skip_matrix:
        _prepare_jax_env()
        from tpu_resnet.analysis import configmatrix

        golden_path = args.golden or configmatrix.GOLDEN_PATH
        matrix_findings, stats = configmatrix.verify_matrix(
            update_golden=args.update_golden, golden_path=golden_path)
        findings += matrix_findings
        checked.append(
            f"matrix: {stats['traced']} traced, "
            f"{stats['must_raise']} must-raise, "
            f"{stats['hash_checked']} hash-checked, "
            f"{stats['lowered']} lowered")
        if args.update_golden:
            print(f"updated {len(stats['updated'])} golden entries in "
                  f"{golden_path}")
        if not args.skip_memory:
            # Memory budgets ride on the same matrix entries but pay
            # real XLA compiles (docs/CHECKS.md "golden memory").
            from tpu_resnet.analysis import memorybudget

            mem_golden = args.golden_memory or memorybudget.GOLDEN_PATH
            mem_findings, mem_stats = memorybudget.verify_memory(
                update_golden=args.update_golden, golden_path=mem_golden)
            findings += mem_findings
            stats["memory"] = {k: v for k, v in mem_stats.items()
                               if k != "updated"}
            checked.append(
                f"memory: {mem_stats['compiled']} compiled, "
                f"{mem_stats['compared']} compared")
            if args.update_golden:
                print(f"updated {len(mem_stats['updated'])} golden "
                      f"memory budgets in {mem_golden}")
        if not args.skip_collectives:
            # Engine 5: collective structure + bytes-on-wire. Shares
            # memorybudget's per-entry compile cache, so running it
            # after the memory engine costs parsing, not compiles.
            from tpu_resnet.analysis import collectives

            comms_golden = (args.golden_collectives
                            or collectives.GOLDEN_PATH)
            comms_findings, comms_stats = collectives.verify_collectives(
                update_golden=args.update_golden,
                golden_path=comms_golden)
            findings += comms_findings
            stats["collectives"] = {k: v for k, v in comms_stats.items()
                                    if k != "updated"}
            checked.append(
                f"collectives: {comms_stats['compiled']} compiled, "
                f"{comms_stats['compared']} compared")
            if args.update_golden:
                print(f"updated {len(comms_stats['updated'])} golden "
                      f"collective summaries in {comms_golden}")

    if args.write_baseline:
        # A partial run MERGES: entries owned by engines/rules that
        # didn't run are preserved verbatim (overwriting from a
        # --skip-matrix run would silently delete every accepted
        # config-matrix entry and fail the next full run); entries of
        # the rules that DID run are replaced by today's findings, so
        # fixed ones still drop out.
        keep = []
        if not full_run:
            matrix_rules = {"config-matrix", "golden-jaxpr-drift",
                            "registry-coverage"}
            memory_rules = {"golden-memory-drift", "memory-budget"}
            collectives_rules = {"golden-collectives-drift",
                                 "stray-gather", "axis-confinement",
                                 "collective-free-serve",
                                 "zero1-exchange", "collectives-budget"}
            selected = set(select) if select else None

            def ran(rule: str) -> bool:
                if rule in matrix_rules:
                    return not args.skip_matrix
                if rule in memory_rules:
                    return not (args.skip_matrix or args.skip_memory)
                if rule in collectives_rules:
                    return not (args.skip_matrix
                                or args.skip_collectives)
                if rule in CONCURRENCY_RULES:
                    return (not args.skip_concurrency
                            and (selected is None or rule in selected))
                if rule in SPMD_RULES:
                    return (not args.skip_spmd
                            and (selected is None or rule in selected))
                lint_rules = set(RULES) | {"parse"}
                return (not args.skip_lint and rule in lint_rules
                        and (selected is None or rule in selected))

            keep = [e for e in load_baseline(args.baseline)
                    if not ran(e.get("rule", ""))]
        save_baseline(args.baseline, findings, keep_entries=keep)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}"
              + (f" (+{len(keep)} preserved from engines that didn't "
                 f"run)" if keep else ""))
        return 0

    baseline = load_baseline(args.baseline)
    new, suppressed, stale = apply_baseline(findings, baseline)
    # Staleness is only decidable on a FULL run: with --skip-matrix /
    # --skip-lint / --rules, a baselined finding of a non-selected
    # engine simply wasn't generated — reporting it stale (and exiting
    # 1) would instruct the user to delete a live entry.
    if not full_run:
        stale = []

    report = render_report(new, suppressed=len(suppressed), stale=stale,
                           checked=", ".join(checked))
    print(report)
    if args.json_out:
        payload = json.dumps(
            {"findings": [f.to_dict() for f in new],
             "suppressed": [f.to_dict() for f in suppressed],
             "stale_baseline": stale, "matrix": stats,
             "engines": checked}, indent=1)
        if args.json_out == "-":
            print(payload)
        else:
            with open(args.json_out, "w") as fh:
                fh.write(payload + "\n")

    errors = [f for f in new if f.severity == "error"]
    return 1 if errors or stale else 0


if __name__ == "__main__":
    sys.exit(main())
