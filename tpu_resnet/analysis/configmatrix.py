"""Config-matrix abstract verifier — engine 2 of `tpu-resnet check`.

For the cross-product of supported run configurations (models × datasets
× mesh shapes × dtypes × fused/remat × data engine) this module traces
the REAL train and eval steps — the same ``make_train_step`` /
``make_eval_step`` / ``per_replica_shard_map`` objects the loop compiles
— on an abstract mesh via ``jax.make_jaxpr``/``jax.eval_shape``. No
hardware, no FLOPs, no buffers: every check runs on a laptop CPU in
seconds per config, which is what makes it a merge gate instead of a
cluster job (config-space correctness is what breaks first at scale —
MLPerf TPU-pod experience, arXiv:1909.09756; pjit LM training,
arXiv:2204.06514).

Checks per combination:

- **dtype discipline** — no float64/complex/int64 anywhere in the traced
  program (a silent x64 leak doubles memory and halves MXU throughput),
  no float16 (this codebase is bf16-or-f32 by design), metrics all
  float32.
- **stable donated-buffer layout** — the train step must map state in ->
  state out with an IDENTICAL pytree layout (paths, shapes, dtypes);
  donation of every state leaf is verified against the lowered program's
  ``args_info`` on a concrete mesh when enough local devices exist.
- **sharding contract** — state replicated, batch split over the mesh's
  ``data`` axis, exactly as ``shard_step`` declares.
- **golden jaxpr hashes** — the canonicalized jaxpr text of each config
  hashes to a value checked into ``analysis/golden_jaxprs.json``. A PR
  that silently changes any compiled program (the PR-1 "wrong cached
  executable" incident class) fails review until the golden is
  regenerated intentionally (``python -m tpu_resnet check
  --update-golden``; see docs/CHECKS.md).
- **unsupported combinations raise** — the guard contracts (fused +
  sync-BN multi-chip, fused + Wide-ResNet widths, fused + bn_axis_name
  at the constructor) are exercised as must-raise entries, so the
  fail-loud guards are themselves regression-tested per config.
- **engine invariance** — ``data.engine`` (thread vs process) must not
  change the compiled program: process-engine entries assert
  hash-equality with their thread twins.

Golden hashes are defined over the CPU abstract trace (the tier-1/CI
environment). On a non-CPU default backend the hash comparison is
skipped with a warning — Pallas kernel call sites legitimately embed
backend-dependent parameters — while every structural check still runs.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
from typing import Dict, List, Optional, Tuple

from tpu_resnet.analysis.findings import Finding

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden_jaxprs.json")
GOLDEN_FORMAT = 1

# bf16 is spelled 'bf16[' — the lookbehind keeps it from matching 'f16['.
_FORBIDDEN_DTYPES = (
    ("float64", re.compile(r"(?<![a-z0-9_])f64\[")),
    ("float16", re.compile(r"(?<![a-z0-9_])f16\[")),
    ("int64", re.compile(r"(?<![a-z0-9_])i64\[")),
    ("uint64", re.compile(r"(?<![a-z0-9_])u64\[")),
    ("complex64", re.compile(r"(?<![a-z0-9_])c64\[")),
    ("complex128", re.compile(r"(?<![a-z0-9_])c128\[")),
)

_ADDR = re.compile(r"0x[0-9a-f]+")


def canonicalize(jaxpr_text: str) -> str:
    """Jaxpr text with process-varying tokens (object addresses in
    embedded function reprs) normalized, so the sha256 is stable across
    processes and machines."""
    return _ADDR.sub("0xX", jaxpr_text)


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()


@dataclasses.dataclass
class MatrixEntry:
    """One point of the supported-config cross-product."""

    name: str
    dataset: str = "cifar10"
    model: str = "resnet"
    size: int = 8
    width: int = 1
    dtype: str = "float32"
    fused: bool = False
    remat: bool = False
    epilogue: str = "off"          # model.fused_epilogue (off | on | auto)
    sync_bn: bool = True
    s2d: bool = True               # model.stem_space_to_depth
    data_axis: int = 1
    model_axis: int = 1
    engine: str = "thread"
    batch: int = 16
    # mesh.partition: replicated | zero1 (parallel/partition.py). zero1
    # rows pin the cross-replica weight-update structure — the sharding
    # constraints the SPMD partitioner turns into reduce-scatter +
    # all-gather are part of the traced program, so they golden-hash
    # like any other op.
    partition: str = "replicated"
    classes: int = 0               # synthetic only; 0 = dataset default
    # Must-raise entries: regex the ValueError message must match.
    expect_error: Optional[str] = None
    # "config" entries build through RunConfig/build_model; "ctor-bn-axis"
    # calls the public constructor directly with bn_axis_name+fused (the
    # ADVICE r4 bypass path); "staged-chunk" traces the fused multi-step
    # chunk program over a staged superbatch (device_data.make_chunk_fn
    # — the program the double-buffered H2D path dispatches) instead of
    # the single step; "serve" traces the bucket inference program
    # (serve/infer.make_serve_infer — what the CheckpointBackend warms
    # per bucket), with ``batch`` as the bucket size.
    builder: str = "config"
    # serve builder only: serve.quantize (off | int8). int8 rows trace
    # the quantized program over the int8 argument tree of ops/quant.py
    # and spell under the registry's `_q8` key family.
    quantize: str = "off"
    # staged-chunk only: steps fused per dispatch / superbatch stage rows.
    chunk_steps: int = 4
    stage_rows: int = 8
    # Assert hash-equality with another entry (e.g. engine must not
    # change the compiled program).
    same_program_as: Optional[str] = None
    # Run the concrete-mesh lowering check (donation + sharding) on this
    # entry when the host has enough local devices.
    check_lowering: bool = False

    def to_config(self):
        from tpu_resnet.config import RunConfig

        cfg = RunConfig()
        cfg.data.dataset = self.dataset
        cfg.data.engine = self.engine
        if self.classes:
            cfg.data.synthetic_classes = self.classes
        cfg.model.name = self.model
        cfg.model.resnet_size = self.size
        cfg.model.width_multiplier = self.width
        cfg.model.compute_dtype = self.dtype
        cfg.model.fused_blocks = self.fused
        cfg.model.remat = self.remat
        cfg.model.fused_epilogue = self.epilogue
        cfg.model.sync_bn = self.sync_bn
        cfg.model.stem_space_to_depth = self.s2d
        cfg.mesh.data = self.data_axis
        cfg.mesh.model = self.model_axis
        cfg.mesh.partition = self.partition
        cfg.train.global_batch_size = self.batch
        cfg.serve.quantize = self.quantize
        return cfg


def _e(name, **kw) -> MatrixEntry:
    return MatrixEntry(name=name, **kw)


# The supported-config matrix. Kept explicit (not a programmatic product)
# so every entry is a deliberate, named, golden-hashed contract; adding a
# config feature means adding its row(s) here.
MATRIX: Tuple[MatrixEntry, ...] = (
    # --- CIFAR basic-block nets: dtypes × fused × remat ---------------
    _e("cifar10_rn8_f32"),
    _e("cifar10_rn8_bf16", dtype="bfloat16"),
    _e("cifar10_rn8_f32_fused", fused=True),
    _e("cifar10_rn8_bf16_fused", dtype="bfloat16", fused=True),
    _e("cifar10_rn8_f32_remat", remat=True),
    _e("cifar10_rn8_f32_fused_remat", fused=True, remat=True),
    # --- mesh shapes: sync-BN jit vs per-replica shard_map ------------
    _e("cifar10_rn8_f32_mesh8", data_axis=8, check_lowering=True),
    _e("cifar10_rn8_f32_mesh8_perreplica", data_axis=8, sync_bn=False,
       check_lowering=True),
    _e("cifar10_rn8_f32_mesh8_perreplica_fused", data_axis=8,
       sync_bn=False, fused=True),
    _e("cifar10_rn8_f32_mesh4x2", data_axis=4, model_axis=2),
    # 2-D ("batch","model") pod shape with cross-replica optimizer
    # sharding — ROADMAP item 1 pre-work: the pod-shaped program (zero1
    # reduce-scatter/all-gather over the 4-way data axis of a 4x2 mesh)
    # is golden-pinned (jaxpr + memory budget) and donation-verified on
    # the concrete 8-device mesh, so pod correctness is check-reviewable
    # before any pod exists.
    _e("cifar10_rn8_f32_mesh4x2_zero1", data_axis=4, model_axis=2,
       partition="zero1", check_lowering=True),
    _e("imagenet_rn18_bf16_mesh4x2", dataset="imagenet", size=18,
       dtype="bfloat16", data_axis=4, model_axis=2),
    # --- depth / width ------------------------------------------------
    _e("cifar10_rn20_bf16", size=20, dtype="bfloat16"),
    _e("cifar10_rn50_bf16", size=50, dtype="bfloat16"),
    # Non-headline dimension arms ride on shallow nets: tracing cost is
    # depth-proportional and the dimension under test (mesh/dtype/stem)
    # is depth-independent; the deep headline programs are pinned by the
    # rn50 rows above/below.
    _e("cifar10_rn20_bf16_mesh8", size=20, dtype="bfloat16", data_axis=8),
    _e("cifar100_rn8_f32", dataset="cifar100"),
    _e("cifar100_wrn28_10_bf16", dataset="cifar100", size=28, width=10,
       dtype="bfloat16"),
    # --- synthetic (smoke/drill configs) ------------------------------
    _e("synthetic_rn8_f32", dataset="synthetic"),
    _e("synthetic100_rn8_f32", dataset="synthetic", classes=100),
    _e("synthetic_mlp_f32", dataset="synthetic", model="mlp"),
    # --- ImageNet -----------------------------------------------------
    _e("imagenet_rn18_bf16", dataset="imagenet", size=18,
       dtype="bfloat16"),
    _e("imagenet_rn18_bf16_remat", dataset="imagenet", size=18,
       dtype="bfloat16", remat=True),
    _e("imagenet_rn18_bf16_process", dataset="imagenet", size=18,
       dtype="bfloat16", engine="process",
       same_program_as="imagenet_rn18_bf16"),
    _e("imagenet_rn18_f32", dataset="imagenet", size=18),
    _e("imagenet_rn18_bf16_mesh8", dataset="imagenet", size=18,
       dtype="bfloat16", data_axis=8),
    _e("imagenet_rn18_bf16_plain_stem", dataset="imagenet", size=18,
       dtype="bfloat16", s2d=False),
    _e("imagenet_rn50_bf16", dataset="imagenet", size=50,
       dtype="bfloat16"),
    _e("imagenet_rn50_bf16_fused", dataset="imagenet", size=50,
       dtype="bfloat16", fused=True),
    # --- fused Pallas epilogues (ops/epilogue.py, MFU campaign) -------
    # "on" pins the kernel-everywhere program (what a forced run and the
    # CPU parity tests compile); the per-replica row pins the supported
    # multi-chip dispatch. "auto" is probe-dependent by design and so
    # cannot carry a golden — its safety net is that every unprobed
    # shape lowers to the same XLA math as these rows' reference arm.
    _e("cifar10_rn8_f32_epilogue", epilogue="on"),
    _e("imagenet_rn18_bf16_epilogue", dataset="imagenet", size=18,
       dtype="bfloat16", epilogue="on"),
    _e("cifar10_rn8_f32_mesh8_perreplica_epilogue", data_axis=8,
       sync_bn=False, epilogue="on"),
    # --- zero1 cross-replica optimizer sharding (parallel/partition.py,
    # parallel/zero.py, arXiv:2004.13336): the sharded weight update's
    # constraint structure is pinned per config, the mesh1 identity twin
    # asserts zero1 on a 1-way data axis compiles the EXACT replicated
    # program, and the lowering check proves donation survives the
    # per-shard optimizer-slot arguments.
    _e("cifar10_rn8_f32_mesh8_zero1", data_axis=8, partition="zero1",
       check_lowering=True),
    _e("imagenet_rn18_bf16_mesh8_zero1", dataset="imagenet", size=18,
       dtype="bfloat16", data_axis=8, partition="zero1"),
    _e("cifar10_rn8_f32_zero1_mesh1", partition="zero1",
       same_program_as="cifar10_rn8_f32"),
    # --- staged/double-buffered chunk program (device_data.make_chunk_fn)
    # The fused multi-step dispatch both streaming input edges execute —
    # including the new DoubleBufferedH2D path, whose contract is that
    # it changes TRANSFER scheduling only, never the compiled program.
    _e("cifar10_rn8_f32_staged_chunk", builder="staged-chunk"),
    _e("imagenet_rn18_bf16_staged_chunk", dataset="imagenet", size=18,
       dtype="bfloat16", builder="staged-chunk"),
    # --- int8 post-training-quantized serve arm (ops/quant.py,
    # serve/infer.py; docs/SERVING.md "Quantized arm"): each quantized
    # bucket program is golden-pinned NEXT TO its f32 twin — same model,
    # same bucket, weights as int8 arguments + folded dequant — and the
    # memory ledger's twin gate (analysis/memorybudget.py,
    # tests/test_quant.py) holds the quantized row's weight-argument
    # bytes to <= 0.30x of the twin's, the ZeRO-1 0.125x pattern.
    _e("serve_cifar10_rn8_f32_b8", builder="serve", batch=8),
    _e("serve_cifar10_rn8_f32_b8_q8", builder="serve", batch=8,
       quantize="int8"),
    _e("serve_synthetic_mlp_f32_b4", builder="serve", dataset="synthetic",
       model="mlp", batch=4),
    _e("serve_synthetic_mlp_f32_b4_q8", builder="serve",
       dataset="synthetic", model="mlp", batch=4, quantize="int8"),
    # --- guard contracts: unsupported combinations must raise ---------
    _e("raise_fused_wrn", dataset="cifar100", size=28, width=10,
       fused=True,
       expect_error="only measured/tiled for.*width_multiplier"),
    _e("raise_fused_syncbn_mesh8", fused=True, data_axis=8,
       expect_error="multi-chip data axis requires.*sync_bn"),
    _e("raise_epilogue_syncbn_mesh8", epilogue="on", data_axis=8,
       expect_error="fused_epilogue on a multi-chip data axis "
                    "requires.*sync_bn"),
    _e("raise_ctor_fused_bn_axis", builder="ctor-bn-axis",
       expect_error="does not implement sync-BN"),
    _e("raise_zero1_perreplica_mesh8", data_axis=8, sync_bn=False,
       partition="zero1",
       expect_error="zero1 on a multi-chip data axis requires.*sync_bn"),
    _e("raise_bad_partition_mode", partition="zero2",
       expect_error="mesh.partition must be one of"),
    # int8 serving of a per-replica-BN multi-replica config: each
    # replica's folded BN affine differs, so one calibration cannot be
    # parity-gated — must refuse (ops/quant.py check_quantize_config).
    _e("raise_quant_perreplica", builder="serve", quantize="int8",
       data_axis=8, sync_bn=False,
       expect_error="serve.quantize=int8 requires model.sync_bn"),
    # Unknown quant mode strings fail loudly, like fused_epilogue typos.
    _e("raise_bad_quantize_mode", builder="serve", quantize="int4",
       expect_error="serve.quantize must be one of"),
)


def _abstract_mesh(data: int, model: int):
    """AbstractMesh across the jax API generations (0.4.x takes a tuple
    of (name, size) pairs; >= 0.5 takes (sizes, names))."""
    from jax.sharding import AbstractMesh

    try:
        return AbstractMesh((("data", data), ("model", model)))
    except TypeError:
        return AbstractMesh((data, model), ("data", "model"))


def _state_layout(state_sds) -> List[Tuple[str, str, Tuple[int, ...]]]:
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(state_sds)[0]
    return [(jax.tree_util.keystr(path), str(leaf.dtype),
             tuple(leaf.shape))
            for path, leaf in leaves]


def _abstract_programs(entry: MatrixEntry):
    """Trace the real train/eval steps for one entry on an abstract mesh.

    Returns (train_text, eval_text, state_layout, out_shapes) where the
    texts are canonicalized jaxpr strings."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpu_resnet.data import augment as aug_lib
    from tpu_resnet.models import build_model, cifar_resnet_v2
    from tpu_resnet.train import schedule as sched_lib
    from tpu_resnet.train.state import init_state
    from tpu_resnet.train.step import (check_step_config, make_eval_step,
                                       make_train_step,
                                       per_replica_shard_map)

    if entry.builder == "ctor-bn-axis":
        # The ADVICE r4 bypass: calling the public constructor directly
        # must hit the same guard as build_model.
        cifar_resnet_v2(entry.size, 10, fused_blocks=True,
                        bn_axis_name="data")
        raise AssertionError("constructor guard did not fire")

    if entry.builder == "serve":
        return _abstract_serve_program(entry)

    cfg = entry.to_config()
    check_step_config(cfg, entry.data_axis)  # the loop's own gate
    model = build_model(cfg)                 # constructor guards run here
    schedule = sched_lib.build_schedule(cfg.optim, cfg.train)
    size = cfg.data.resolved_image_size
    sample = jnp.zeros((1, size, size, 3), jnp.float32)

    def init_fn(rng):
        return init_state(model, cfg.optim, schedule, rng, sample)

    state_sds = jax.eval_shape(init_fn, jax.random.PRNGKey(0))

    augment_fn, eval_pre = aug_lib.get_augment_fns(cfg.data.dataset)
    per_replica = (not cfg.model.sync_bn) and entry.data_axis > 1
    # The partitioner traces over an AbstractMesh — the sharding
    # constraints it injects (the zero1 weight update) carry only axis
    # names/sizes into the jaxpr text, so the golden hash stays
    # machine-independent like every other entry. Replicated entries get
    # a non-sharding partitioner: make_update_fn then returns the plain
    # optax chain, byte-identical to the pre-partitioner trace.
    from tpu_resnet.parallel.partition import StatePartitioner

    partitioner = StatePartitioner(
        _abstract_mesh(entry.data_axis, entry.model_axis), entry.partition)
    step = make_train_step(model, cfg.optim, schedule,
                           cfg.data.num_classes, augment_fn,
                           base_rng=jax.random.PRNGKey(0), mesh=None,
                           grad_axis="data" if per_replica else None,
                           partitioner=partitioner)
    if per_replica:
        step = per_replica_shard_map(
            step, _abstract_mesh(entry.data_axis, entry.model_axis),
            in_specs=(P(), P("data"), P("data")))

    if partitioner.is_sharded:
        # The loop's startup gate, applied to the abstract state tree:
        # an unshardable (model × mesh × partition) combination must be
        # a per-leaf ValueError here too, not a silently replicated slot.
        partitioner.validate(state_sds)

    imgs = jax.ShapeDtypeStruct((entry.batch, size, size, 3), jnp.uint8)
    labels = jax.ShapeDtypeStruct((entry.batch,), jnp.int32)
    if entry.builder == "staged-chunk":
        # The fused multi-step chunk over a staged superbatch — exactly
        # the program compile_staged_stream_steps jits for the streaming
        # (and double-buffered H2D) input edge.
        from tpu_resnet.data.device_data import make_chunk_fn

        chunk = make_chunk_fn(step, entry.chunk_steps)
        gi = jax.ShapeDtypeStruct(
            (entry.stage_rows, entry.batch, size, size, 3), jnp.uint8)
        gl = jax.ShapeDtypeStruct((entry.stage_rows, entry.batch),
                                  jnp.int32)
        off = jax.ShapeDtypeStruct((), jnp.int32)
        train_text = canonicalize(str(jax.make_jaxpr(chunk)(
            state_sds, gi, gl, off)))
        out_shapes = jax.eval_shape(chunk, state_sds, gi, gl, off)
    else:
        train_text = canonicalize(str(jax.make_jaxpr(step)(
            state_sds, imgs, labels)))
        out_shapes = jax.eval_shape(step, state_sds, imgs, labels)

    eval_step = make_eval_step(model, cfg.data.num_classes, eval_pre)
    eval_text = canonicalize(str(jax.make_jaxpr(eval_step)(
        state_sds, imgs, labels)))
    return train_text, eval_text, _state_layout(state_sds), \
        (state_sds, out_shapes)


def _abstract_serve_program(entry: MatrixEntry):
    """Trace the bucket inference program for a serve row — the exact
    ``make_serve_infer`` jit the CheckpointBackend warms per bucket,
    over the exact argument avals it wraps (the int8 quantized tree for
    ``quantize="int8"`` rows — ops/quant.py). Returned in the train-row
    shape (variables stand in for state; empty metrics) so the
    structural checks — forbidden dtypes, layout identity — apply
    unchanged; int8 is deliberately NOT a forbidden dtype."""
    import jax
    import jax.numpy as jnp

    from tpu_resnet.models import build_model
    from tpu_resnet.ops import quant as quant_lib
    from tpu_resnet.serve.infer import make_serve_infer

    cfg = entry.to_config()
    # The serve arm's own config gate — must-raise quant rows fire here.
    quant_lib.check_quantize_config(cfg, entry.data_axis)
    model = build_model(cfg)  # constructor guards run here
    size = cfg.data.resolved_image_size
    sample = jnp.zeros((1, size, size, 3), jnp.float32)

    def init_vars(rng):
        v = model.init(rng, sample, train=False)
        return {"params": v["params"],
                "batch_stats": v.get("batch_stats", {})}

    var_sds = jax.eval_shape(init_vars, jax.random.PRNGKey(0))
    if cfg.serve.quantize == "int8":
        var_sds = jax.eval_shape(quant_lib.quantize_variables, var_sds)
    infer = make_serve_infer(cfg)
    imgs = jax.ShapeDtypeStruct((entry.batch, size, size, 3), jnp.uint8)
    infer_text = canonicalize(str(jax.make_jaxpr(infer)(var_sds, imgs)))
    # No eval twin and no metrics on the serve path: the empty eval text
    # hashes to a constant and the (vars, (vars, {})) shape tuple makes
    # the layout-identity check trivially true.
    return infer_text, "", _state_layout(var_sds), \
        (var_sds, (var_sds, {}))


def _structural_findings(entry: MatrixEntry, train_text: str,
                         eval_text: str, shapes) -> List[Finding]:
    path = f"<config-matrix>/{entry.name}"
    findings = []
    for which, text in (("train", train_text), ("eval", eval_text)):
        for dtype_name, pat in _FORBIDDEN_DTYPES:
            if pat.search(text):
                findings.append(Finding(
                    "config-matrix", path, 0,
                    f"{dtype_name} appears in the {which} step program — "
                    f"dtype discipline is f32/bf16/i32/u8 only (an x64 "
                    f"leak silently doubles memory and halves MXU "
                    f"throughput)"))
    state_sds, out = shapes
    new_state, metrics = out
    in_layout = _state_layout(state_sds)
    out_layout = _state_layout(new_state)
    if in_layout != out_layout:
        diff = [f"{a} != {b}" for a, b in zip(in_layout, out_layout)
                if a != b][:3]
        findings.append(Finding(
            "config-matrix", path, 0,
            f"train step breaks the donated-buffer layout: state-in and "
            f"state-out trees differ ({len(in_layout)} vs "
            f"{len(out_layout)} leaves; first diffs: {diff}) — donation "
            f"requires identical layout or every step copies"))
    for k, v in metrics.items():
        if str(v.dtype) != "float32":
            findings.append(Finding(
                "config-matrix", path, 0,
                f"metric '{k}' of the train step is {v.dtype}, expected "
                f"float32 (dtype promotion leak)"))
    return findings


def verify_lowering(entry: MatrixEntry) -> List[Finding]:
    """Concrete-mesh contract check: lower (no compile, no execute) the
    exact ``shard_step`` jit the loop uses and assert every state leaf is
    donated and the batch is split over 'data'. Needs >= mesh-size local
    devices; the caller skips otherwise."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from tpu_resnet.data import augment as aug_lib
    from tpu_resnet.models import build_model
    from tpu_resnet.train import schedule as sched_lib
    from tpu_resnet.train.state import init_state
    from tpu_resnet.train.step import make_train_step, shard_step

    path = f"<config-matrix>/{entry.name}"
    cfg = entry.to_config()
    model = build_model(cfg)
    schedule = sched_lib.build_schedule(cfg.optim, cfg.train)
    size = cfg.data.resolved_image_size
    sample = jnp.zeros((1, size, size, 3), jnp.float32)
    state_sds = jax.eval_shape(
        lambda r: init_state(model, cfg.optim, schedule, r, sample),
        jax.random.PRNGKey(0))
    n = entry.data_axis * entry.model_axis
    mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(
        entry.data_axis, entry.model_axis), ("data", "model"))
    per_replica = (not cfg.model.sync_bn) and entry.data_axis > 1
    augment_fn, _ = aug_lib.get_augment_fns(cfg.data.dataset)
    from tpu_resnet.parallel.partition import StatePartitioner

    partitioner = StatePartitioner(mesh, entry.partition)
    base = make_train_step(model, cfg.optim, schedule,
                           cfg.data.num_classes, augment_fn,
                           base_rng=jax.random.PRNGKey(0), mesh=mesh,
                           grad_axis="data" if per_replica else None,
                           partitioner=partitioner)
    jitted = shard_step(base, mesh, per_replica_bn=per_replica,
                        state_sharding=(partitioner.state_shardings(state_sds)
                                        if partitioner.is_sharded else None))
    imgs = jax.ShapeDtypeStruct((entry.batch, size, size, 3), jnp.uint8)
    labels = jax.ShapeDtypeStruct((entry.batch,), jnp.int32)
    lowered = jitted.lower(state_sds, imgs, labels)
    findings = []
    args_info = lowered.args_info[0] if isinstance(
        lowered.args_info, tuple) else lowered.args_info
    state_info, img_info, label_info = args_info
    not_donated = [
        jax.tree_util.keystr(p) for p, info in
        jax.tree_util.tree_flatten_with_path(state_info)[0]
        if not info.donated]
    if not_donated:
        findings.append(Finding(
            "config-matrix", path, 0,
            f"{len(not_donated)} state leaf/leaves NOT donated in the "
            f"lowered step (e.g. {not_donated[:3]}) — shard_step promises "
            f"donate_argnums=(0,); an undonated state doubles parameter "
            f"HBM"))
    for name, info_tree in (("images", img_info), ("labels", label_info)):
        if any(i.donated for i in jax.tree_util.tree_leaves(info_tree)):
            findings.append(Finding(
                "config-matrix", path, 0,
                f"{name} buffer is donated — only the state may be"))
    text = lowered.as_text()
    if entry.data_axis > 1 and "sharding" not in text:
        findings.append(Finding(
            "config-matrix", path, 0,
            "lowered program carries no sharding annotations on a "
            f"{entry.data_axis}-way mesh — batch is not split over "
            "'data' (the SPMD contract of shard_step)"))
    return findings


# ----------------------------------------------------------------- golden
def load_golden(path: str = GOLDEN_PATH) -> dict:
    try:
        with open(path) as fh:
            return json.load(fh)
    except FileNotFoundError:
        return {"format": GOLDEN_FORMAT, "entries": {}}


def save_golden(golden: dict, path: str = GOLDEN_PATH) -> None:
    golden["entries"] = dict(sorted(golden["entries"].items()))
    with open(path, "w") as fh:
        json.dump(golden, fh, indent=1)
        fh.write("\n")


def verify_matrix(entries: Optional[Tuple[MatrixEntry, ...]] = None,
                  update_golden: bool = False,
                  golden_path: str = GOLDEN_PATH,
                  progress=None) -> Tuple[List[Finding], dict]:
    """Run the matrix. Returns (findings, stats). With ``update_golden``
    the golden file is rewritten from the current programs instead of
    compared (stats['updated'] lists the entries)."""
    import jax

    entries = MATRIX if entries is None else entries
    golden = load_golden(golden_path)
    on_cpu = jax.default_backend() == "cpu"
    findings: List[Finding] = []
    hashes: Dict[str, Tuple[str, str]] = {}
    stats = {"traced": 0, "must_raise": 0, "hash_checked": 0,
             "lowered": 0, "updated": [], "skipped_lowering": 0,
             "registry_keys": 0}

    for entry in entries:
        if progress:
            progress(entry.name)
        path = f"<config-matrix>/{entry.name}"
        if entry.expect_error is not None:
            stats["must_raise"] += 1
            try:
                _abstract_programs(entry)
            except ValueError as e:
                if not re.search(entry.expect_error, str(e)):
                    findings.append(Finding(
                        "config-matrix", path, 0,
                        f"unsupported combination raised, but with the "
                        f"wrong message: {e!r} !~ /{entry.expect_error}/"))
            except AssertionError as e:
                findings.append(Finding(
                    "config-matrix", path, 0,
                    f"guard did not fire: {e}"))
            except Exception as e:  # wrong exception TYPE is a finding,
                findings.append(Finding(  # not a crashed check run
                    "config-matrix", path, 0,
                    f"unsupported combination raised "
                    f"{type(e).__name__} ({e}) instead of a ValueError "
                    f"matching /{entry.expect_error}/ — the fail-loud "
                    f"guard drifted (users now see an obscure error)"))
            else:
                findings.append(Finding(
                    "config-matrix", path, 0,
                    f"unsupported combination was accepted — expected "
                    f"ValueError matching /{entry.expect_error}/ (a "
                    f"fail-loud guard was removed or weakened)"))
            continue

        try:
            train_text, eval_text, layout, shapes = \
                _abstract_programs(entry)
        except Exception as e:
            # One broken entry must not cost the report for the rest.
            findings.append(Finding(
                "config-matrix", path, 0,
                f"supported combination FAILED to trace: "
                f"{type(e).__name__}: {e}"))
            continue
        stats["traced"] += 1
        findings.extend(_structural_findings(entry, train_text,
                                             eval_text, shapes))
        th, eh = _sha(train_text), _sha(eval_text)
        hashes[entry.name] = (th, eh)
        layout_hash = _sha(json.dumps(layout))
        record = {"train": th, "eval": eh,
                  "state_leaves": len(layout),
                  "state_layout": layout_hash}
        if update_golden:
            golden["entries"][entry.name] = record
            stats["updated"].append(entry.name)
            continue
        want = golden["entries"].get(entry.name)
        if not on_cpu:
            findings.append(Finding(
                "config-matrix", path, 0,
                f"golden hash compare skipped on backend "
                f"'{jax.default_backend()}' (goldens are defined over "
                f"the CPU abstract trace)", "warning"))
        elif want is None:
            findings.append(Finding(
                "golden-jaxpr-drift", path, 0,
                "no golden recorded for this entry — run `python -m "
                "tpu_resnet check --update-golden` and commit the "
                "regenerated analysis/golden_jaxprs.json"))
        else:
            stats["hash_checked"] += 1
            for which, got, exp in (("train", th, want.get("train")),
                                    ("eval", eh, want.get("eval"))):
                if got != exp:
                    findings.append(Finding(
                        "golden-jaxpr-drift", path, 0,
                        f"the compiled {which} program for this config "
                        f"CHANGED (jaxpr {got[:12]}… != golden "
                        f"{exp[:12]}…, golden jax {golden.get('jax')} vs "
                        f"current {jax.__version__}). If intended, "
                        f"regenerate via `python -m tpu_resnet check "
                        f"--update-golden` and say why in the PR; if "
                        f"not, this is the silent-program-change "
                        f"incident class (PR 1) caught at review time"))
            if want.get("state_layout") != layout_hash:
                findings.append(Finding(
                    "golden-jaxpr-drift", path, 0,
                    f"donated-buffer/state layout changed "
                    f"({want.get('state_leaves')} -> {len(layout)} "
                    f"leaves) — checkpoints and donation layout are "
                    f"affected; regenerate goldens if intended"))

    # Registry coverage (tpu_resnet/programs): every traced entry must
    # resolve through the ONE key spelling (programs.spell_entry — the
    # same function the FLOPs registry, memory ledger and executable
    # cache key by), and one key must name exactly one program: two
    # entries that spell the same key with different traced programs
    # mean the spelling under-specifies a config dimension — the
    # executable cache would hand one config the other's program (the
    # PR 1 wrong-executable class, caught here at review time). Two
    # keys naming one program is fine (identity twins).
    from tpu_resnet.programs import spell_entry

    key_owners: Dict[str, Tuple[str, Tuple[str, str]]] = {}
    for entry in entries:
        if entry.name not in hashes:
            continue  # must-raise/failed entries never built a program
        path = f"<config-matrix>/{entry.name}"
        try:
            key = spell_entry(entry)
        except Exception as e:  # noqa: BLE001 - a spell crash is a finding
            findings.append(Finding(
                "registry-coverage", path, 0,
                f"entry does not resolve through the program registry's "
                f"key spelling (programs.spell_entry raised "
                f"{type(e).__name__}: {e}) — the check engines and the "
                f"runtime can no longer agree on what this program is "
                f"called"))
            continue
        stats["registry_keys"] = stats.get("registry_keys", 0) + 1
        prior = key_owners.get(key)
        if prior is None:
            key_owners[key] = (entry.name, hashes[entry.name])
        elif prior[1] != hashes[entry.name]:
            findings.append(Finding(
                "registry-coverage", path, 0,
                f"program key collision: '{entry.name}' and "
                f"'{prior[0]}' both spell {key} but trace DIFFERENT "
                f"programs — the registry key under-specifies a config "
                f"dimension; extend programs.spell so the executable "
                f"cache and the flops/memory ledgers can never hand one "
                f"config the other's program"))

    # engine (and any other declared-invariant) twins
    for entry in entries:
        if entry.same_program_as and entry.name in hashes:
            twin = hashes.get(entry.same_program_as)
            if twin is None:
                findings.append(Finding(
                    "config-matrix", f"<config-matrix>/{entry.name}", 0,
                    f"declared-identical twin '{entry.same_program_as}' "
                    f"was not traced in this run (renamed/removed?) — "
                    f"the engine-invariance contract is silently "
                    f"unverified; fix the same_program_as reference"))
            elif twin != hashes[entry.name]:
                findings.append(Finding(
                    "config-matrix", f"<config-matrix>/{entry.name}", 0,
                    f"program differs from declared-identical twin "
                    f"'{entry.same_program_as}' — this dimension (e.g. "
                    f"data.engine) must not change the compiled step"))

    # concrete-mesh donation/sharding contract where devices allow
    for entry in entries:
        if entry.expect_error is None and entry.check_lowering:
            need = entry.data_axis * entry.model_axis
            if len(jax.devices()) >= need:
                findings.extend(verify_lowering(entry))
                stats["lowered"] += 1
            else:
                stats["skipped_lowering"] += 1

    if update_golden:
        # Prune renamed/removed entries: the golden mirrors MATRIX exactly.
        live = {e.name for e in entries if e.expect_error is None}
        golden["entries"] = {k: v for k, v in golden["entries"].items()
                             if k in live}
        golden["format"] = GOLDEN_FORMAT
        golden["jax"] = jax.__version__
        try:
            import flax
            golden["flax"] = flax.__version__
        except Exception:
            pass
        save_golden(golden, golden_path)
    return findings, stats
