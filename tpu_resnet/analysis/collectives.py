"""Golden collective-communication budgets — check engine 5, the wire
twin of the golden-memory verifier.

The config-matrix verifier pins WHAT program each supported
configuration compiles to, golden memory pins what it costs in HBM;
this engine pins what it puts ON THE WIRE. For every traced matrix
entry it compiles the real program on a concrete CPU mesh (one shared
compile with the memory engine — ``memorybudget.entry_artifacts``),
extracts every collective op from the post-SPMD-partitioner HLO
(``obs/comms.py``: op, payload bytes, replica groups in both HLO
spellings, mesh-axis bucket, ring-model bytes-on-wire) and compares the
summary against ``analysis/golden_collectives.json`` — tolerance bands
on byte totals, exact compare on the op multiset and structure
signature, ``--update-golden`` regen, empty-baseline merge rules:
exactly the golden-memory workflow.

Named rules (docs/CHECKS.md has the catalog):

``golden-collectives-drift``  op multiset / structure signature differs
                              from golden, byte totals leave the band,
                              or an entry has no golden recorded.
``stray-gather``              a replicated-mode train program all-
                              gathers parameter-scale payloads — the
                              ZeRO-bloat regression (replicated state
                              must never be re-gathered).
``axis-confinement``          a 2-D mesh program emits a collective
                              whose replica groups span BOTH mesh axes
                              without being a full-mesh group — the
                              pod-hang/pod-slow class (arXiv:2211.05102;
                              model-axis traffic must stay inside its
                              row).
``collective-free-serve``     serve-bucket programs (incl. the ``_q8``
                              family) must contain ZERO collectives — a
                              collective in a serve program is a fleet-
                              wide hang the moment replicas stop being
                              single-process.
``zero1-exchange``            the zero1 twins must show reduce-scatter
                              + all-gather REPLACING the gradient
                              all-reduce (bytes-ratio gated against the
                              analytic param footprint and the
                              replicated twin) — the comms dual of the
                              ZeRO-1 0.125x memory gate, and the
                              template ZeRO-2/3 will extend.
``collectives-budget``        a supported entry failed to compile for
                              its comms summary (per-entry, one broken
                              row never costs the rest).

Budgets are defined over the CPU compile (tier-1/CI environment, same
rule as the jaxpr/memory goldens). XLA's CPU pipeline decomposes
reduce-scatter into all-reduce + slice; the extractor re-derives the
logical op from consumer shapes (see ``obs/comms.py``), so the golden
structure means the same thing CPU and TPU. Off-CPU the compare is
skipped with a warning. Regenerate intentionally with ``python -m
tpu_resnet check --update-golden`` and say why in the PR.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from tpu_resnet.analysis.configmatrix import MATRIX, MatrixEntry
from tpu_resnet.analysis.findings import Finding
from tpu_resnet.obs.comms import summarize_collectives

GOLDEN_PATH = os.path.join(os.path.dirname(__file__),
                           "golden_collectives.json")
GOLDEN_FORMAT = 1
# Byte totals get the golden-memory band: the ring-model arithmetic is
# deterministic, but payload rounding across jax/XLA releases (fusion of
# small reductions, combined BN-stat tuples) can shuffle a few percent.
# The STRUCTURE (op multiset, signatures, axis buckets) compares exactly
# — structure drift is never compiler noise.
DEFAULT_TOLERANCE = 0.10
SLACK_BYTES = 4096

# Banded byte components of a collectives summary.
BYTE_COMPONENTS = ("wire_bytes_per_device", "all_gather_bytes",
                   "reduce_scatter_bytes", "plain_all_reduce_bytes")

# zero1-exchange gates, as fractions of the analytic replicated param
# footprint (params_argument_bytes, exact partitioner arithmetic):
# the scattered/gathered float bytes must each cover most of the
# parameters (momentum exchange = one scatter + one gather of every
# divisible leaf; BN moments and axis-undivisible leaves stay plain,
# hence < 1.0), and the plain float all-reduce bytes must have DROPPED
# well below the replicated twin's (the "replacing" proof).
ZERO1_MIN_EXCHANGE_FRACTION = 0.75
ZERO1_MAX_PLAIN_FRACTION = 0.50

# stray-gather fires when a non-zero1 train program all-gathers float
# payloads at parameter scale — small halo/metric gathers stay legal.
STRAY_GATHER_FRACTION = 0.25


def entry_comms_summary(entry: MatrixEntry) -> dict:
    """Compile ``entry`` (shared, cached compile —
    ``memorybudget.entry_artifacts``) and summarize its collectives.
    The summary carries ``params_argument_bytes`` from the memory
    budget so the zero1/stray-gather gates can compare wire traffic
    against the analytic parameter footprint without a second source of
    truth."""
    from tpu_resnet.analysis import memorybudget

    art = memorybudget.entry_artifacts(entry)
    if art["hlo_text"] is None:
        raise RuntimeError("backend reported no HLO text for the "
                           "compiled program")
    summary = summarize_collectives(art["hlo_text"], entry.data_axis,
                                    entry.model_axis)
    summary["partition"] = entry.partition
    budget = art["budget"]
    summary["params_argument_bytes"] = int(
        budget.get("params_argument_bytes")
        or budget.get("weight_argument_bytes") or 0)
    return summary


# ----------------------------------------------------------- named rules
def _rule_collective_free_serve(entry: MatrixEntry,
                                summary: dict) -> List[Finding]:
    if entry.builder != "serve":
        return []
    if summary["collective_count"] == 0:
        return []
    ops = ", ".join(f"{op} x{n}" for op, n in summary["ops"].items())
    return [Finding(
        "collective-free-serve", f"<golden-collectives>/{entry.name}", 0,
        f"serve-bucket program contains {summary['collective_count']} "
        f"collective(s) ({ops}) — serve programs must be collective-free: "
        f"any cross-device op in the inference path becomes a fleet-wide "
        f"hang the moment replicas stop being single-process "
        f"(serve/infer.py replicates weights; nothing it computes may "
        f"synchronize devices)")]


def _rule_stray_gather(entry: MatrixEntry, summary: dict) -> List[Finding]:
    if entry.builder == "serve" or entry.partition == "zero1":
        return []
    params = summary.get("params_argument_bytes", 0)
    ag = summary.get("all_gather_bytes", 0)
    if not params or ag < STRAY_GATHER_FRACTION * params:
        return []
    return [Finding(
        "stray-gather", f"<golden-collectives>/{entry.name}", 0,
        f"replicated-mode program all-gathers {ag:,} float bytes "
        f"(>= {STRAY_GATHER_FRACTION:.0%} of the {params:,}-byte param "
        f"footprint) — replicated state must never be re-gathered: this "
        f"is the ZeRO-bloat regression (a sharding constraint leaked "
        f"into a replicated program, paying ZeRO's exchange without its "
        f"memory cut)")]


def _rule_axis_confinement(entry: MatrixEntry,
                           summary: dict) -> List[Finding]:
    if entry.model_axis <= 1:
        return []
    mixed = summary.get("bytes_by_axis", {}).get("mixed")
    if not mixed:
        return []
    return [Finding(
        "axis-confinement", f"<golden-collectives>/{entry.name}", 0,
        f"2-D mesh program moves {mixed:,} bytes on collectives whose "
        f"replica groups span BOTH mesh axes without covering the full "
        f"mesh — model-axis traffic must stay inside its mesh row "
        f"(groups varying only the model coordinate) and gradient "
        f"traffic inside its column; a diagonal group serializes the "
        f"ICI links both ways (the pod-slow class, arXiv:2211.05102)")]


def _rule_zero1_exchange(entry: MatrixEntry, summary: dict,
                         twin: Optional[dict]) -> List[Finding]:
    if entry.partition != "zero1" or entry.data_axis <= 1:
        return []
    path = f"<golden-collectives>/{entry.name}"
    params = summary.get("params_argument_bytes", 0)
    findings: List[Finding] = []
    floor = ZERO1_MIN_EXCHANGE_FRACTION * params
    for comp, label in (("reduce_scatter_bytes", "reduce-scatter"),
                        ("all_gather_bytes", "all-gather")):
        got = summary.get(comp, 0)
        if got < floor:
            findings.append(Finding(
                "zero1-exchange", path, 0,
                f"zero1 program {label}s only {got:,} float bytes, below "
                f"{ZERO1_MIN_EXCHANGE_FRACTION:.0%} of the {params:,}-"
                f"byte param footprint — the ZeRO-1 exchange (scatter "
                f"the gradient, gather the updated shard) is missing or "
                f"degraded; the partitioner's constraints "
                f"(parallel/zero.py zero1_update) are not reaching the "
                f"compiled program"))
    plain = summary.get("plain_all_reduce_bytes", 0)
    ceiling = ZERO1_MAX_PLAIN_FRACTION * (
        twin.get("plain_all_reduce_bytes", 0) if twin else 0)
    if twin and plain > ceiling:
        findings.append(Finding(
            "zero1-exchange", path, 0,
            f"zero1 program still moves {plain:,} float bytes as PLAIN "
            f"all-reduce vs {twin.get('plain_all_reduce_bytes', 0):,} in "
            f"its replicated twin (gate: < "
            f"{ZERO1_MAX_PLAIN_FRACTION:.0%}) — reduce-scatter + "
            f"all-gather must REPLACE the gradient all-reduce, not ride "
            f"alongside it; only BN moments and axis-undivisible leaves "
            f"may stay plain"))
    return findings


def apply_rules(entry: MatrixEntry, summary: dict,
                twin: Optional[dict] = None) -> List[Finding]:
    """Every semantic rule over one entry's comms summary. ``twin`` is
    the replicated twin's summary for zero1 rows (found by stripping
    ``_zero1`` from the entry name), when it compiled this run."""
    findings: List[Finding] = []
    findings.extend(_rule_collective_free_serve(entry, summary))
    findings.extend(_rule_stray_gather(entry, summary))
    findings.extend(_rule_axis_confinement(entry, summary))
    findings.extend(_rule_zero1_exchange(entry, summary, twin))
    return findings


# ------------------------------------------------------- golden workflow
def _compare(name: str, want: dict, got: dict,
             tolerance: float) -> List[Finding]:
    path = f"<golden-collectives>/{name}"
    findings: List[Finding] = []
    for comp in ("ops", "structure"):
        w, g = want.get(comp, {}), got.get(comp, {})
        if w == g:
            continue
        gone = sorted(set(w) - set(g))
        new = sorted(set(g) - set(w))
        moved = sorted(k for k in set(w) & set(g) if w[k] != g[k])
        detail = "; ".join(
            s for s in (f"removed: {', '.join(gone)}" if gone else "",
                        f"added: {', '.join(new)}" if new else "",
                        f"recount: {', '.join(f'{k} {w[k]}->{g[k]}' for k in moved)}"
                        if moved else "") if s)
        findings.append(Finding(
            "golden-collectives-drift", path, 0,
            f"collective {comp} drifted from golden ({detail}) — the "
            f"compiled program's communication structure changed. If "
            f"intended (new partition rule, optimizer change), "
            f"regenerate via `python -m tpu_resnet check "
            f"--update-golden` and say why in the PR; structure is "
            f"exact, never compiler noise"))
    for comp in BYTE_COMPONENTS:
        w = int(want.get(comp, 0) or 0)
        g = int(got.get(comp, 0) or 0)
        if abs(g - w) <= max(tolerance * max(w, g), SLACK_BYTES):
            continue
        ratio = g / w if w else float("inf")
        findings.append(Finding(
            "golden-collectives-drift", path, 0,
            f"{comp} drifted {w:,} -> {g:,} bytes ({ratio:.2f}x), "
            f"outside the ±{tolerance:.0%} band — the program's bytes-"
            f"on-wire changed. If intended, regenerate via `python -m "
            f"tpu_resnet check --update-golden` and say why; if not, "
            f"this is a silent comms regression caught at review time"))
    wa = {k: int(v) for k, v in want.get("bytes_by_axis", {}).items()}
    ga = {k: int(v) for k, v in got.get("bytes_by_axis", {}).items()}
    for axis in sorted(set(wa) | set(ga)):
        w, g = wa.get(axis, 0), ga.get(axis, 0)
        if abs(g - w) > max(tolerance * max(w, g), SLACK_BYTES):
            findings.append(Finding(
                "golden-collectives-drift", path, 0,
                f"bytes on the '{axis}' mesh axis drifted {w:,} -> "
                f"{g:,} — traffic moved between mesh axes relative to "
                f"golden. If intended, regenerate via `python -m "
                f"tpu_resnet check --update-golden` and say why"))
    return findings


def load_golden(path: str = GOLDEN_PATH) -> dict:
    try:
        with open(path) as fh:
            return json.load(fh)
    except FileNotFoundError:
        return {"format": GOLDEN_FORMAT, "entries": {}}


def save_golden(golden: dict, path: str = GOLDEN_PATH) -> None:
    golden["entries"] = dict(sorted(golden["entries"].items()))
    with open(path, "w") as fh:
        json.dump(golden, fh, indent=1)
        fh.write("\n")


def _twin_name(entry: MatrixEntry) -> str:
    return entry.name.replace("_zero1", "")


def verify_collectives(entries: Optional[Tuple[MatrixEntry, ...]] = None,
                       update_golden: bool = False,
                       golden_path: str = GOLDEN_PATH,
                       tolerance: Optional[float] = None,
                       progress=None) -> Tuple[List[Finding], dict]:
    """Compile every supported matrix entry (shared cache with the
    memory engine) and verify — or, with ``update_golden``, rewrite —
    its golden collectives summary. Returns ``(findings, stats)``. The
    semantic rules (stray-gather, axis-confinement, collective-free-
    serve, zero1-exchange) run in BOTH modes: a regen can never bake a
    violation into the golden file."""
    import jax

    entries = MATRIX if entries is None else entries
    golden = load_golden(golden_path)
    tol = (tolerance if tolerance is not None
           else float(golden.get("tolerance", DEFAULT_TOLERANCE)))
    on_cpu = jax.default_backend() == "cpu"
    findings: List[Finding] = []
    stats = {"compiled": 0, "compared": 0, "updated": [],
             "skipped_devices": 0, "failed": 0}

    if not on_cpu:
        findings.append(Finding(
            "golden-collectives-drift", "<golden-collectives>", 0,
            f"golden collectives "
            f"{'update' if update_golden else 'compare'} skipped on "
            f"backend '{jax.default_backend()}' (summaries are defined "
            f"over the CPU compile, like the jaxpr/memory goldens)",
            "warning"))
        return findings, stats

    live = [e for e in entries
            if e.expect_error is None and e.builder != "ctor-bn-axis"
            and e.data_axis * e.model_axis <= len(jax.devices())]
    stats["skipped_devices"] = sum(
        1 for e in entries
        if e.expect_error is None and e.builder != "ctor-bn-axis") \
        - len(live)
    summaries: Dict[str, dict] = {}
    for entry in live:
        if progress:
            progress(entry.name)
        try:
            summaries[entry.name] = entry_comms_summary(entry)
            stats["compiled"] += 1
        except Exception as e:  # one broken entry must not cost the rest
            stats["failed"] += 1
            findings.append(Finding(
                "collectives-budget",
                f"<golden-collectives>/{entry.name}", 0,
                f"supported combination FAILED to compile for its comms "
                f"summary: {type(e).__name__}: {e}"))

    for entry in live:
        summary = summaries.get(entry.name)
        if summary is None:
            continue
        # Semantic rules always run — including under --update-golden.
        findings.extend(apply_rules(entry, summary,
                                    twin=summaries.get(_twin_name(entry))))
        if update_golden:
            golden["entries"][entry.name] = summary
            stats["updated"].append(entry.name)
            continue
        want = golden["entries"].get(entry.name)
        if want is None:
            findings.append(Finding(
                "golden-collectives-drift",
                f"<golden-collectives>/{entry.name}", 0,
                "no golden collectives summary recorded for this entry "
                "— run `python -m tpu_resnet check --update-golden` and "
                "commit the regenerated "
                "analysis/golden_collectives.json"))
            continue
        stats["compared"] += 1
        findings.extend(_compare(entry.name, want, summary, tol))

    if update_golden:
        keep = {e.name for e in entries
                if e.expect_error is None and e.builder != "ctor-bn-axis"}
        golden["entries"] = {k: v for k, v in golden["entries"].items()
                             if k in keep}
        golden["format"] = GOLDEN_FORMAT
        golden["tolerance"] = tol
        golden["jax"] = jax.__version__
        save_golden(golden, golden_path)
    return findings, stats
