"""Static-analysis suite — ``python -m tpu_resnet check``.

Four engines over one Finding model (docs/CHECKS.md):

``jaxlint``       AST lints for the repo's JAX/TPU contracts (host-sync
                  hazards under jit, static-arg hygiene, fork-safe worker
                  import closure, signal-handler safety, fail-loud guard
                  parity). Pure ``ast`` — importing it never imports jax.
``configmatrix``  abstract-eval verifier: traces the real train/eval
                  steps for every supported config combination on an
                  abstract mesh and checks dtype discipline, donation
                  layout, sharding contracts and golden jaxpr hashes
                  (the golden memory budgets ride on the same entries).
``concurrency``   thread/lock race detector: per-class thread-context
                  graphs over every threaded module (batcher, router,
                  data engine, watchdog, pollers) with unguarded-write /
                  guard-consistency / lock-order / blocking-under-lock /
                  daemon-teardown rules. Pure ``ast``.
``spmd``          SPMD-divergence lint for the multi-host on-ramp:
                  process-identity-gated dispatch/collectives, shared
                  train_dir artifact writer discipline, unordered
                  iteration feeding program construction. Pure ``ast``.

Import note: keep this ``__init__`` lazy-free and jax-free so the
lint-only CLI path stays sub-second.
"""

from tpu_resnet.analysis.concurrency import (CONCURRENCY_RULES,
                                             run_concurrency)
from tpu_resnet.analysis.findings import (
    Finding,
    apply_baseline,
    apply_pragmas,
    load_baseline,
    render_report,
    save_baseline,
)
from tpu_resnet.analysis.jaxlint import RULES, run_jaxlint
from tpu_resnet.analysis.spmd import SPMD_RULES, run_spmd

__all__ = [
    "CONCURRENCY_RULES",
    "Finding",
    "RULES",
    "SPMD_RULES",
    "apply_baseline",
    "apply_pragmas",
    "load_baseline",
    "render_report",
    "run_concurrency",
    "run_jaxlint",
    "run_spmd",
    "save_baseline",
]
