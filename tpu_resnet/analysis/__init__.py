"""Static-analysis suite — ``python -m tpu_resnet check``.

Two engines over one Finding model (docs/CHECKS.md):

``jaxlint``       AST lints for the repo's JAX/TPU contracts (host-sync
                  hazards under jit, static-arg hygiene, fork-safe worker
                  import closure, signal-handler safety, fail-loud guard
                  parity). Pure ``ast`` — importing it never imports jax.
``configmatrix``  abstract-eval verifier: traces the real train/eval
                  steps for every supported config combination on an
                  abstract mesh and checks dtype discipline, donation
                  layout, sharding contracts and golden jaxpr hashes.

Import note: keep this ``__init__`` lazy-free and jax-free so the
lint-only CLI path stays sub-second.
"""

from tpu_resnet.analysis.findings import (
    Finding,
    apply_baseline,
    apply_pragmas,
    load_baseline,
    render_report,
    save_baseline,
)
from tpu_resnet.analysis.jaxlint import RULES, run_jaxlint

__all__ = [
    "Finding",
    "RULES",
    "apply_baseline",
    "apply_pragmas",
    "load_baseline",
    "render_report",
    "run_jaxlint",
    "save_baseline",
]
