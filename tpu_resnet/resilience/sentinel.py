"""NaN/divergence sentinel — bounded rollback-and-retry policy.

A diverged run (NaN/Inf loss from an LR spike, a poisoned batch, or a bad
host) burns its whole remaining budget producing garbage: every parameter
is NaN within one step and the reference would happily train to step 100k
that way. The sentinel checks loss finiteness **at the existing log
boundaries only** — the loop already host-syncs the metrics dict there, so
the check costs zero extra device syncs and never changes fusion/chunking
behavior.

The sentinel owns the *policy* (how many rollbacks before giving up); the
*mechanics* (checkpoint restore, data-stream advance) live in
``train/loop.py`` where the state and iterator are.
"""

from __future__ import annotations

import logging
import math

log = logging.getLogger("tpu_resnet")


class DivergenceError(RuntimeError):
    """Training diverged and rollback retries are exhausted (or there is
    no checkpoint to roll back to) — fail loudly instead of training NaNs."""


class NaNSentinel:
    def __init__(self, max_retries: int = 2, enabled: bool = True):
        self.enabled = enabled
        self.max_retries = int(max_retries)
        self.rollbacks = 0

    def check(self, step: int, loss: float) -> bool:
        """True ⇒ the loop must roll back (non-finite loss and the sentinel
        is enabled). Raises :class:`DivergenceError` when retries are
        exhausted; the message carries everything the operator needs."""
        if not self.enabled or math.isfinite(loss):
            return False
        if self.rollbacks >= self.max_retries:
            raise DivergenceError(
                f"non-finite loss ({loss}) at step {step} after "
                f"{self.rollbacks} rollback(s) — divergence persists past "
                f"resilience.nan_max_retries={self.max_retries}; lower the "
                f"LR / inspect the data around this step window")
        self.rollbacks += 1
        log.warning("non-finite loss (%s) at step %d — rolling back to the "
                    "last checkpoint and skipping the bad data window "
                    "(retry %d/%d)", loss, step, self.rollbacks,
                    self.max_retries)
        return True

    def no_checkpoint(self, step: int, loss: float) -> DivergenceError:
        """The error for a divergence with nothing to roll back to."""
        return DivergenceError(
            f"non-finite loss ({loss}) at step {step} and no checkpoint "
            f"exists to roll back to — failing immediately (first "
            f"checkpoint lands at train.checkpoint_every)")
