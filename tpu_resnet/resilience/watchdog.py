"""Hang watchdog — stalls become diagnosable events, not silent hangs.

A wedged collective, a dead data source, or a blocked host thread leaves
the reference trainer sitting at 0% CPU forever; the only signal is an
operator noticing the log went quiet (SURVEY.md §5). The watchdog is a
daemon thread fed a cheap ``progress(step)`` call at every chunk boundary.
When no progress lands for ``stall_sec``:

- all-thread stacks are dumped to ``<train_dir>/stall_stacks_<n>.txt``
  (the "where is it stuck" evidence, captured while it is stuck);
- the telemetry registry is marked unhealthy, so ``/healthz`` answers 503
  with the stall reason even though the heartbeat-staleness threshold
  (``train.telemetry_stale_sec``, typically minutes) has not tripped yet;
- a ``watchdog_stall`` span is recorded and the
  ``fault_watchdog_stalls`` gauge incremented.

If progress then resumes (transient stall — a slow storage blip, a
recovered data source), the unhealthy mark is cleared and a
``watchdog_recovered`` span records the outage length. Timing is armed by
the FIRST ``progress()`` call, so the first-dispatch compile (minutes on
a cold pod) can never false-trigger it.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
import traceback
from typing import Optional

log = logging.getLogger("tpu_resnet")


def dump_all_stacks(path: str, reason: str = "") -> None:
    """Write every live thread's stack to ``path`` (best-effort)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = [f"# all-thread stack dump @ {time.strftime('%F %T')}"]
    if reason:
        lines.append(f"# reason: {reason}")
    for ident, frame in sys._current_frames().items():
        lines.append(f"\n--- thread {names.get(ident, '?')} ({ident}) ---")
        lines.extend(l.rstrip() for l in traceback.format_stack(frame))
    try:
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
    except OSError as e:  # diagnostics must never crash the diagnosed
        log.warning("could not write stack dump %s: %s", path, e)


class HangWatchdog:
    """``maybe_start`` returns None when ``stall_sec <= 0`` (disabled)."""

    def __init__(self, stall_sec: float, train_dir: str, telemetry=None,
                 spans=None, poll_sec: Optional[float] = None):
        self.stall_sec = float(stall_sec)
        self.train_dir = train_dir
        self._telemetry = telemetry
        self._spans = spans
        self._poll = poll_sec if poll_sec else min(self.stall_sec / 4, 5.0)
        self._lock = threading.Lock()
        self._last_wall: Optional[float] = None  # armed by first progress()
        self._last_step: Optional[int] = None
        self._stalled_since: Optional[float] = None
        self.stalls = 0
        self.dumps = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="tpu-resnet-watchdog",
                                        daemon=True)

    @classmethod
    def maybe_start(cls, stall_sec: float, train_dir: str, telemetry=None,
                    spans=None) -> Optional["HangWatchdog"]:
        if stall_sec is None or stall_sec <= 0:
            return None
        wd = cls(stall_sec, train_dir, telemetry=telemetry, spans=spans)
        wd.start()
        return wd

    def start(self) -> "HangWatchdog":
        self._thread.start()
        return self

    def progress(self, step: int) -> None:
        """Mark step progress; called at every chunk boundary (a lock +
        two assignments — nanoseconds against a multi-ms chunk)."""
        with self._lock:
            self._last_wall = time.monotonic()
            self._last_step = int(step)

    def close(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=self._poll + 5)

    # ------------------------------------------------------------ internals
    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            with self._lock:
                last_wall, last_step = self._last_wall, self._last_step
            if last_wall is None:  # not armed yet (still compiling)
                continue
            stalled = time.monotonic() - last_wall
            if stalled > self.stall_sec and self._stalled_since is None:
                self._stalled_since = last_wall
                self._on_stall(last_step, stalled)
            elif stalled <= self.stall_sec and self._stalled_since \
                    is not None:
                outage = last_wall - self._stalled_since
                self._stalled_since = None
                self._on_recover(last_step, outage)

    def _on_stall(self, step, stalled_sec: float) -> None:
        n = self.stalls + 1
        path = os.path.join(self.train_dir, f"stall_stacks_{n}.txt")
        reason = (f"no step progress for {stalled_sec:.1f}s "
                  f"(> watchdog deadline {self.stall_sec:.1f}s) at step "
                  f"{step}")
        log.error("watchdog: %s — dumping all-thread stacks to %s and "
                  "flipping /healthz unhealthy", reason, path)
        dump_all_stacks(path, reason=reason)
        self.dumps.append(path)
        if self._telemetry is not None:
            self._telemetry.mark_unhealthy(reason)
            self._telemetry.set("fault_watchdog_stalls", n)
        if self._spans is not None:
            self._spans.event("watchdog_stall", step=step,
                              stalled_sec=round(stalled_sec, 3),
                              stack_dump=path)
        # Published last: pollers of ``stalls`` see the dump/telemetry/
        # span side effects already landed.
        self.stalls = n

    def _on_recover(self, step, outage_sec: float) -> None:
        log.warning("watchdog: step progress resumed at step %s after a "
                    "%.1fs stall — clearing the unhealthy mark",
                    step, outage_sec)
        if self._telemetry is not None:
            self._telemetry.clear_unhealthy()
        if self._spans is not None:
            self._spans.event("watchdog_recovered", step=step,
                              outage_sec=round(outage_sec, 3))
