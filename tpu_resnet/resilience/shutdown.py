"""Preemption-graceful shutdown.

Preemptible TPU VMs get SIGTERM with a short grace window before the
machine disappears; the reference trainer dies mid-step and loses
everything since the last 1000-step checkpoint. The coordinator converts
the signal into a *request*: the training loop finishes the in-flight
fused chunk, saves a final checkpoint, runs its normal closer chain, and
``train()`` raises :class:`Preempted` — which the CLI maps to
``PREEMPT_EXIT_CODE`` so a supervisor (tools/supervise.py, or any restart
policy keyed on exit codes) can distinguish "machine reclaimed, resume me"
from a real crash.

A second signal while the first is still being honored escalates: the
original handlers are restored and ``KeyboardInterrupt`` is raised, so an
operator hammering Ctrl-C is never trapped behind a slow final save.
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Optional

from tpu_resnet.resilience import exitcodes

log = logging.getLogger("tpu_resnet")

# Canonical value lives in resilience/exitcodes.py; re-exported here
# because this module defined it first and callers import it from here.
PREEMPT_EXIT_CODE = exitcodes.PREEMPTED


class Preempted(Exception):
    """Raised by ``train()`` after a graceful preemption stop: the final
    checkpoint is on disk and telemetry is closed. Carries the stop step
    and the final state so in-process callers (tests, notebooks) can
    inspect them; the CLI maps it to ``PREEMPT_EXIT_CODE``."""

    def __init__(self, step: int, state=None, signum: Optional[int] = None):
        self.step = int(step)
        self.state = state
        self.signum = signum
        name = signal.Signals(signum).name if signum is not None else "?"
        super().__init__(
            f"training preempted by {name} at step {step}; final "
            f"checkpoint saved — restart to resume")


class ShutdownCoordinator:
    """Installable SIGTERM/SIGINT → stop-request flag.

    ``install()`` is a no-op off the main thread (CPython only delivers
    signals there, and ``signal.signal`` raises elsewhere) and when
    ``enabled=False`` — ``requested`` then just stays False and the
    process keeps its default signal behavior."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, enabled: bool = True,
                 action_desc: Optional[str] = None):
        """``action_desc`` is what the first-signal log line promises the
        process will now do — the trainer's default below; the predict
        server passes its drain contract (stop accepting, flush the
        queue, exit 0) so operators aren't told to expect exit 42."""
        self.enabled = enabled
        self.action_desc = action_desc or (
            f"finishing the current chunk, saving a final checkpoint, "
            f"then exiting with code {PREEMPT_EXIT_CODE}")
        self.signum: Optional[int] = None
        self.requested_at: Optional[float] = None
        self._event = threading.Event()
        self._previous = {}

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    @property
    def event(self) -> threading.Event:
        """The stop-request event, for consumers that block outside the
        loop (e.g. the input pipeline's consumer-side get)."""
        return self._event

    def request_stop(self, signum: Optional[int] = None) -> None:
        """Programmatic stop request (what the signal handler calls)."""
        if self.signum is None:
            self.signum = signum
            self.requested_at = time.time()
        self._event.set()

    def _handle(self, signum, frame) -> None:
        if self._event.is_set():
            # Second signal: the operator wants OUT, not a slow final
            # save. Put the default handlers back and raise.
            self.uninstall()
            raise KeyboardInterrupt(
                f"second {signal.Signals(signum).name} during graceful "
                f"shutdown — aborting immediately")
        log.warning("received %s: %s (send again to abort immediately)",
                    signal.Signals(signum).name, self.action_desc)
        self.request_stop(signum)

    def install(self) -> "ShutdownCoordinator":
        if not self.enabled or self._previous:
            return self
        if threading.current_thread() is not threading.main_thread():
            return self
        for sig in self.SIGNALS:
            try:
                self._previous[sig] = signal.signal(sig, self._handle)
            except (ValueError, OSError):  # exotic embedding; stay inert
                self._previous.pop(sig, None)
        return self

    def uninstall(self) -> None:
        prev, self._previous = self._previous, {}
        for sig, handler in prev.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):
                pass

    def __enter__(self) -> "ShutdownCoordinator":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
