"""Elastic capacity — resume on whatever devices exist.

Production TPU fleets are preemptible and capacity is diurnal: the
machine a run resumes on is routinely NOT the machine it checkpointed
on. Before this module every resume assumed the exact mesh shape and
``mesh.partition`` mode that wrote the checkpoint — a run that lost half
its chips was dead, not degraded (the same rigidity as the reference's
``ps_hosts``/``worker_hosts`` launchers, which could only ever restart
the cluster they were scripted for).

This module makes topology a RUNTIME variable, composing two contracts
the repo already proved separately:

- PR 2's preempt/resume contract: SIGTERM → final checkpoint → exit 42 →
  supervisor restarts → resume at the exact stop step;
- PR 9's cross-partition restore: orbax checkpoints store **global
  logical arrays** (layout-free), and every restore goes through the
  partitioner's abstract template — so restoring into a DIFFERENT layout
  is an explicit, value-identical reshard, never a corruption.

The composition: on restart, :func:`resolve` inspects the devices that
actually exist, re-derives the mesh (``parallel.fit_mesh`` — an explicit
``mesh.data`` that no longer fits shrinks to what does; ``-1`` follows
the hardware in both directions) and hands the loop a mesh whose
partitioner template the checkpoint restores straight into — 8→4→2
chips, replicated↔zero1, any direction. The global batch is the
INVARIANT: per-device batch rescales with the data axis, the host-side
work-order slicing (a pure function of ``(seed, step)`` and the
per-process batch) is untouched, so the deterministic batch stream
continues bit-compatibly across the reshape (ROADMAP's contract; the
``doctor --reshape-drill`` gate).

Every run records the topology it trained on in
``<train_dir>/topology.json`` (:func:`write_topology`); a resume whose
topology differs emits a ``topology_change`` span on the run timeline
and a manifest entry, so trace-export and perfwatch can see capacity
waves instead of inferring them from throughput cliffs.

Colocation (the other half of riding capacity waves): a serve replica
joining a trainer's host asks :func:`colocation_admission` first — the
verdict is arbitrated by the PR 8 live HBM gauges
(``device.memory_stats()``), falling back to the per-chip capacity
table, so admission is a measured decision, not hope. Each tenant then
drains per its established contract (trainer: exit 42; serve: drain,
exit 0).

Import stays jax-free at module level (jax only inside functions): the
supervisor-side and doctor-side consumers read topology records on
hosts whose accelerator stack may be the thing that is broken.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Optional

log = logging.getLogger("tpu_resnet")

TOPOLOGY_FILE = "topology.json"

# topology dict keys (one flat schema, stdlib-readable):
#   devices        int   total devices the mesh used
#   mesh_shape     dict  {"data": N, "model": M}
#   partition      str   mesh.partition mode the run trained with
#   global_batch   int   train.global_batch_size (the elastic invariant)
#   device_kind    str   e.g. "TPU v5e" / "cpu"


def topology_record(mesh, partition: str, global_batch: int) -> dict:
    """The one constructor of the topology-record schema — shared by
    :func:`write_topology`, :func:`resolve` and the loop's caller-mesh
    fallback, so the records the reshape diff and the restore-error
    hints compare can never drift field-by-field."""
    devices = list(mesh.devices.flat)
    return {
        "devices": len(devices),
        "mesh_shape": dict(mesh.shape),
        "partition": str(partition),
        "global_batch": int(global_batch),
        "device_kind": devices[0].device_kind if devices else "",
    }


def write_topology(train_dir: str, mesh, partition: str,
                   global_batch: int) -> Optional[str]:
    """Record the topology that is writing this directory's checkpoints
    (primary-only, atomic — the same writer discipline as manifest.json).

    The loop calls this on the FIRST SUCCESSFUL SAVE of a (re)start, not
    at startup: the file must name the topology that wrote the NEWEST
    checkpoints — a resume that reshapes but dies before its first save
    leaves the record pointing at the old topology, so the next resume
    still detects the reshape and restore errors still blame the right
    saver."""
    from tpu_resnet import parallel

    if not parallel.is_primary():
        return None
    record = topology_record(mesh, partition, global_batch)
    os.makedirs(train_dir, exist_ok=True)
    path = os.path.join(train_dir, TOPOLOGY_FILE)
    tmp = path + f".tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1)
        os.replace(tmp, path)
    except OSError as e:  # recording is best-effort; training must not die
        log.warning("could not write %s: %s", path, e)
        return None
    return path


def read_topology(train_dir: str) -> Optional[dict]:
    """The topology record of the run that last trained in
    ``train_dir``; None for a fresh directory (or a pre-elastic one)."""
    try:
        with open(os.path.join(train_dir, TOPOLOGY_FILE)) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) and "mesh_shape" in rec else None


def describe(topology: Optional[dict]) -> str:
    """One-line human form of a topology record ('unknown' when None) —
    shared by the reshape log lines and the restore error hints."""
    if not topology:
        return "unknown (no topology record)"
    return (f"mesh {topology.get('mesh_shape')} "
            f"partition={topology.get('partition')} "
            f"({topology.get('devices')} device(s), "
            f"global batch {topology.get('global_batch')})")


@dataclasses.dataclass
class ElasticResume:
    """The resolved topology decision for one (re)start."""

    mesh: object                    # the concrete Mesh to train on
    current: dict                   # topology record this run will write
    prior: Optional[dict] = None    # record of the run that checkpointed
    downsized: bool = False         # requested mesh.data didn't fit
    requested_data: int = -1        # cfg.mesh.data as configured
    stream_compatible: bool = True  # global batch unchanged vs prior

    @property
    def changed(self) -> bool:
        """True when this run's topology differs from the recorded one —
        the condition for a ``topology_change`` span/manifest entry."""
        if self.prior is None:
            return False
        return any(
            self.prior.get(k) != self.current.get(k)
            for k in ("mesh_shape", "partition", "global_batch"))

    def attrs(self) -> dict:
        """Span/manifest attributes describing the reshape."""
        out = {
            "from_mesh": (self.prior or {}).get("mesh_shape"),
            "to_mesh": self.current["mesh_shape"],
            "from_partition": (self.prior or {}).get("partition"),
            "to_partition": self.current["partition"],
            "from_devices": (self.prior or {}).get("devices"),
            "to_devices": self.current["devices"],
            "global_batch": self.current["global_batch"],
            "stream_compatible": self.stream_compatible,
        }
        if self.downsized:
            out["downsized_from_requested_data"] = self.requested_data
        return out


def resolve(cfg, devices=None, train_dir: Optional[str] = None
            ) -> ElasticResume:
    """Derive the mesh for THIS restart from the devices that actually
    exist, and detect whether that is a reshape of the recorded run.

    - ``mesh.data=-1`` follows the hardware in both directions (today's
      behavior, now recorded as an explicit decision);
    - an explicit ``mesh.data`` that no longer fits is DOWNSIZED to the
      largest data axis the devices support (a warning, a
      ``topology_change`` record — not a dead run);
    - the global batch must divide the new data axis: the global batch
      is the determinism invariant (the host batch stream is a pure
      function of (seed, step) and the per-process batch), so it never
      rescales implicitly — a non-divisible combination raises with
      both topologies named;
    - a CHANGED ``train.global_batch_size`` vs the record is allowed but
      loudly marked ``stream_compatible=False`` — the resumed stream is
      a different stream, and every downstream consumer of the span
      should know.
    """
    import jax

    from tpu_resnet import parallel

    devices = list(devices if devices is not None else jax.devices())
    train_dir = train_dir or cfg.train.train_dir
    requested_data = getattr(cfg.mesh, "data", -1)
    data, model, downsized = parallel.fit_mesh(cfg.mesh, len(devices))
    mesh_cfg = dataclasses.replace(cfg.mesh, data=data, model=model)
    mesh = parallel.create_mesh(mesh_cfg, devices=devices[:data * model])
    prior = read_topology(train_dir)

    if cfg.train.global_batch_size % data:
        raise ValueError(
            f"elastic resume: global batch {cfg.train.global_batch_size} "
            f"does not divide the {data}-way data axis of the mesh this "
            f"host supports ({len(devices)} device(s)); checkpoint "
            f"topology: {describe(prior)}. The global batch is the "
            f"deterministic-stream invariant and never rescales "
            f"implicitly — pick a device count whose data axis divides "
            f"it, or change train.global_batch_size knowingly.")

    current = topology_record(mesh,
                              getattr(cfg.mesh, "partition", "replicated"),
                              cfg.train.global_batch_size)
    resume = ElasticResume(
        mesh=mesh, current=current, prior=prior, downsized=downsized,
        requested_data=requested_data,
        stream_compatible=(prior is None or prior.get("global_batch")
                           == current["global_batch"]))
    if downsized:
        log.warning(
            "elastic resume: mesh.data=%d does not fit on %d device(s) — "
            "downsizing to a %dx%d mesh (checkpoint topology: %s)",
            requested_data, len(devices), data, model, describe(prior))
    if resume.changed:
        log.warning(
            "topology change on resume: %s -> %s — restoring through the "
            "partitioner template (explicit cross-topology reshard)%s",
            describe(prior), describe(current),
            "" if resume.stream_compatible else
            "; GLOBAL BATCH CHANGED: the deterministic (seed, step) batch "
            "stream does NOT continue bit-compatibly")
    return resume


# ------------------------------------------------------ colocation admission
def colocation_admission(required_bytes: int, devices=None,
                         reserve_frac: float = 0.05) -> dict:
    """May a new workload (a serve replica, a second trainer) join this
    host's devices? Arbitrated by the live PR 8 HBM gauges.

    Returns ``{"admit": bool, "reason": str, "required_bytes": int,
    "headroom_bytes": int|None, "in_use_bytes": int, "limit_bytes":
    int|None}``. Decision order:

    1. live ``device.memory_stats()`` (``obs.memory.sample_device_memory``)
       — in-use and limit come from the device itself;
    2. no stats (CPU rehearsal, older plugins): the per-chip capacity
       table / ``TPU_RESNET_HBM_BYTES`` override supplies the limit and
       in-use is taken as 0;
    3. no limit from anywhere: admit with an explicit "not arbitrated"
       reason — an un-gauged host must not hard-deny capacity it cannot
       measure, but the verdict says so.

    ``reserve_frac`` holds back a slice of the limit for allocator slack
    and the incumbent's transient peaks (fragmentation, checkpoint
    restore double-residency)."""
    import jax

    from tpu_resnet.obs import memory as memory_obs

    if devices is None:
        devices = jax.local_devices()
    sample = memory_obs.sample_device_memory(devices)
    in_use = int(sample.get("hbm_bytes_in_use", 0))
    limit = sample.get("hbm_bytes_limit")
    if limit is None and devices:
        limit = memory_obs.hbm_bytes_per_chip(
            getattr(devices[0], "device_kind", ""))
    verdict = {"required_bytes": int(required_bytes),
               "in_use_bytes": in_use,
               "limit_bytes": int(limit) if limit else None,
               "headroom_bytes": None}
    if not limit:
        verdict.update(admit=True,
                       reason="no device memory limit known — admission "
                              "not arbitrated (set TPU_RESNET_HBM_BYTES "
                              "to arbitrate on this backend)")
        return verdict
    headroom = int(limit * (1.0 - reserve_frac)) - in_use
    verdict["headroom_bytes"] = headroom
    if required_bytes <= headroom:
        verdict.update(admit=True,
                       reason=f"fits: {int(required_bytes):,} B required "
                              f"<= {headroom:,} B headroom")
    else:
        verdict.update(admit=False,
                       reason=f"denied: {int(required_bytes):,} B required "
                              f"> {headroom:,} B headroom "
                              f"({in_use:,} B in use of {int(limit):,} B, "
                              f"{reserve_frac:.0%} reserved)")
    return verdict
