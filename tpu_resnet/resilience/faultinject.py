"""Deterministic fault injection — the drill harness.

A recovery path that has never fired is a recovery path that does not
work. The injector plants exactly one fault of each requested kind at a
deterministic step, so the drill tests (tests/test_resilience_drills.py)
and ``doctor --fault-drill`` can prove every path end-to-end: NaN batch →
sentinel rollback; data stall → watchdog fires and the stream recovers;
SIGTERM → graceful save + distinct exit code + resume; corrupt checkpoint
→ restore fallback. The serve-side faults (slow inference, accept-then-
hang, SIGKILL at request K) are the same idea pointed at the predict
fleet: ``doctor --fleet-probe`` and the loadgen chaos scenarios use them
to prove the router's failover/eviction paths (docs/SERVING.md).

Everything is **off by default**: an empty plan wraps nothing and costs
nothing. Sources, in precedence order:

1. ``TPU_RESNET_FAULT_*`` environment variables (drills driven from
   outside the config system, e.g. a supervisor chaos schedule);
2. the ``resilience.inject_*`` config fields.

Each fault is one-shot *per injector object* — the injector outlives a
sentinel rollback's iterator rebuild, so a recovered run does not re-hit
the same fault it just survived.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time

import numpy as np

log = logging.getLogger("tpu_resnet")

ENV_PREFIX = "TPU_RESNET_FAULT_"


# Cross-restart burst bookkeeping: one SIGTERM per supervised child, K
# total — the count has to survive the very process deaths it causes,
# so it lives in a train_dir file, not in the injector object.
BURST_STATE_FILE = "fault_burst_state.json"


@dataclasses.dataclass
class FaultPlan:
    nan_at_step: int = -1        # poison the batch consumed at this step
    stall_at_step: int = -1      # producer sleeps before this step's batch
    stall_seconds: float = 0.0
    sigterm_at_step: int = -1    # SIGTERM to self at this chunk boundary
    corrupt_ckpt_at_start: bool = False  # corrupt newest ckpt before restore
    oom_at_step: int = -1        # synthetic RESOURCE_EXHAUSTED at boundary
    preempt_burst: int = 0       # K SIGTERMs total across supervised runs
    preempt_burst_every: int = 10  # each fires this many steps after start
    # ---- serve-side faults (fleet chaos drills; serve/server.py) ----
    serve_slow_ms: float = 0.0       # extra latency per inference batch
    serve_hang_at_request: int = -1  # accept, then hang at request K
    serve_kill_at_request: int = -1  # SIGKILL self at request K
    serve_drop_at_request: int = -1  # close the connection at request K

    @property
    def active(self) -> bool:
        return (self.nan_at_step >= 0 or self.sigterm_at_step >= 0
                or (self.stall_at_step >= 0 and self.stall_seconds > 0)
                or self.corrupt_ckpt_at_start or self.oom_at_step >= 0
                or self.preempt_burst > 0 or self.serves_faults)

    @property
    def serves_faults(self) -> bool:
        return (self.serve_slow_ms > 0 or self.serve_hang_at_request >= 0
                or self.serve_kill_at_request >= 0
                or self.serve_drop_at_request >= 0)

    @classmethod
    def from_config(cls, resilience_cfg, env=None) -> "FaultPlan":
        """Config fields overridden by ``TPU_RESNET_FAULT_*`` env vars:
        NAN_STEP, STALL_STEP, STALL_SEC, SIGTERM_STEP, CORRUPT_CKPT,
        OOM_STEP, PREEMPT_BURST, PREEMPT_BURST_EVERY, SERVE_SLOW_MS,
        SERVE_HANG_REQ, SERVE_KILL_REQ, SERVE_DROP_REQ."""
        env = os.environ if env is None else env
        r = resilience_cfg

        def pick(env_key, cfg_val, cast):
            raw = env.get(ENV_PREFIX + env_key)
            return cast(raw) if raw not in (None, "") else cfg_val

        return cls(
            nan_at_step=pick("NAN_STEP", r.inject_nan_at_step, int),
            stall_at_step=pick("STALL_STEP", r.inject_stall_at_step, int),
            stall_seconds=pick("STALL_SEC", r.inject_stall_seconds, float),
            sigterm_at_step=pick("SIGTERM_STEP", r.inject_sigterm_at_step,
                                 int),
            corrupt_ckpt_at_start=pick(
                "CORRUPT_CKPT", r.inject_corrupt_ckpt,
                lambda v: v.lower() in ("1", "true", "yes")),
            oom_at_step=pick("OOM_STEP", r.inject_oom_at_step, int),
            preempt_burst=pick("PREEMPT_BURST",
                               r.inject_preempt_burst, int),
            preempt_burst_every=pick("PREEMPT_BURST_EVERY",
                                     r.inject_preempt_burst_every, int),
            serve_slow_ms=pick("SERVE_SLOW_MS",
                               r.inject_serve_slow_ms, float),
            serve_hang_at_request=pick("SERVE_HANG_REQ",
                                       r.inject_serve_hang_at_request,
                                       int),
            serve_kill_at_request=pick("SERVE_KILL_REQ",
                                       r.inject_serve_kill_at_request,
                                       int),
            serve_drop_at_request=pick("SERVE_DROP_REQ",
                                       r.inject_serve_drop_at_request,
                                       int),
        )


class FaultInjector:
    """Applies a :class:`FaultPlan`, once per fault, at exact steps.

    ``train_dir`` anchors the cross-restart state of the preemption
    burst (each burst SIGTERM kills this process; the K-of-N count must
    outlive it)."""

    def __init__(self, plan: FaultPlan, train_dir: str = None):
        self.plan = plan
        self.train_dir = train_dir
        self._nan_fired = False
        self._stall_fired = False
        self._sigterm_fired = False
        self._corrupt_fired = False
        self._oom_fired = False
        self._burst_start_step = None  # first boundary this process saw
        self._burst_spent = False      # caches fired >= K (no re-reads)
        self._serve_requests = 0       # predict requests admitted so far
        self._serve_hung = False
        self._serve_dropped = False
        if plan.active:
            log.warning("FAULT INJECTION ACTIVE: %s", plan)

    @property
    def wraps_data(self) -> bool:
        return self.plan.nan_at_step >= 0 or (
            self.plan.stall_at_step >= 0 and self.plan.stall_seconds > 0)

    def wrap_host_batches(self, it, start_step: int = 0):
        """Wrap a host batch iterator; batch ``i`` of the wrapped stream is
        the one consumed at global step ``start_step + i``. Returns ``it``
        untouched when no data fault is planned (the default): zero
        overhead, identical stream object."""
        if not self.wraps_data:
            return it

        def wrapped():
            for i, (images, labels) in enumerate(it):
                step = start_step + i
                if (self.plan.stall_at_step == step
                        and not self._stall_fired):
                    self._stall_fired = True
                    log.warning("injecting %.1fs data stall before the "
                                "step-%d batch", self.plan.stall_seconds,
                                step)
                    time.sleep(self.plan.stall_seconds)
                if self.plan.nan_at_step == step and not self._nan_fired:
                    self._nan_fired = True
                    log.warning("injecting NaN batch at step %d", step)
                    images = np.full_like(np.asarray(images, np.float32),
                                          np.nan)
                yield images, labels

        return wrapped()

    def maybe_sigterm(self, step: int) -> None:
        """SIGTERM this process at the first chunk boundary >= the planned
        step (the loop calls this where a real preemption would land)."""
        if (self.plan.sigterm_at_step >= 0 and not self._sigterm_fired
                and step >= self.plan.sigterm_at_step):
            self._sigterm_fired = True
            import signal

            log.warning("injecting SIGTERM at step %d", step)
            os.kill(os.getpid(), signal.SIGTERM)
        self._maybe_burst_sigterm(step)

    # ------------------------------------------------- preemption burst
    @property
    def burst_fired(self) -> int:
        """SIGTERMs the burst has delivered so far, across restarts (the
        ``fault_preempt_burst`` gauge value)."""
        if self.plan.preempt_burst <= 0 or not self.train_dir:
            return 0
        try:
            with open(os.path.join(self.train_dir, BURST_STATE_FILE)) as f:
                return int(json.load(f).get("fired", 0))
        except (OSError, ValueError):
            return 0

    def _maybe_burst_sigterm(self, step: int) -> None:
        """K SIGTERMs spaced S steps apart ACROSS the supervise restart
        loop: each supervised child preempts itself S steps after its
        first chunk boundary until K rounds have fired in total — the
        deterministic drill for the supervisor's downsize policy. The
        fired-count lives in ``<train_dir>/fault_burst_state.json``
        because each firing kills the process that would have
        remembered it; only the PRIMARY process advances the counter
        (the same writer discipline as every shared-train_dir artifact),
        while every process still SIGTERMs itself off the shared count —
        one counted round per supervised restart, any process count."""
        if self.plan.preempt_burst <= 0 or self._sigterm_fired \
                or self._burst_spent or not self.train_dir:
            return
        if self._burst_start_step is None:
            self._burst_start_step = step
        if step < self._burst_start_step + self.plan.preempt_burst_every:
            return
        fired = self.burst_fired
        if fired >= self.plan.preempt_burst:
            self._burst_spent = True  # never re-read the file per boundary
            return
        self._sigterm_fired = True  # at most one per child, either path
        try:
            from tpu_resnet import parallel

            primary = parallel.is_primary()
        except Exception:  # noqa: BLE001 - jax-free drill harnesses
            primary = True
        if primary:
            path = os.path.join(self.train_dir, BURST_STATE_FILE)
            try:
                os.makedirs(self.train_dir, exist_ok=True)
                tmp = path + f".tmp{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump({"fired": fired + 1,
                               "of": self.plan.preempt_burst}, f)
                os.replace(tmp, path)
            except OSError as e:
                log.warning("preempt burst: could not persist state (%s) "
                            "— not firing (an unbounded burst would "
                            "never converge)", e)
                return
        import signal

        log.warning("injecting preemption burst SIGTERM %d/%d at step %d",
                    fired + 1, self.plan.preempt_burst, step)
        os.kill(os.getpid(), signal.SIGTERM)

    # ---------------------------------------------------- serve faults
    def wrap_serve_infer(self, infer_fn):
        """Wrap the predict server's inference callable with the planned
        serve-side faults, counted in predict *requests* (the server
        ticks :meth:`note_serve_request` per admitted request; the wrap
        itself only adds the slow/hang behavior at dispatch time so the
        batcher thread is the thread that hangs — the accept-then-hang
        shape the router must ride). Returns ``infer_fn`` untouched when
        no serve fault is planned: zero overhead, identical callable."""
        if not self.plan.serves_faults:
            return infer_fn

        def wrapped(images):
            if (self.plan.serve_hang_at_request >= 0
                    and self._serve_requests
                    >= self.plan.serve_hang_at_request):
                if not self._serve_hung:
                    self._serve_hung = True
                    log.warning("injecting serve hang at request %d "
                                "(batcher thread sleeps; requests keep "
                                "being accepted and time out)",
                                self._serve_requests)
                while True:          # hung for good: the drill target is
                    time.sleep(60)   # probe-driven eviction, not recovery
            if self.plan.serve_slow_ms > 0:
                time.sleep(self.plan.serve_slow_ms / 1e3)
            return infer_fn(images)

        return wrapped

    def note_serve_request(self) -> None:
        """Count one admitted predict request; fires the hard-kill fault
        (SIGKILL — no drain, no exit handler: the replica death the
        failover drill rides) when the plan says this is request K."""
        self._serve_requests += 1
        if (self.plan.serve_kill_at_request >= 0
                and self._serve_requests
                >= self.plan.serve_kill_at_request):
            import signal

            log.warning("injecting serve SIGKILL at request %d",
                        self._serve_requests)
            os.kill(os.getpid(), signal.SIGKILL)

    def should_drop_connection(self) -> bool:
        """One-shot router↔replica connection drop: True exactly once,
        for the first incoming predict request >= the planned request K.
        The HTTP handler (serve/server.py do_POST) calls this BEFORE the
        request is admitted (``note_serve_request`` never ticks for the
        dropped one) and then closes the client socket with no response
        at all — the abrupt RemoteDisconnected the router's retry-once
        failover must absorb without a client-visible failure."""
        if (self.plan.serve_drop_at_request < 0 or self._serve_dropped
                or self._serve_requests + 1
                < self.plan.serve_drop_at_request):
            return False
        self._serve_dropped = True
        log.warning("injecting serve connection drop at request %d "
                    "(no HTTP response; the client sees an abrupt "
                    "disconnect)", self._serve_requests + 1)
        return True

    def maybe_oom(self, step: int) -> None:
        """Raise a synthetic RESOURCE_EXHAUSTED at the first chunk
        boundary >= the planned step — the exception class and status
        string a real XLA device OOM raises, so the loop's forensics
        path (``obs.memory.is_oom_error`` → oom_report.json) is drilled
        end-to-end. The real ``XlaRuntimeError`` is used when
        constructible; a RuntimeError carrying the same status is the
        fallback (both satisfy ``is_oom_error``)."""
        if (self.plan.oom_at_step < 0 or self._oom_fired
                or step < self.plan.oom_at_step):
            return
        self._oom_fired = True
        log.warning("injecting RESOURCE_EXHAUSTED at step %d", step)
        msg = (f"RESOURCE_EXHAUSTED: injected OOM drill at step {step} "
               f"(resilience.inject_oom_at_step) — out of memory while "
               f"trying to allocate 18446744073709551615 bytes")
        try:
            from jax._src.lib import xla_client

            err = xla_client.XlaRuntimeError(msg)
        except Exception:  # noqa: BLE001 - private-API drift
            err = RuntimeError(msg)
        raise err

    def maybe_corrupt_checkpoint(self, train_dir: str) -> None:
        """Corrupt the newest checkpoint before the startup restore (the
        drill for the restore-fallback path)."""
        if self.plan.corrupt_ckpt_at_start and not self._corrupt_fired:
            self._corrupt_fired = True
            step = corrupt_checkpoint(train_dir)
            log.warning("injected corruption into checkpoint step %s under "
                        "%s", step, train_dir)


def corrupt_checkpoint(directory: str, step=None):
    """Overwrite every regular file of one checkpoint step with garbage
    (default: the newest step). Returns the corrupted step, or None when
    the directory holds no step-numbered checkpoints. Used by the drills;
    the restore fallback must then skip this step."""
    directory = os.path.abspath(directory)
    steps = sorted(int(name) for name in os.listdir(directory)
                   if name.isdigit()) if os.path.isdir(directory) else []
    if not steps:
        return None
    step = max(steps) if step is None else int(step)
    step_dir = os.path.join(directory, str(step))
    for root, _, files in os.walk(step_dir):
        for name in files:
            path = os.path.join(root, name)
            try:
                size = max(os.path.getsize(path), 16)
                with open(path, "wb") as f:
                    f.write(b"\xde\xad\xbe\xef" * ((size + 3) // 4))
            except OSError:
                pass
    return step
