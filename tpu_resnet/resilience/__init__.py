"""Fault tolerance — the recovery paths the reference never had.

The reference's only recovery story is MonitoredTrainingSession's implicit
resume-from-latest-checkpoint (reference resnet_imagenet_train.py:267-270);
preemption, divergence, data stalls and corrupt checkpoints all turn into
raw stack traces or silent hangs. On preemptible TPU pods those are the
*dominant* failure modes (arXiv:1909.09756 runs MLPerf on pods where any
host can vanish mid-step; arXiv:1605.08695 §4.3 names the checkpoint-
restore contract as the system's core fault-tolerance mechanism). This
package makes each one a handled path:

``shutdown``     ShutdownCoordinator — SIGTERM/SIGINT request a stop at the
                 next chunk boundary; the loop saves a final checkpoint,
                 closes telemetry, and ``train()`` raises ``Preempted`` so
                 the CLI can exit with a distinct code
                 (``PREEMPT_EXIT_CODE``) that a supervisor
                 (tools/supervise.py) auto-resumes on.
``sentinel``     NaNSentinel — loss finiteness checked at the existing log
                 boundaries (already host-synced there: zero extra device
                 syncs); on trigger the loop rolls back to the last
                 checkpoint, advances the data stream past the bad window,
                 and retries a bounded number of times before raising
                 ``DivergenceError``.
``watchdog``     HangWatchdog — a daemon thread that dumps all-thread
                 stacks and flips ``/healthz`` unhealthy when step progress
                 stalls past a configurable deadline, and clears the flag
                 when progress resumes.
``faultinject``  FaultPlan/FaultInjector — deterministic, config/env-driven
                 fault injection (NaN batch at step N, data stall of S
                 seconds, SIGTERM at step N — single or as a supervised
                 preemption burst, checkpoint corruption), off by
                 default, used by the drill tests and ``doctor
                 --fault-drill`` to prove every recovery path end-to-end.
``elastic``      topology as a runtime variable — on restart, derive the
                 mesh from the devices that actually exist (8→4→2 chips,
                 replicated↔zero1, any direction), restore through the
                 partitioner template (explicit cross-topology reshard),
                 record every reshape as a ``topology_change`` span, and
                 arbitrate train+serve colocation with the live HBM
                 gauges (``doctor --reshape-drill`` proves the chain).

Checkpoint-level fallback (restore falls back through ``all_steps()`` to
the newest restorable checkpoint) lives in ``train/checkpoint.py``; the
input-pipeline liveness fixes live in ``data/pipeline.py``.
"""

from tpu_resnet.resilience import elastic
from tpu_resnet.resilience.faultinject import (
    FaultInjector,
    FaultPlan,
    corrupt_checkpoint,
)
from tpu_resnet.resilience.sentinel import DivergenceError, NaNSentinel
from tpu_resnet.resilience.shutdown import (
    PREEMPT_EXIT_CODE,
    Preempted,
    ShutdownCoordinator,
)
from tpu_resnet.resilience.watchdog import HangWatchdog

__all__ = [
    "PREEMPT_EXIT_CODE",
    "DivergenceError",
    "FaultInjector",
    "FaultPlan",
    "HangWatchdog",
    "NaNSentinel",
    "Preempted",
    "ShutdownCoordinator",
    "corrupt_checkpoint",
    "elastic",
]
