"""Fault tolerance — the recovery paths the reference never had.

The reference's only recovery story is MonitoredTrainingSession's implicit
resume-from-latest-checkpoint (reference resnet_imagenet_train.py:267-270);
preemption, divergence, data stalls and corrupt checkpoints all turn into
raw stack traces or silent hangs. On preemptible TPU pods those are the
*dominant* failure modes (arXiv:1909.09756 runs MLPerf on pods where any
host can vanish mid-step; arXiv:1605.08695 §4.3 names the checkpoint-
restore contract as the system's core fault-tolerance mechanism). This
package makes each one a handled path:

``shutdown``     ShutdownCoordinator — SIGTERM/SIGINT request a stop at the
                 next chunk boundary; the loop saves a final checkpoint,
                 closes telemetry, and ``train()`` raises ``Preempted`` so
                 the CLI can exit with a distinct code
                 (``PREEMPT_EXIT_CODE``) that a supervisor
                 (tools/supervise.py) auto-resumes on.
``sentinel``     NaNSentinel — loss finiteness checked at the existing log
                 boundaries (already host-synced there: zero extra device
                 syncs); on trigger the loop rolls back to the last
                 checkpoint, advances the data stream past the bad window,
                 and retries a bounded number of times before raising
                 ``DivergenceError``.
``watchdog``     HangWatchdog — a daemon thread that dumps all-thread
                 stacks and flips ``/healthz`` unhealthy when step progress
                 stalls past a configurable deadline, and clears the flag
                 when progress resumes.
``faultinject``  FaultPlan/FaultInjector — deterministic, config/env-driven
                 fault injection (NaN batch at step N, data stall of S
                 seconds, SIGTERM at step N — single or as a supervised
                 preemption burst, checkpoint corruption), off by
                 default, used by the drill tests and ``doctor
                 --fault-drill`` to prove every recovery path end-to-end.
``elastic``      topology as a runtime variable — on restart, derive the
                 mesh from the devices that actually exist (8→4→2 chips,
                 replicated↔zero1, any direction), restore through the
                 partitioner template (explicit cross-topology reshard),
                 record every reshape as a ``topology_change`` span, and
                 arbitrate train+serve colocation with the live HBM
                 gauges (``doctor --reshape-drill`` proves the chain).

Checkpoint-level fallback (restore falls back through ``all_steps()`` to
the newest restorable checkpoint) lives in ``train/checkpoint.py``; the
input-pipeline liveness fixes live in ``data/pipeline.py``.
"""

# Lazy re-exports (PEP 562): ``elastic`` pulls jax at import time, and
# the jax-free consumers of this package's contracts — the scenario
# conductor, tools/supervise.py, the router's exit-code imports — must
# be able to ``import tpu_resnet.resilience.exitcodes`` on a host whose
# accelerator stack is the thing being drilled without paying (or
# crashing on) the accelerator import. Attribute access keeps the
# eager-import API: ``from tpu_resnet.resilience import Preempted``
# still works everywhere it did.
_EXPORTS = {
    "PREEMPT_EXIT_CODE": ("tpu_resnet.resilience.shutdown",
                          "PREEMPT_EXIT_CODE"),
    "Preempted": ("tpu_resnet.resilience.shutdown", "Preempted"),
    "ShutdownCoordinator": ("tpu_resnet.resilience.shutdown",
                            "ShutdownCoordinator"),
    "DivergenceError": ("tpu_resnet.resilience.sentinel",
                        "DivergenceError"),
    "NaNSentinel": ("tpu_resnet.resilience.sentinel", "NaNSentinel"),
    "FaultInjector": ("tpu_resnet.resilience.faultinject",
                      "FaultInjector"),
    "FaultPlan": ("tpu_resnet.resilience.faultinject", "FaultPlan"),
    "corrupt_checkpoint": ("tpu_resnet.resilience.faultinject",
                           "corrupt_checkpoint"),
    "HangWatchdog": ("tpu_resnet.resilience.watchdog", "HangWatchdog"),
    "elastic": ("tpu_resnet.resilience.elastic", None),
    "exitcodes": ("tpu_resnet.resilience.exitcodes", None),
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    import importlib

    try:
        module_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    module = importlib.import_module(module_name)
    value = module if attr is None else getattr(module, attr)
    globals()[name] = value  # cache: __getattr__ fires once per name
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
