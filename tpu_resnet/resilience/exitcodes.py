"""One registry for the repo's process exit-code contracts.

Every layer of the resilience story keys decisions on exit codes — the
trainer exits distinctly on preemption, the serve replica on a denied
colocation admission, the supervisor restarts or stops by code, doctor
and the scenario conductor assert on all of them. Until this module each
caller re-hardcoded the numbers with "keep in sync" comments; now the
numbers live here once and everyone imports them.

Stdlib-only and jax-free: the supervisor, the router and the scenario
conductor import this on hosts whose accelerator stack is the thing
being drilled (tpu_resnet/resilience/__init__ lazy-loads its jax-aware
submodules precisely so this import stays cheap).

The codes, and why each is distinct from every shell/Python convention
in use (0 ok, 1 crash, 2 usage, 124 timeout(1), 126/127 spawn,
128+N killed-by-signal):

``PREEMPTED`` (42)
    Graceful preemption: SIGTERM honored, final checkpoint on disk —
    a supervisor resumes instead of backing off (resilience/shutdown.py,
    tools/supervise.py).
``NO_CAPACITY`` (3)
    Serve colocation admission denied: this host has no HBM headroom —
    the placement layer should try another host, never restart here
    (serve/server.py, supervise --stop-codes).
``DONE`` / ``DRAINED`` (0)
    A trainer's 0 means finished; a serve replica's 0 means it honored
    a drain (rolling upgrade) — supervise --restart-clean-exits gives
    the fleet reading.
``USAGE_ERROR`` (2)
    CLI contract errors (argparse convention): bad flags, and the
    scenario validator's named schema errors.
``HOSTENV_TIMEOUT`` (124) / ``HOSTENV_SPAWN_FAILED`` (127)
    hostenv.run_scrubbed_subprocess's timeout(1)-compatible reporting.
"""

from __future__ import annotations

PREEMPTED = 42
NO_CAPACITY = 3
DONE = 0
DRAINED = 0
USAGE_ERROR = 2
HOSTENV_TIMEOUT = 124
HOSTENV_SPAWN_FAILED = 127
