"""tpu_resnet — a TPU-native deep-learning training framework.

A brand-new JAX/XLA/pjit framework with the capabilities of the reference
``michaelwfc/distributed-tensorflow-resnet`` repo (TF1 parameter-server +
Horovod ResNet trainer), designed TPU-first:

- One SPMD program over a ``jax.sharding.Mesh`` replaces the reference's
  entire ps/worker/gRPC + Horovod/MPI/NCCL machinery
  (reference: resnet_model.py:102-117, resnet_cifar_train.py:371-403).
- A typed config (``tpu_resnet.config``) replaces ~60 tf.app.flags
  re-declared per entry script (reference: resnet_cifar_main.py:32-97).
- Pure-function LR schedules of the step replace feed-dict mutating hooks
  (reference: resnet_cifar_train.py:291-311).
- Orbax checkpoints + a checkpoint-polling evaluator replace
  MonitoredTrainingSession saving + the eval sidecar
  (reference: resnet_cifar_eval.py:85-143).

Subpackages
-----------
``config``      typed run configuration + CLI
``data``        CIFAR binary / ImageNet TFRecord input pipelines (host side)
``models``      Flax ResNet-v2 (CIFAR 6n+2 and ImageNet 18-200) + MLP
``ops``         Pallas TPU kernels for hot ops
``parallel``    mesh construction, sharding, collectives, multi-host init
``train``       train state, optimizer, schedules, jitted step, loop, hooks
``evaluation``  eval-once and checkpoint-polling continuous evaluator
``obs``         step-time breakdown, event spans, run manifest, and the
                per-host /metrics + /healthz telemetry server
``export``      serialized inference export (freeze_graph equivalent)
``tools``       checkpoint inspector, predict, FLOP/param analysis
"""

__version__ = "0.1.0"
