"""Process-environment helpers that must not import jax.

Used by the driver entry (`__graft_entry__`), `bench.py`'s no-jax parent
orchestrator, and `tpu_resnet doctor` — all of which spawn clean
subprocesses while the ambient process may have a wedged TPU plugin.
"""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def scrubbed_cpu_env(n_devices: int) -> dict:
    """A copy of the environment with the CPU platform forced and every
    TPU/backend-selection knob stripped, so a child process can only ever
    initialize the virtual-device CPU backend.

    This includes dropping any sitecustomize-style PJRT plugin hooks from
    PYTHONPATH: a TPU plugin that registers itself at interpreter startup
    can hang a process that never asked for TPU devices (observed: with
    ``JAX_PLATFORMS=cpu`` set at startup the ambient plugin hook still
    blocks on its transport; without the hook on PYTHONPATH, CPU-only
    startup takes ~2 s)."""
    env = dict(os.environ)
    for key in list(env):
        if key.startswith(("TPU_", "LIBTPU", "PJRT_", "CLOUD_TPU",
                           "AXON_", "PALLAS_AXON_")):
            del env[key]
    pypath = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
              if p and os.path.basename(p.rstrip("/")) != ".axon_site"]
    env["PYTHONPATH"] = os.pathsep.join([_REPO_ROOT] + pypath)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    return env


def run_scrubbed_subprocess(argv, n_devices: int, timeout: int):
    """Run ``argv`` under ``scrubbed_cpu_env(n_devices)`` with merged
    stdout/stderr and a timeout that yields (124, partial_output) instead
    of raising — the one subprocess wrapper shared by the driver entry,
    the doctor's CPU-mesh check, and the pod-scaling proof (they had
    drifted: only one handled TimeoutExpired). Returns (rc, output)."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(argv, env=scrubbed_cpu_env(n_devices),
                              cwd=_REPO_ROOT, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              timeout=timeout)
        return proc.returncode, proc.stdout
    except subprocess.TimeoutExpired as e:
        out = e.stdout or b""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        return 124, out + f"\n[parent] timeout after {timeout}s"
    except Exception as e:  # spawn failure (missing interpreter etc.)
        print(f"[hostenv] subprocess spawn failed: {e}", file=sys.stderr)
        return 127, f"spawn failed: {e}"
