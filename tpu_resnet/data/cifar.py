"""CIFAR-10/100 binary-format readers + synthetic data.

Formats (reference cifar_input.py:39-53):
- cifar10: records of 1 label byte + 3072 image bytes (depth-major
  3×32×32), files ``cifar-10-batches-bin/data_batch_{1..5}.bin`` and
  ``test_batch.bin`` (reference resnet_cifar_train.py:141-155).
- cifar100: records of 1 coarse + 1 fine label byte + 3072 image bytes —
  the reference reads the *fine* label via ``label_offset=1``
  (cifar_input.py:44-47); files ``cifar-100-binary/train.bin``, ``test.bin``.

The whole dataset (~180 MB) is loaded into host RAM once as uint8 NHWC — no
per-record reader processes; the per-step path never touches disk. A native
C++ reader (tpu_resnet/native) accelerates the one-time decode when built;
the numpy path below is the always-available fallback.
"""

from __future__ import annotations

import os
from typing import List, Tuple

import numpy as np

_IMAGE_BYTES = 32 * 32 * 3


def _decode_records(raw: np.ndarray, label_offset: int) -> Tuple[np.ndarray, np.ndarray]:
    """raw uint8 [N, record_bytes] → (images NHWC uint8, labels int32)."""
    labels = raw[:, label_offset].astype(np.int32)
    images = raw[:, label_offset + 1:label_offset + 1 + _IMAGE_BYTES]
    # depth-major [C,H,W] → NHWC (reference cifar_input.py:64-68)
    images = images.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return np.ascontiguousarray(images), labels


def _read_files(files: List[str], record_bytes: int) -> np.ndarray:
    parts = []
    for f in files:
        buf = np.fromfile(f, dtype=np.uint8)
        if buf.size % record_bytes:
            raise ValueError(f"{f}: size {buf.size} not a multiple of "
                             f"record_bytes {record_bytes}")
        parts.append(buf.reshape(-1, record_bytes))
    return np.concatenate(parts)


def cifar_files(dataset: str, data_dir: str, train: bool) -> List[str]:
    if dataset == "cifar10":
        d = os.path.join(data_dir, "cifar-10-batches-bin")
        if not os.path.isdir(d):
            d = data_dir
        names = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
                 else ["test_batch.bin"])
    elif dataset == "cifar100":
        d = os.path.join(data_dir, "cifar-100-binary")
        if not os.path.isdir(d):
            d = data_dir
        names = ["train.bin"] if train else ["test.bin"]
    else:
        raise ValueError(f"not a cifar dataset: {dataset}")
    files = [os.path.join(d, n) for n in names]
    missing = [f for f in files if not os.path.exists(f)]
    if missing:
        raise FileNotFoundError(f"missing CIFAR files: {missing}")
    return files


def load_cifar(dataset: str, data_dir: str, train: bool,
               use_native: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    label_offset = 1 if dataset == "cifar100" else 0
    record_bytes = 1 + label_offset + _IMAGE_BYTES
    files = cifar_files(dataset, data_dir, train)
    raw = None
    if use_native:
        try:
            from tpu_resnet.native import loader as native_loader
            raw = native_loader.read_fixed_length_records(files, record_bytes)
        except ImportError:
            raw = None
    if raw is None:
        raw = _read_files(files, record_bytes)
    return _decode_records(raw, label_offset)


def synthetic_data(num_examples: int, image_size: int = 32,
                   num_classes: int = 10, seed: int = 0,
                   learnable: bool = False, task: str = "bands",
                   label_noise: float = 0.0
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic random images for smoke tests and benchmarks (the
    role of the reference's batch_size=10 localhost configs,
    mkl-scripts/run_dist_tf_local.sh:14-21).

    ``learnable=True`` derives labels from image content instead of random
    noise, so a working training loop must drive precision well above
    chance — the test-scale analog of the reference's convergence-curve
    verification (SURVEY.md §4.4). Two tasks:

    - ``bands`` (easy): label = which horizontal band is brightened; a
      linear probe can recover it. Saturates in under an epoch — good for
      smoke gates, useless for schedule/regularization evidence.
    - ``freq100`` (hard): label = (vertical, horizontal) spatial-frequency
      pair of a low-contrast sinusoid with random per-image phase,
      superposed on noise. Random phase makes position memorization
      useless; crop shifts phase and flip reverses it without changing
      frequency, so the features that work are exactly the
      augmentation-invariant ones. Up to 100 classes. With
      ``label_noise`` > 0 (train split only) a fraction of labels is
      resampled — the high-LR phase fits the signal, the decayed tail
      decides the achievable precision, which is what makes a compressed
      piecewise schedule visibly matter (VERDICT r2 item 6).
    """
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, (num_examples, image_size, image_size, 3),
                          dtype=np.uint8)
    labels = rng.integers(0, num_classes, (num_examples,), dtype=np.int32)
    if learnable and task == "bands":
        if num_classes > image_size:
            raise ValueError(f"bands task needs num_classes "
                             f"({num_classes}) <= image_size "
                             f"({image_size}) for distinct bands")
        band = max(1, image_size // num_classes)
        for i, lab in enumerate(labels):
            y0 = int(lab) * band
            sl = images[i, y0:y0 + band]
            images[i, y0:y0 + band] = np.minimum(
                sl.astype(np.int32) + 120, 255).astype(np.uint8)
    elif learnable and task == "freq100":
        if num_classes > 100:
            raise ValueError(f"freq100 task supports <= 100 classes, "
                             f"got {num_classes}")
        # Nyquist guard: the largest frequency used must stay below
        # image_size/2 cycles or it aliases onto a lower class's signal.
        max_f = max(((num_classes - 1) // 10) + 1,
                    min(num_classes, 10))
        if image_size < 2 * max_f + 1:
            raise ValueError(
                f"freq100 with {num_classes} classes uses frequencies up "
                f"to {max_f} cycles; image_size {image_size} aliases them "
                f"(needs >= {2 * max_f + 1})")
        amp = 30.0  # well under the noise std (~74): forces averaging
        grid = np.arange(image_size, dtype=np.float64)
        for i, lab in enumerate(labels):
            fy, fx = divmod(int(lab), 10)
            py, px = rng.uniform(0, 2 * np.pi, 2)
            wave = (np.sin(2 * np.pi * (fy + 1) * grid / image_size + py)
                    [:, None]
                    + np.sin(2 * np.pi * (fx + 1) * grid / image_size + px)
                    [None, :])
            images[i] = np.clip(images[i].astype(np.float64)
                                + amp * wave[..., None], 0, 255
                                ).astype(np.uint8)
        if label_noise > 0:
            n_noise = int(round(label_noise * num_examples))
            idx = rng.choice(num_examples, n_noise, replace=False)
            labels[idx] = rng.integers(0, num_classes, n_noise,
                                       dtype=np.int32)
    elif learnable:
        raise ValueError(f"unknown synthetic task {task!r}")
    return images, labels


def load_split(cfg, train: bool) -> Tuple[np.ndarray, np.ndarray]:
    """Dispatch on DataConfig (in-memory datasets; ImageNet streams through
    tpu_resnet.data.imagenet instead)."""
    if cfg.dataset in ("cifar10", "cifar100"):
        return load_cifar(cfg.dataset, cfg.data_dir, train,
                          use_native=cfg.use_native_loader)
    if cfg.dataset == "synthetic":
        n = cfg.train_examples if train else cfg.eval_examples
        return synthetic_data(n, cfg.resolved_image_size, cfg.num_classes,
                              seed=0 if train else 1,
                              learnable=cfg.synthetic_learnable,
                              task=cfg.synthetic_task,
                              label_noise=(cfg.synthetic_label_noise
                                           if train else 0.0))
    raise ValueError(f"load_split does not handle {cfg.dataset!r}")
