"""Preallocated ring of batch slots for the host data engine.

The GIL-bound thread pool in the old ``ImageNetIterator.__iter__`` paid
two taxes per batch: a full ``images.copy()`` on the producer side and —
for any future *process* worker — a pickle of the whole decoded batch
through a ``multiprocessing.Queue``. The ring removes both: workers decode
**directly into** preallocated slots, and only tiny ``(seq, slot, count)``
tuples cross the queue.

Two backings with one interface:

``ShmRing``    one ``multiprocessing.shared_memory`` segment sliced into
               ``slots`` batch slots (images uint8 [B,H,W,3] + labels
               int32 [B]). The **parent creates and unlinks**; workers
               attach by name. Crash hygiene: every created segment is
               registered in a module-level set and unlinked from an
               ``atexit`` hook, so an exception path that misses
               ``close()`` still leaves ``/dev/shm`` clean.
``ArrayRing``  the same slot math over ordinary numpy arrays — the
               thread-mode backing (no shared memory needed inside one
               process), also the CPU-cheap choice for tests.

Aliasing contract (shared with the engine): ``images(slot)``/
``labels(slot)`` return **views**. A slot's views stay valid until the
slot is recycled by the engine's hold window; consumers that need a batch
beyond that window must copy.
"""

from __future__ import annotations

import atexit
import os
import secrets
import threading
from multiprocessing import shared_memory
from typing import List, Tuple

import numpy as np

SHM_PREFIX = "tpures_ring_"

# Segments created by THIS process, unlinked on interpreter exit as a
# crash backstop (the engine's close() is the normal path and removes the
# entry here).  Guarded by a lock: train loop closers and atexit can race.
_created: set = set()
# Safe module-level lock: spawn re-runs this import, so every worker gets
# its own fresh lock — nothing is shared or captured across the fork
# boundary; it only serializes THIS process's closers against atexit.
_created_lock = threading.Lock()  # check: disable=fork-safety


def _atexit_unlink():
    with _created_lock:
        names = list(_created)
        _created.clear()
    for name in names:
        try:
            seg = shared_memory.SharedMemory(name=name)
            seg.close()
            seg.unlink()
        except Exception:
            pass


atexit.register(_atexit_unlink)


def _slot_nbytes(local_batch: int, image_size: int) -> int:
    return local_batch * image_size * image_size * 3 + 4 * local_batch


class ShmRing:
    """``slots`` batch slots in one named shared-memory segment."""

    def __init__(self, slots: int, local_batch: int, image_size: int,
                 name: str = None, create: bool = True):
        self.slots = int(slots)
        self.local_batch = int(local_batch)
        self.image_size = int(image_size)
        self._slot_bytes = _slot_nbytes(local_batch, image_size)
        nbytes = self.slots * self._slot_bytes
        if create:
            name = name or SHM_PREFIX + f"{os.getpid()}_{secrets.token_hex(4)}"
            self._shm = shared_memory.SharedMemory(name=name, create=True,
                                                   size=nbytes)
            with _created_lock:
                _created.add(name)
        else:
            self._shm = _attach_untracked(name)
        self.name = self._shm.name
        self._owner = create
        self._img_shape = (local_batch, image_size, image_size, 3)
        self._views_built = False
        self._images: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []
        self._build_views()

    def _build_views(self):
        buf = self._shm.buf
        img_bytes = self.local_batch * self.image_size * self.image_size * 3
        for s in range(self.slots):
            base = s * self._slot_bytes
            self._images.append(np.ndarray(
                self._img_shape, dtype=np.uint8, buffer=buf,
                offset=base))
            self._labels.append(np.ndarray(
                (self.local_batch,), dtype=np.int32, buffer=buf,
                offset=base + img_bytes))
        self._views_built = True

    def images(self, slot: int) -> np.ndarray:
        return self._images[slot]

    def labels(self, slot: int) -> np.ndarray:
        return self._labels[slot]

    def close(self):
        """Worker-side release of the mapping (no unlink)."""
        self._drop_views()
        try:
            self._shm.close()
        except BufferError:  # a consumer still holds a view — the mapping
            pass             # is reclaimed when the last view is GC'd

    def unlink(self):
        """Parent-side teardown: remove the name from /dev/shm. Safe to
        call twice; the mapping itself is released when the last view
        drops (``close`` above tolerates live exports)."""
        with _created_lock:
            _created.discard(self.name)
        self.close()
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass

    def _drop_views(self):
        self._images = []
        self._labels = []
        self._views_built = False


def _attach_untracked(name: str) -> shared_memory.SharedMemory:
    """Attach to an existing segment WITHOUT registering it with this
    process's resource tracker.

    Python 3.10's tracker (bpo-38119) unlinks every shared-memory segment
    a process ever attached to when that process exits — a worker that
    finished its shard would tear the ring down under the parent. The
    parent is the sole owner here; workers must attach untracked. (3.13+
    exposes ``track=False`` for exactly this; this is the documented
    workaround for older runtimes.)"""
    from multiprocessing import resource_tracker

    orig = resource_tracker.register
    try:
        resource_tracker.register = lambda *a, **kw: None
        return shared_memory.SharedMemory(name=name)
    finally:
        resource_tracker.register = orig


class ArrayRing:
    """ShmRing's interface over plain numpy buffers — thread-mode backing
    (one address space, nothing to share or unlink)."""

    def __init__(self, slots: int, local_batch: int, image_size: int):
        self.slots = int(slots)
        self.local_batch = int(local_batch)
        self.image_size = int(image_size)
        self.name = None
        self._images = [np.empty((local_batch, image_size, image_size, 3),
                                 np.uint8) for _ in range(slots)]
        self._labels = [np.empty((local_batch,), np.int32)
                        for _ in range(slots)]

    def images(self, slot: int) -> np.ndarray:
        return self._images[slot]

    def labels(self, slot: int) -> np.ndarray:
        return self._labels[slot]

    def close(self):
        pass

    def unlink(self):
        pass


def leaked_segments(pid: int = None) -> Tuple[str, ...]:
    """Names of ring segments currently present in /dev/shm — the
    cleanliness assertion the shm-hygiene tests and drills use.

    Defaults to segments created by THIS process (the creator pid is
    embedded in the name): /dev/shm is a host-global namespace, so an
    unfiltered scan would report another process's legitimately-live ring
    (e.g. two test suites running concurrently) as a "leak". Pass
    ``pid=0`` for the unfiltered host-wide view."""
    if pid is None:
        pid = os.getpid()
    prefix = SHM_PREFIX if pid == 0 else f"{SHM_PREFIX}{pid}_"
    try:
        return tuple(n for n in os.listdir("/dev/shm")
                     if n.startswith(prefix))
    except OSError:  # platform without /dev/shm: nothing to report
        return ()
