"""ImageNet input pipeline: TFRecord shards → decoded, cropped uint8 batches.

Reference behavior being matched (file:line):
- Shard naming: ``train-{00000..01023}-of-01024`` /
  ``validation-{00000..00127}-of-00128`` under ``data_dir``
  (resnet_imagenet_train.py:105-114).
- Example keys: ``image/encoded`` (JPEG bytes), ``image/class/label``
  (int64, 1-based → the dense layer has 1000(+1 background) classes; the
  reference keeps labels as-is and uses 1000 one-hot with label-1? No — it
  one-hots the raw label into 1000 classes after subtracting nothing;
  Inception shards store 1..1000, the reference's ``tf.one_hot(label,
  1000)`` silently maps 1000→all-zeros. We subtract 1 explicitly and
  document the deviation — it fixes a real off-by-one in the reference
  (resnet_imagenet_train.py:136-158).)
- VGG preprocessing, host half (vgg_preprocessing.py): train =
  aspect-preserving resize to a uniformly random smaller side in
  [resize_min, resize_max] (:306-309) then random 224×224 crop (:284-314);
  eval = resize to side 256 then central crop (:317-333). The flip and
  mean-subtraction run on-device (tpu_resnet.data.augment).
- Parallel decode: ``num_parallel_calls`` map threads
  (resnet_imagenet_train.py:170-171) → a thread pool here (PIL releases
  the GIL for JPEG decode).

Unlike the reference — where every worker reads all 1024 shards and
"shards" by independent shuffling (SURVEY.md §2.3) — shard files are
striped across processes, and the per-epoch file order is a pure function
of (seed, epoch).
"""

from __future__ import annotations

import glob
import io
import os
import queue
import threading
from typing import Iterator, List, Tuple

import numpy as np

from tpu_resnet.data import tfrecord

try:
    from PIL import Image
except ImportError:  # pragma: no cover - PIL is baked into the image
    Image = None

IMAGE_SIZE = 224
EVAL_RESIZE = 256


def read_shard_records(path: str, use_native: bool = True,
                       verify_crc: bool = False) -> Iterator[bytes]:
    """Record payloads of one shard — native C++ splitter when built
    (tpu_resnet/native), pure-python framing otherwise.

    ``verify_crc`` checks the masked CRC32C of every record. With the
    native plane this costs almost nothing (~700 MB/s measured vs
    ~3 MB/s for the pure-python CRC — the C++ data plane's headline win),
    so corrupted shards fail loudly instead of feeding garbage JPEGs."""
    if use_native:
        native_loader = None
        try:  # narrow: only the probe may fall through to python —
            # errors from the actual read (corrupt framing, CRC mismatch,
            # short read) must propagate, not trigger a silent re-read
            from tpu_resnet.native import available, loader
            if available():
                native_loader = loader
        except Exception:
            native_loader = None
        if native_loader is not None:
            return iter(native_loader.tfrecord_payloads(
                path, verify_crc=verify_crc))
    return tfrecord.read_records(path, verify_crc=verify_crc)


def shard_files(data_dir: str, train: bool) -> List[str]:
    pattern = os.path.join(data_dir, "train-*" if train else "validation-*")
    files = sorted(glob.glob(pattern))
    if not files:
        raise FileNotFoundError(f"no ImageNet shards match {pattern}")
    return files


def parse_record(serialized: bytes) -> Tuple[bytes, int]:
    ex = tfrecord.parse_example(serialized)
    jpeg = ex["image/encoded"][0]
    label = int(ex["image/class/label"][0])
    return jpeg, label


def _resize_keep_aspect(img: "Image.Image", smaller_side: int) -> "Image.Image":
    w, h = img.size
    scale = smaller_side / min(w, h)
    # round-half-up, matching the native path's lround — Python round()
    # half-rounds to even, which would give a 1px-different grid on
    # exact-.5 products
    return img.resize((max(1, int(w * scale + 0.5)),
                       max(1, int(h * scale + 0.5))), Image.BILINEAR)


def _native_decoder():
    """The C++ decode function when the JPEG-enabled library is built."""
    try:
        from tpu_resnet.native import jpeg_available, loader
        if jpeg_available():
            return loader.decode_jpeg_vgg
    except Exception:
        pass
    return None


_NATIVE_DECODE = None
_NATIVE_PROBED = False


def decode_and_crop(jpeg: bytes, train: bool, rng: np.random.Generator,
                    resize_min: int = 256, resize_max: int = 512,
                    eval_resize: int = EVAL_RESIZE,
                    out_size: int = IMAGE_SIZE,
                    use_native: bool = True) -> np.ndarray:
    """JPEG bytes → uint8 [out_size, out_size, 3] per VGG preprocessing
    (host half; see module docstring).

    Random draws (resize side, crop fractions) happen once up front, so
    the native C++ decoder (GIL-free libjpeg + bilinear, native/loader.cc)
    and the PIL fallback consume the same stream and are interchangeable
    per-image — unsupported images (CMYK, non-JPEG bytes) silently fall
    back to PIL."""
    global _NATIVE_DECODE, _NATIVE_PROBED
    if train:
        side = int(rng.integers(resize_min, resize_max + 1))
        fx, fy = float(rng.random()), float(rng.random())
    else:
        side = eval_resize
        fx = fy = -1.0  # floor-central crop in both decoders
    if use_native:
        if not _NATIVE_PROBED:
            _NATIVE_DECODE = _native_decoder()
            _NATIVE_PROBED = True
        if _NATIVE_DECODE is not None:
            out = _NATIVE_DECODE(jpeg, side, out_size, fx, fy)
            if out is not None:
                return out
    img = Image.open(io.BytesIO(jpeg))
    if img.mode != "RGB":
        img = img.convert("RGB")
    img = _resize_keep_aspect(img, side)
    w, h = img.size
    if fx < 0:  # eval: floor-central crop (vgg_preprocessing.py:171-193)
        x0, y0 = (w - out_size) // 2, (h - out_size) // 2
    else:  # train: fx/fy map uniformly onto the w-out+1 valid offsets
        x0 = min(int(fx * (w - out_size + 1)), w - out_size)
        y0 = min(int(fy * (h - out_size + 1)), h - out_size)
    img = img.crop((x0, y0, x0 + out_size, y0 + out_size))
    return np.asarray(img, np.uint8)


class ImageNetIterator:
    """Streaming train iterator: files striped per process, epoch-shuffled
    record buffer, thread-pool JPEG decode, fixed-size uint8 batches."""

    def __init__(self, data_dir: str, local_batch: int, *, train: bool = True,
                 seed: int = 0, num_workers: int = 4,
                 shuffle_buffer: int = 4096, resize_min: int = 256,
                 resize_max: int = 512, eval_resize: int = EVAL_RESIZE,
                 start_step: int = 0,
                 process_index: int = 0, process_count: int = 1,
                 image_size: int = IMAGE_SIZE, verify_records: bool = False,
                 use_native: bool = True):
        self.files = shard_files(data_dir, train)[process_index::process_count]
        if not self.files:
            raise ValueError("fewer shard files than processes")
        self.local_batch = local_batch
        self.train = train
        self.seed = seed
        self.num_workers = max(1, num_workers)
        self.shuffle_buffer = shuffle_buffer
        self.resize_min = resize_min
        self.resize_max = resize_max
        self.eval_resize = eval_resize
        self.image_size = image_size
        self.start_step = start_step
        self.verify_records = verify_records
        self.use_native = use_native
        self._findex: dict = {}
        self._read_f = None
        self._read_path = None

    def _records(self) -> Iterator[Tuple[bytes, int]]:
        epoch = 0
        while True:
            files = (self._epoch_files(epoch) if self.train
                     else list(self.files))
            for f in files:
                for rec in read_shard_records(
                        f, use_native=self.use_native,
                        verify_crc=self.verify_records):
                    yield rec
            if not self.train:
                return
            epoch += 1

    # -------------------------------------------------- resume fast-forward
    def _file_index(self, path: str):
        """Cached seek-only (offset, length) index of one shard."""
        if path not in self._findex:
            self._findex[path] = tfrecord.record_index(path)
        return self._findex[path]

    def _epoch_files(self, epoch: int) -> List[str]:
        """Per-epoch shard order — pure function of (seed, epoch), shared
        by ``_records`` and the resume fast-forward."""
        files = list(self.files)
        np.random.default_rng((self.seed, epoch)).shuffle(files)
        return files

    def _read_at(self, path: str, idx: int) -> bytes:
        """Random-access one record payload (sequential in practice: the
        position stream visits files in order, so this keeps one shard
        open and seeks forward within it). Honors ``verify_records`` so
        the resume path has the same corruption guarantee as bulk reads."""
        import struct

        if self._read_path != path:
            if self._read_f is not None:
                self._read_f.close()
            self._read_f = open(path, "rb")
            self._read_path = path
        off, length = self._file_index(path)[idx]
        self._read_f.seek(off)
        payload = self._read_f.read(length)
        if self.verify_records:
            (want,) = struct.unpack("<I", self._read_f.read(4))
            if tfrecord.masked_crc32c_fast(payload) != want:
                raise ValueError(f"{path}: record {idx} CRC mismatch")
        return payload

    def _shuffle_stream(self, records: Iterator[bytes],
                        rng: np.random.Generator,
                        buf: List[bytes]) -> Iterator[bytes]:
        """Reservoir-style shuffle buffer (the reference's
        ``shuffle(buffer_size=1024)``, resnet_imagenet_train.py:174-178),
        resumable: ``rng`` and ``buf`` carry the mid-stream state."""
        for rec in records:
            buf.append(rec)
            if len(buf) >= self.shuffle_buffer:
                idx = int(rng.integers(0, len(buf)))
                buf[idx], buf[-1] = buf[-1], buf[idx]
                yield buf.pop()
        while buf:
            idx = int(rng.integers(0, len(buf)))
            buf[idx], buf[-1] = buf[-1], buf[idx]
            yield buf.pop()

    def _shuffled_records(self) -> Iterator[bytes]:
        """Shuffled record stream; with ``start_step > 0`` it continues
        *exactly* where an uninterrupted run's stream would be after
        ``start_step`` batches (reference resume contract,
        resnet_imagenet_train.py:267-270 — which the reference itself does
        not honor for the input stream).

        Fast-forward replays the shuffle-buffer algorithm over cheap
        (file, record#) positions — identical RNG draws, no payload reads —
        reconstructing the buffer contents and RNG state at the resume
        point; only the ≤ ``shuffle_buffer`` records still in the buffer
        are then fetched via the seek-only shard index."""
        if not self.train:
            yield from self._records()
            return
        rng = np.random.default_rng((self.seed, 1))
        if self.start_step <= 0:
            yield from self._shuffle_stream(self._records(), rng, [])
            return
        skip = self.start_step * self.local_batch
        # Explicit (epoch, file#, record#) cursor through the position
        # stream, so the continuation below can resume with *bulk* shard
        # reads — only the <= shuffle_buffer records reconstructed into the
        # buffer (and the tail of the one partially-consumed shard) use
        # indexed random access.
        epoch, fi, ri = 0, 0, 0
        files = self._epoch_files(0)
        pos_buf: List[Tuple[str, int]] = []
        emitted = 0
        while emitted < skip:  # train stream is infinite → never drains
            while ri >= len(self._file_index(files[fi])):
                fi, ri = fi + 1, 0
                if fi >= len(files):
                    epoch, fi = epoch + 1, 0
                    files = self._epoch_files(epoch)
            pos_buf.append((files[fi], ri))
            ri += 1
            if len(pos_buf) >= self.shuffle_buffer:
                idx = int(rng.integers(0, len(pos_buf)))
                pos_buf[idx], pos_buf[-1] = pos_buf[-1], pos_buf[idx]
                pos_buf.pop()
                emitted += 1
        buf = [self._read_at(f, i) for f, i in pos_buf]

        def rest() -> Iterator[bytes]:
            e, f0, r0 = epoch, fi, ri
            while True:
                efiles = self._epoch_files(e) if e != epoch else files
                for k in range(f0, len(efiles)):
                    if r0:  # tail of the partially-consumed shard
                        index = self._file_index(efiles[k])
                        for i in range(r0, len(index)):
                            yield self._read_at(efiles[k], i)
                        r0 = 0
                    else:  # whole shards go through the bulk reader
                        yield from read_shard_records(
                            efiles[k], use_native=self.use_native,
                            verify_crc=self.verify_records)
                e, f0 = e + 1, 0

        yield from self._shuffle_stream(rest(), rng, buf)

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        if Image is None:
            raise RuntimeError("PIL is required for ImageNet decoding")
        rec_iter = self._shuffled_records()
        lock = threading.Lock()
        out_q: "queue.Queue" = queue.Queue(maxsize=4)
        stop = threading.Event()

        def worker(widx: int):
            rng = np.random.default_rng((self.seed, widx, self.start_step))
            images = np.empty((self.local_batch, self.image_size,
                               self.image_size, 3), np.uint8)
            labels = np.empty((self.local_batch,), np.int32)
            # Each worker builds whole batches to avoid cross-thread
            # assembly; batch order across workers is nondeterministic but
            # contents are seed-stable per worker.
            while not stop.is_set():
                count = 0
                while count < self.local_batch:
                    with lock:
                        try:
                            rec = next(rec_iter)
                        except StopIteration:
                            rec = None
                    if rec is None:
                        break
                    jpeg, label = parse_record(rec)
                    images[count] = decode_and_crop(
                        jpeg, self.train, rng,
                        self.resize_min, self.resize_max,
                        eval_resize=self.eval_resize,
                        out_size=self.image_size,
                        use_native=self.use_native)
                    labels[count] = label - 1  # 1-based shard labels → 0-based
                    count += 1
                if count == self.local_batch:
                    out_q.put((images.copy(), labels.copy()))
                else:
                    break
            out_q.put(None)

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(self.num_workers)]
        for t in threads:
            t.start()
        finished = 0
        try:
            while finished < len(threads):
                item = out_q.get()
                if item is None:
                    finished += 1
                    continue
                yield item
        finally:
            stop.set()
            # drain so workers blocked on put() can exit
            while not out_q.empty():
                out_q.get_nowait()


def eval_examples(data_dir: str, batch: int, *,
                  process_index: int = 0, process_count: int = 1,
                  image_size: int = IMAGE_SIZE,
                  eval_resize: int = EVAL_RESIZE,
                  verify_records: bool = False, use_native: bool = True
                  ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Sequential eval pass with zero-padded final batch (labels=-1 mark
    padding, mirroring pipeline.eval_batches)."""
    files = shard_files(data_dir, train=False)[process_index::process_count]
    if not files:
        raise ValueError("fewer validation shard files than processes")
    rng = np.random.default_rng(0)
    images = np.empty((batch, image_size, image_size, 3), np.uint8)
    labels = np.full((batch,), -1, np.int32)
    count = 0
    if Image is None:
        raise RuntimeError("PIL is required for ImageNet decoding")
    for f in files:
        for rec in read_shard_records(f, use_native=use_native,
                                      verify_crc=verify_records):
            jpeg, label = parse_record(rec)
            images[count] = decode_and_crop(jpeg, False, rng,
                                            eval_resize=eval_resize,
                                            out_size=image_size,
                                            use_native=use_native)
            labels[count] = label - 1
            count += 1
            if count == batch:
                yield images.copy(), labels.copy()
                count = 0
                labels[:] = -1
    if count:
        images[count:] = 0
        yield images.copy(), labels.copy()
