"""ImageNet input pipeline: TFRecord shards → decoded, cropped uint8 batches.

Reference behavior being matched (file:line):
- Shard naming: ``train-{00000..01023}-of-01024`` /
  ``validation-{00000..00127}-of-00128`` under ``data_dir``
  (resnet_imagenet_train.py:105-114).
- Example keys: ``image/encoded`` (JPEG bytes), ``image/class/label``
  (int64, 1-based → the dense layer has 1000(+1 background) classes; the
  reference keeps labels as-is and uses 1000 one-hot with label-1? No — it
  one-hots the raw label into 1000 classes after subtracting nothing;
  Inception shards store 1..1000, the reference's ``tf.one_hot(label,
  1000)`` silently maps 1000→all-zeros. We subtract 1 explicitly and
  document the deviation — it fixes a real off-by-one in the reference
  (resnet_imagenet_train.py:136-158).)
- VGG preprocessing, host half (vgg_preprocessing.py): train =
  aspect-preserving resize to a uniformly random smaller side in
  [resize_min, resize_max] (:306-309) then random 224×224 crop (:284-314);
  eval = resize to side 256 then central crop (:317-333). The flip and
  mean-subtraction run on-device (tpu_resnet.data.augment).
- Parallel decode: ``num_parallel_calls`` map threads
  (resnet_imagenet_train.py:170-171) → the host data engine here
  (tpu_resnet/data/engine.py): sequence-numbered per-batch work orders
  over **positions** (file, offset, length), decoded by thread or process
  workers into a preallocated slot ring. Batch order and contents are a
  pure function of (seed, step) — independent of worker count, mode and
  resume point.

Unlike the reference — where every worker reads all 1024 shards and
"shards" by independent shuffling (SURVEY.md §2.3) — shard files are
striped across processes, and the per-epoch file order is a pure function
of (seed, epoch).
"""

from __future__ import annotations

import glob
import io
import os
from typing import Iterator, List, Optional, Tuple

import numpy as np

from tpu_resnet.data import tfrecord

try:
    from PIL import Image
except ImportError:  # pragma: no cover - PIL is baked into the image
    Image = None

IMAGE_SIZE = 224
EVAL_RESIZE = 256


def read_shard_records(path: str, use_native: bool = True,
                       verify_crc: bool = False) -> Iterator[bytes]:
    """Record payloads of one shard — native C++ splitter when built
    (tpu_resnet/native), pure-python framing otherwise.

    ``verify_crc`` checks the masked CRC32C of every record. With the
    native plane this costs almost nothing (~700 MB/s measured vs
    ~3 MB/s for the pure-python CRC — the C++ data plane's headline win),
    so corrupted shards fail loudly instead of feeding garbage JPEGs."""
    if use_native:
        native_loader = None
        try:  # narrow: only the probe may fall through to python —
            # errors from the actual read (corrupt framing, CRC mismatch,
            # short read) must propagate, not trigger a silent re-read
            from tpu_resnet.native import available, loader
            if available():
                native_loader = loader
        except Exception:
            native_loader = None
        if native_loader is not None:
            return iter(native_loader.tfrecord_payloads(
                path, verify_crc=verify_crc))
    return tfrecord.read_records(path, verify_crc=verify_crc)


def shard_files(data_dir: str, train: bool) -> List[str]:
    pattern = os.path.join(data_dir, "train-*" if train else "validation-*")
    files = sorted(glob.glob(pattern))
    if not files:
        raise FileNotFoundError(f"no ImageNet shards match {pattern}")
    return files


def parse_record(serialized: bytes) -> Tuple[bytes, int]:
    ex = tfrecord.parse_example(serialized)
    jpeg = ex["image/encoded"][0]
    label = int(ex["image/class/label"][0])
    return jpeg, label


def _resize_keep_aspect(img: "Image.Image", smaller_side: int) -> "Image.Image":
    w, h = img.size
    scale = smaller_side / min(w, h)
    # round-half-up, matching the native path's lround — Python round()
    # half-rounds to even, which would give a 1px-different grid on
    # exact-.5 products
    return img.resize((max(1, int(w * scale + 0.5)),
                       max(1, int(h * scale + 0.5))), Image.BILINEAR)


def _native_decoder():
    """The C++ decode function when the JPEG-enabled library is built."""
    try:
        from tpu_resnet.native import jpeg_available, loader
        if jpeg_available():
            return loader.decode_jpeg_vgg
    except Exception:
        pass
    return None


_NATIVE_DECODE = None
_NATIVE_PROBED = False


def decode_and_crop(jpeg: bytes, train: bool, rng: np.random.Generator,
                    resize_min: int = 256, resize_max: int = 512,
                    eval_resize: int = EVAL_RESIZE,
                    out_size: int = IMAGE_SIZE,
                    use_native: bool = True) -> np.ndarray:
    """JPEG bytes → uint8 [out_size, out_size, 3] per VGG preprocessing
    (host half; see module docstring).

    Random draws (resize side, crop fractions) happen once up front, so
    the native C++ decoder (GIL-free libjpeg + bilinear, native/loader.cc)
    and the PIL fallback consume the same stream and are interchangeable
    per-image — unsupported images (CMYK, non-JPEG bytes) silently fall
    back to PIL."""
    global _NATIVE_DECODE, _NATIVE_PROBED
    if train:
        side = int(rng.integers(resize_min, resize_max + 1))
        fx, fy = float(rng.random()), float(rng.random())
    else:
        side = eval_resize
        fx = fy = -1.0  # floor-central crop in both decoders
    if use_native:
        if not _NATIVE_PROBED:
            _NATIVE_DECODE = _native_decoder()
            _NATIVE_PROBED = True
        if _NATIVE_DECODE is not None:
            out = _NATIVE_DECODE(jpeg, side, out_size, fx, fy)
            if out is not None:
                return out
    img = Image.open(io.BytesIO(jpeg))
    if img.mode != "RGB":
        img = img.convert("RGB")
    img = _resize_keep_aspect(img, side)
    w, h = img.size
    if fx < 0:  # eval: floor-central crop (vgg_preprocessing.py:171-193)
        x0, y0 = (w - out_size) // 2, (h - out_size) // 2
    else:  # train: fx/fy map uniformly onto the w-out+1 valid offsets
        x0 = min(int(fx * (w - out_size + 1)), w - out_size)
        y0 = min(int(fy * (h - out_size + 1)), h - out_size)
    img = img.crop((x0, y0, x0 + out_size, y0 + out_size))
    return np.asarray(img, np.uint8)


class ImageNetIterator:
    """Streaming iterator: files striped per process, epoch-shuffled
    record buffer, engine-decoded fixed-size uint8 batches.

    The iterator owns the *order* of the stream — per-epoch file shuffle,
    the reservoir shuffle buffer, the resume skip — all computed over
    cheap ``(file, record#)`` positions (the old payload-carrying buffer
    held up to ``shuffle_buffer`` whole JPEGs in RAM and needed an
    elaborate payload-free replay just to resume). Decoding is delegated
    to :class:`tpu_resnet.data.engine.HostDataEngine` via per-batch work
    orders, which is what makes the stream deterministic for any worker
    count: the old thread pool raced on a shared ``next(rec_iter)`` and
    admitted its batch order was nondeterministic.

    ``__iter__`` yields **views** into the engine's slot ring, valid for
    the following ``hold - 1`` draws (default hold 2: the current batch
    is always safe); copy to retain longer. Consumers that need engine
    lifecycle control (close, stats, process workers) call
    :meth:`engine` directly."""

    def __init__(self, data_dir: str, local_batch: int, *, train: bool = True,
                 seed: int = 0, num_workers: int = 4,
                 shuffle_buffer: int = 4096, resize_min: int = 256,
                 resize_max: int = 512, eval_resize: int = EVAL_RESIZE,
                 start_step: int = 0,
                 process_index: int = 0, process_count: int = 1,
                 image_size: int = IMAGE_SIZE, verify_records: bool = False,
                 use_native: bool = True):
        self.files = shard_files(data_dir, train)[process_index::process_count]
        if not self.files:
            raise ValueError("fewer shard files than processes")
        self.local_batch = local_batch
        self.train = train
        self.seed = seed
        self.num_workers = max(1, num_workers)
        self.shuffle_buffer = shuffle_buffer
        self.resize_min = resize_min
        self.resize_max = resize_max
        self.eval_resize = eval_resize
        self.image_size = image_size
        self.start_step = start_step
        self.verify_records = verify_records
        self.use_native = use_native
        self._findex: dict = {}

    def _file_index(self, path: str):
        """Cached seek-only (offset, length) index of one shard."""
        if path not in self._findex:
            self._findex[path] = tfrecord.record_index(path)
        return self._findex[path]

    def _epoch_files(self, epoch: int) -> List[str]:
        """Per-epoch shard order — pure function of (seed, epoch); the
        backbone ``_position_stream`` (and through it ``work_orders``)
        rides on."""
        files = list(self.files)
        np.random.default_rng((self.seed, epoch)).shuffle(files)
        return files

    def _position_stream(self) -> Iterator[Tuple[str, int]]:
        """(file, record#) visit order — the deterministic backbone both
        the shuffle and the work orders ride on. Infinite (epoch-cycled)
        for train, one pass for eval."""
        epoch = 0
        while True:
            files = (self._epoch_files(epoch) if self.train
                     else list(self.files))
            for f in files:
                for i in range(len(self._file_index(f))):
                    yield f, i
            if not self.train:
                return
            epoch += 1

    def _shuffle_stream(self, items: Iterator, rng: np.random.Generator,
                        buf: List) -> Iterator:
        """Reservoir-style shuffle buffer (the reference's
        ``shuffle(buffer_size=1024)``, resnet_imagenet_train.py:174-178)
        over arbitrary items — here cheap positions, never payloads."""
        for item in items:
            buf.append(item)
            if len(buf) >= self.shuffle_buffer:
                idx = int(rng.integers(0, len(buf)))
                buf[idx], buf[-1] = buf[-1], buf[idx]
                yield buf.pop()
        while buf:
            idx = int(rng.integers(0, len(buf)))
            buf[idx], buf[-1] = buf[-1], buf[idx]
            yield buf.pop()

    def _shuffled_positions(self) -> Iterator[Tuple[str, int]]:
        """Shuffled position stream; with ``start_step > 0`` it continues
        *exactly* where an uninterrupted run's stream would be after
        ``start_step`` batches (reference resume contract,
        resnet_imagenet_train.py:267-270 — which the reference itself does
        not honor for the input stream). Because the stream carries
        positions, resume is a plain skip of already-consumed draws — no
        payload reads, no replay machinery."""
        if not self.train:
            yield from self._position_stream()
            return
        rng = np.random.default_rng((self.seed, 1))
        stream = self._shuffle_stream(self._position_stream(), rng, [])
        for _ in range(self.start_step * self.local_batch):
            next(stream)  # infinite train stream: never drains
        yield from stream

    def work_orders(self) -> Iterator[List[Tuple[int, int, int]]]:
        """Pre-sliced per-batch record entries ``(file_idx, offset,
        length)`` — the engine's task-queue payload. Batch ``i`` of this
        stream is consumed at global step ``start_step + i``; contents
        are a pure function of (seed, step)."""
        fidx = {f: i for i, f in enumerate(self.files)}
        batch: List[Tuple[int, int, int]] = []
        for path, ri in self._shuffled_positions():
            off, length = self._file_index(path)[ri]
            batch.append((fidx[path], off, length))
            if len(batch) == self.local_batch:
                yield batch
                batch = []
        if batch:  # finite eval tail → partial order, engine zero-pads
            yield batch

    def engine(self, *, mode: str = "thread", workers: Optional[int] = None,
               ring_slots: int = 0, hold: int = 2, external_stop=None):
        """The decode engine for this stream (tpu_resnet/data/engine.py).
        Callers own its lifecycle: ``close()`` releases workers and (in
        process mode) unlinks the shared-memory ring."""
        from tpu_resnet.data.engine import HostDataEngine

        if Image is None:
            raise RuntimeError("PIL is required for ImageNet decoding")
        return HostDataEngine(
            self.work_orders(), files=self.files,
            local_batch=self.local_batch, image_size=self.image_size,
            seed=self.seed, train=self.train,
            resize_min=self.resize_min, resize_max=self.resize_max,
            eval_resize=self.eval_resize,
            verify_records=self.verify_records, use_native=self.use_native,
            mode=mode, workers=workers or self.num_workers,
            ring_slots=ring_slots, hold=hold, first_seq=self.start_step,
            external_stop=external_stop)

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        eng = self.engine()
        try:
            yield from eng
        finally:
            eng.close()


def eval_examples(data_dir: str, batch: int, *,
                  process_index: int = 0, process_count: int = 1,
                  image_size: int = IMAGE_SIZE,
                  eval_resize: int = EVAL_RESIZE,
                  verify_records: bool = False, use_native: bool = True,
                  pool_slots: int = 4
                  ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Sequential eval pass with zero-padded final batch (labels=-1 mark
    padding, mirroring pipeline.eval_batches).

    Yields from a small round-robin pool of preallocated batch buffers
    instead of ``np.empty`` + ``.copy()`` per batch: a yielded pair stays
    valid for the next ``pool_slots - 1`` batches, then its buffer is
    reused. Every in-repo consumer (evaluator → immediate device upload,
    predict → mask-indexed copies) is inside that window; copy to retain
    longer."""
    files = shard_files(data_dir, train=False)[process_index::process_count]
    if not files:
        raise ValueError("fewer validation shard files than processes")
    if Image is None:
        raise RuntimeError("PIL is required for ImageNet decoding")
    rng = np.random.default_rng(0)
    pool = [(np.empty((batch, image_size, image_size, 3), np.uint8),
             np.empty((batch,), np.int32))
            for _ in range(max(2, pool_slots))]
    slot = 0
    images, labels = pool[slot]
    count = 0
    for f in files:
        for rec in read_shard_records(f, use_native=use_native,
                                      verify_crc=verify_records):
            jpeg, label = parse_record(rec)
            images[count] = decode_and_crop(jpeg, False, rng,
                                            eval_resize=eval_resize,
                                            out_size=image_size,
                                            use_native=use_native)
            labels[count] = label - 1
            count += 1
            if count == batch:
                yield images, labels
                slot = (slot + 1) % len(pool)
                images, labels = pool[slot]
                count = 0
    if count:
        images[count:] = 0
        labels[count:] = -1
        yield images, labels
