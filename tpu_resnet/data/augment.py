"""On-device, jit-compatible data augmentation.

The reference augments on the host inside 16 queue-runner threads
(reference cifar_input.py:70-100). On TPU the idiomatic split is: the host
streams raw uint8 batches; augmentation runs *inside the compiled train step*
on the VPU, fused by XLA with the rest of the step. That removes the host
CPU from the per-step critical path entirely.

CIFAR semantics match reference cifar_input.py:70-79 exactly:
pad to 36×36 (symmetric — resize_image_with_crop_or_pad(36,36) pads 2 px per
side), random 32×32 crop, random horizontal flip, per-image standardization
with TF's ``adjusted_stddev = max(std, 1/sqrt(num_elements))``.

ImageNet device-side ops cover the tail of the VGG pipeline: random flip and
mean subtraction (reference vgg_preprocessing.py:284-314; the RGB means are
divided by 255 because images arrive as floats in [0,1],
vgg_preprocessing.py:37-39). Decode/resize/crop are host-side
(tpu_resnet.data.imagenet) since JPEG sizes are dynamic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Reference vgg_preprocessing.py:37-39 — means already divided by 255.
VGG_MEANS_01 = (123.68 / 255.0, 116.78 / 255.0, 103.94 / 255.0)


def per_image_standardization(images: jnp.ndarray) -> jnp.ndarray:
    """tf.image.per_image_standardization over a batch
    (reference cifar_input.py:79, :91)."""
    images = images.astype(jnp.float32)
    n = images[0].size
    mean = jnp.mean(images, axis=(1, 2, 3), keepdims=True)
    std = jnp.std(images, axis=(1, 2, 3), keepdims=True)
    adjusted = jnp.maximum(std, 1.0 / jnp.sqrt(jnp.float32(n)))
    return (images - mean) / adjusted


def _random_crop_batch(rng: jax.Array, images: jnp.ndarray,
                       pad: int) -> jnp.ndarray:
    """Pad symmetrically then take a per-image random crop of original size."""
    b, h, w, c = images.shape
    padded = jnp.pad(images, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    rng_h, rng_w = jax.random.split(rng)
    off_h = jax.random.randint(rng_h, (b,), 0, 2 * pad + 1)
    off_w = jax.random.randint(rng_w, (b,), 0, 2 * pad + 1)

    def crop_one(img, oh, ow):
        return jax.lax.dynamic_slice(img, (oh, ow, 0), (h, w, c))

    return jax.vmap(crop_one)(padded, off_h, off_w)


def _random_flip_batch(rng: jax.Array, images: jnp.ndarray) -> jnp.ndarray:
    b = images.shape[0]
    flip = jax.random.bernoulli(rng, 0.5, (b, 1, 1, 1))
    return jnp.where(flip, images[:, :, ::-1, :], images)


def cifar_train_augment(rng: jax.Array, images: jnp.ndarray) -> jnp.ndarray:
    """uint8 [B,32,32,3] → standardized float32, training path
    (reference cifar_input.py:70-79: crop_or_pad 36 → random_crop 32 → flip →
    standardize)."""
    rng_crop, rng_flip = jax.random.split(rng)
    images = images.astype(jnp.float32)
    images = _random_crop_batch(rng_crop, images, pad=2)
    images = _random_flip_batch(rng_flip, images)
    return per_image_standardization(images)


def cifar_eval_preprocess(images: jnp.ndarray) -> jnp.ndarray:
    """Eval path: standardization only (reference cifar_input.py:87-91)."""
    return per_image_standardization(images)


def imagenet_train_augment(rng: jax.Array, images: jnp.ndarray) -> jnp.ndarray:
    """uint8 [B,224,224,3] (already random-resized+cropped on host) →
    flip + mean-subtract, in [0,1] scale (vgg_preprocessing.py:284-314)."""
    images = images.astype(jnp.float32) / 255.0
    images = _random_flip_batch(rng, images)
    return images - jnp.asarray(VGG_MEANS_01).reshape(1, 1, 1, 3)


def imagenet_eval_preprocess(images: jnp.ndarray) -> jnp.ndarray:
    """Host already did aspect-preserving resize + central crop
    (vgg_preprocessing.py:317-333)."""
    images = images.astype(jnp.float32) / 255.0
    return images - jnp.asarray(VGG_MEANS_01).reshape(1, 1, 1, 3)


def get_augment_fns(dataset: str):
    """(train_augment(rng, imgs), eval_preprocess(imgs)) for a dataset."""
    if dataset == "imagenet":
        return imagenet_train_augment, imagenet_eval_preprocess
    if dataset in ("cifar10", "cifar100", "synthetic"):
        return cifar_train_augment, cifar_eval_preprocess
    raise ValueError(f"unknown dataset {dataset!r}")
