from tpu_resnet.data.augment import get_augment_fns
from tpu_resnet.data.cifar import load_cifar, load_split, synthetic_data
from tpu_resnet.data.pipeline import (
    BackgroundIterator,
    ShardedBatcher,
    device_prefetch,
    eval_batches,
)

__all__ = [
    "get_augment_fns",
    "load_cifar",
    "load_split",
    "synthetic_data",
    "BackgroundIterator",
    "ShardedBatcher",
    "device_prefetch",
    "eval_batches",
]
