"""Data-layer package. Re-exports resolve LAZILY (PEP 562): the engine's
spawned decode workers import ``tpu_resnet.data.engine`` (running this
``__init__`` as its parent package), and an eager ``pipeline``/``augment``
import here would drag a full jax import — seconds of spawn latency and
hundreds of MB RSS — into every worker process that only needs
numpy/PIL/the native loader."""

__all__ = [
    "get_augment_fns",
    "load_cifar",
    "load_split",
    "synthetic_data",
    "BackgroundIterator",
    "ShardedBatcher",
    "device_prefetch",
    "eval_batches",
    "train_batches",
    "eval_split_batches",
    "engine_workers",
]

_LAZY = {
    "get_augment_fns": "tpu_resnet.data.augment",
    "load_cifar": "tpu_resnet.data.cifar",
    "load_split": "tpu_resnet.data.cifar",
    "synthetic_data": "tpu_resnet.data.cifar",
    "BackgroundIterator": "tpu_resnet.data.pipeline",
    "ShardedBatcher": "tpu_resnet.data.pipeline",
    "device_prefetch": "tpu_resnet.data.pipeline",
    "eval_batches": "tpu_resnet.data.pipeline",
}


def __getattr__(name):
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(module), name)
    globals()[name] = value  # cache: __getattr__ fires once per name
    return value


def engine_workers(data_cfg) -> int:
    """Decode worker count for the configured engine mode."""
    if data_cfg.engine == "process":
        return data_cfg.num_decode_procs or data_cfg.num_workers
    return data_cfg.num_workers


def train_batches(data_cfg, local_batch: int, seed: int = 0,
                  start_step: int = 0, *, hold: int = 2,
                  external_stop=None):
    """Per-dataset training batch iterator (host side, per-process shard),
    yielding (uint8 images, int32 labels).

    ImageNet returns a :class:`tpu_resnet.data.engine.HostDataEngine`
    (mode per ``data_cfg.engine``): already backgrounded with its own
    ring prefetch, owns ``close()``, and yields ring *views* valid for
    ``hold - 1`` further draws — callers must NOT wrap it in another
    buffering layer (a queue holding more than ``hold`` references would
    alias recycled slots). In-memory datasets return a plain iterator the
    caller backgrounds as before."""
    import jax

    if data_cfg.dataset == "imagenet":
        from tpu_resnet.data.imagenet import ImageNetIterator
        it = ImageNetIterator(
            data_cfg.data_dir, local_batch, train=True, seed=seed,
            num_workers=data_cfg.num_workers,
            shuffle_buffer=min(data_cfg.shuffle_buffer, 65536),
            resize_min=data_cfg.resize_min, resize_max=data_cfg.resize_max,
            start_step=start_step,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            image_size=data_cfg.resolved_image_size,
            verify_records=data_cfg.verify_records,
            use_native=data_cfg.use_native_loader)
        return it.engine(mode=data_cfg.engine,
                         workers=engine_workers(data_cfg),
                         ring_slots=data_cfg.ring_slots, hold=hold,
                         external_stop=external_stop)
    from tpu_resnet.data.cifar import load_split
    from tpu_resnet.data.pipeline import ShardedBatcher

    images, labels = load_split(data_cfg, train=True)
    return iter(ShardedBatcher(images, labels, local_batch, seed=seed,
                               start_step=start_step))


def eval_split_batches(data_cfg, batch: int,
                       process_index: int = None, process_count: int = None):
    """Eval-split pass in batches of ``batch``; short batches zero-padded
    with labels=-1.

    Multi-process: each process iterates a *disjoint stripe* of the split
    (record striping for in-memory datasets, shard-file striping for
    ImageNet — the multi-host fix over the reference's every-node-reads-
    everything eval, resnet_imagenet_eval.py:83-165). ``batch`` is then the
    per-process batch; the evaluator assembles the global batch with
    ``make_array_from_process_local_data`` (pipeline.to_global_arrays)."""
    import jax

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if data_cfg.dataset == "imagenet":
        if data_cfg.engine == "process":
            # Process-decoded eval: same engine, sequential finite order
            # (reassembly by sequence number keeps the pass exact for any
            # worker count). The stream auto-closes at exhaustion; early
            # abandoners must call .close() (the evaluator does).
            from tpu_resnet.data.imagenet import ImageNetIterator
            it = ImageNetIterator(
                data_cfg.data_dir, batch, train=False,
                process_index=pi, process_count=pc,
                num_workers=data_cfg.num_workers,
                image_size=data_cfg.resolved_image_size,
                eval_resize=data_cfg.eval_resize,
                verify_records=data_cfg.verify_records,
                use_native=data_cfg.use_native_loader)
            return it.engine(mode="process",
                             workers=engine_workers(data_cfg),
                             ring_slots=data_cfg.ring_slots)
        from tpu_resnet.data.imagenet import eval_examples
        return eval_examples(data_cfg.data_dir, batch,
                             process_index=pi, process_count=pc,
                             image_size=data_cfg.resolved_image_size,
                             eval_resize=data_cfg.eval_resize,
                             verify_records=data_cfg.verify_records,
                             use_native=data_cfg.use_native_loader)
    from tpu_resnet.data.cifar import load_split
    from tpu_resnet.data.pipeline import eval_batches

    images, labels = load_split(data_cfg, train=False)
    return eval_batches(images[pi::pc], labels[pi::pc], batch)
