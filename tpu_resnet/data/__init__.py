from tpu_resnet.data.augment import get_augment_fns
from tpu_resnet.data.cifar import load_cifar, load_split, synthetic_data
from tpu_resnet.data.pipeline import (
    BackgroundIterator,
    ShardedBatcher,
    device_prefetch,
    eval_batches,
)

__all__ = [
    "get_augment_fns",
    "load_cifar",
    "load_split",
    "synthetic_data",
    "BackgroundIterator",
    "ShardedBatcher",
    "device_prefetch",
    "eval_batches",
    "train_batches",
    "eval_split_batches",
]


def train_batches(data_cfg, local_batch: int, seed: int = 0,
                  start_step: int = 0):
    """Per-dataset training batch iterator (host side, per-process shard),
    yielding (uint8 images, int32 labels)."""
    import jax

    if data_cfg.dataset == "imagenet":
        from tpu_resnet.data.imagenet import ImageNetIterator
        return iter(ImageNetIterator(
            data_cfg.data_dir, local_batch, train=True, seed=seed,
            num_workers=data_cfg.num_workers,
            shuffle_buffer=min(data_cfg.shuffle_buffer, 65536),
            resize_min=data_cfg.resize_min, resize_max=data_cfg.resize_max,
            start_step=start_step,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            image_size=data_cfg.resolved_image_size,
            verify_records=data_cfg.verify_records,
            use_native=data_cfg.use_native_loader))
    images, labels = load_split(data_cfg, train=True)
    return iter(ShardedBatcher(images, labels, local_batch, seed=seed,
                               start_step=start_step))


def eval_split_batches(data_cfg, batch: int,
                       process_index: int = None, process_count: int = None):
    """Eval-split pass in batches of ``batch``; short batches zero-padded
    with labels=-1.

    Multi-process: each process iterates a *disjoint stripe* of the split
    (record striping for in-memory datasets, shard-file striping for
    ImageNet — the multi-host fix over the reference's every-node-reads-
    everything eval, resnet_imagenet_eval.py:83-165). ``batch`` is then the
    per-process batch; the evaluator assembles the global batch with
    ``make_array_from_process_local_data`` (pipeline.to_global_arrays)."""
    import jax

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if data_cfg.dataset == "imagenet":
        from tpu_resnet.data.imagenet import eval_examples
        return eval_examples(data_cfg.data_dir, batch,
                             process_index=pi, process_count=pc,
                             image_size=data_cfg.resolved_image_size,
                             eval_resize=data_cfg.eval_resize,
                             verify_records=data_cfg.verify_records,
                             use_native=data_cfg.use_native_loader)
    images, labels = load_split(data_cfg, train=False)
    return eval_batches(images[pi::pc], labels[pi::pc], batch)
