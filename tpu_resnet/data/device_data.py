"""Device-resident dataset — the TPU-native answer to the reference's
16-thread host queue pipeline (reference cifar_input.py:81-103).

CIFAR-scale datasets (150 MB) are small next to TPU HBM, so instead of
streaming every batch over PCIe/host-link each step, the whole training
split is uploaded **once** and batches are cut on-device:

  flat uint8 dataset (replicated)
    ── once per epoch ──► jitted permutation → epoch buffer
                          shape (steps_per_epoch, batch, H, W, C),
                          batch axis sharded over the mesh 'data' axis
    ── every step ──────► ``dynamic_slice`` of row ``step % steps_per_epoch``

This removes all per-step host→device traffic (the reference moves every
batch through queue runners and feed dicts, resnet_cifar_train.py:204-247)
and keeps the input edge on the device timeline. Epoch shuffling is a pure
function of (seed, epoch) — same determinism contract as the host
``ShardedBatcher`` — computed by the TPU itself.

``make_chunked_step`` additionally fuses ``k`` consecutive steps into one
``lax.scan`` so a single dispatch drives k optimizer updates — amortizing
host→device command latency, which dominates when the chip is fast and the
per-step FLOPs are small (exactly the CIFAR regime).

Multi-host runs keep the streaming pipeline (each process owns a disjoint
record stripe that never leaves its host); this path is gated to
single-process meshes by ``should_use`` below.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def should_use(data_cfg) -> bool:
    """True when the resident path applies: policy 'on'/'auto', an
    in-memory dataset, a single-process run, and a split small enough for
    double-buffered residency (flat + epoch buffer). Policy 'on' raises
    when the path is impossible rather than silently streaming."""
    policy = getattr(data_cfg, "device_resident", "auto")
    if policy == "off":
        return False
    forced = policy == "on"
    if jax.process_count() != 1:
        if forced:
            raise ValueError("data.device_resident=on requires a "
                             "single-process run; multi-host uses the "
                             "streaming pipeline")
        return False
    if data_cfg.dataset not in ("cifar10", "cifar100", "synthetic"):
        if forced:
            raise ValueError(
                f"data.device_resident=on is unsupported for dataset "
                f"{data_cfg.dataset!r} (streams from TFRecord shards)")
        return False
    size = data_cfg.resolved_image_size
    nbytes = 2 * data_cfg.train_examples * size * size * 3  # flat + epoch buf
    return forced or nbytes <= data_cfg.resident_max_bytes


class DeviceDataset:
    """Training split resident in HBM with on-device epoch shuffling."""

    def __init__(self, mesh: Mesh, images: np.ndarray, labels: np.ndarray,
                 batch: int, seed: int = 0):
        n = len(images)
        if n < batch:  # tile tiny (smoke/synthetic) datasets up to one batch
            reps = -(-batch // n)
            images = np.concatenate([images] * reps)
            labels = np.concatenate([labels] * reps)
            n = len(images)
        self.n = n
        self.batch = batch
        self.steps_per_epoch = n // batch
        self.seed = seed
        self._epoch = None

        repl = NamedSharding(mesh, P())
        # Epoch buffer: (steps_per_epoch, batch, ...) with the *batch* axis
        # sharded over 'data' — each step's slice lands pre-sharded.
        self._buf_sharding = NamedSharding(mesh, P(None, "data"))
        self._flat_images = jax.device_put(images, repl)
        self._flat_labels = jax.device_put(labels.astype(np.int32), repl)

        spe, b = self.steps_per_epoch, batch

        def shuffle(flat_i, flat_l, epoch):
            rng = jax.random.fold_in(jax.random.PRNGKey(seed), epoch)
            order = jax.random.permutation(rng, n)[: spe * b]
            ib = jnp.take(flat_i, order, axis=0).reshape(
                (spe, b) + flat_i.shape[1:])
            lb = jnp.take(flat_l, order, axis=0).reshape((spe, b))
            return ib, lb

        self._shuffle = jax.jit(
            shuffle,
            in_shardings=(repl, repl, None),
            out_shardings=(self._buf_sharding, self._buf_sharding),
            static_argnums=(),
        )
        self.images = None
        self.labels = None

    def epoch_of(self, step: int) -> int:
        return step // self.steps_per_epoch

    def ensure_epoch(self, epoch: int) -> None:
        """(Re)build the shuffled epoch buffer if ``epoch`` changed — one
        on-device permutation per epoch (~ms), zero host traffic."""
        if epoch != self._epoch:
            self.images, self.labels = self._shuffle(
                self._flat_images, self._flat_labels, epoch)
            self._epoch = epoch


def make_resident_step(base_step: Callable, steps_per_epoch: int):
    """Wrap ``base_step(state, images, labels)`` into
    ``step(state, epoch_images, epoch_labels)`` that cuts the batch for
    ``state.step`` out of the resident epoch buffer on-device."""

    def step(state, epoch_images, epoch_labels):
        row = (state.step % steps_per_epoch).astype(jnp.int32)
        images = jax.lax.dynamic_index_in_dim(epoch_images, row, axis=0,
                                              keepdims=False)
        labels = jax.lax.dynamic_index_in_dim(epoch_labels, row, axis=0,
                                              keepdims=False)
        return base_step(state, images, labels)

    return step


def make_chunked_step(step_fn: Callable, k: int):
    """Fuse ``k`` consecutive steps into one ``lax.scan`` dispatch.
    Returns the state after k updates and the metrics of the *last* step
    (what the reference's LoggingTensorHook displays,
    resnet_cifar_train.py:282-287)."""
    if k == 1:
        return step_fn

    def chunk(state, epoch_images, epoch_labels):
        def body(s, _):
            s2, m = step_fn(s, epoch_images, epoch_labels)
            return s2, None

        state, _ = jax.lax.scan(body, state, None, length=k - 1)
        return step_fn(state, epoch_images, epoch_labels)

    return chunk


def compile_staged_stream_steps(base_step: Callable, mesh: Mesh,
                                per_replica_bn: bool = False):
    """Fused multi-step dispatch for the *streaming* input path — the
    counterpart of ``compile_resident_steps`` for data that arrives as
    staged ``(stage, B, ...)`` superbatches
    (pipeline.staged_superbatch_prefetch).

    Returns ``run(state, gi, gl, off, c) -> (state, metrics)`` executing
    steps ``off .. off+c`` of the superbatch in ONE dispatch (a
    ``lax.scan`` over the stage rows): per-dispatch host↔device command
    latency — which dominates on a remote-attached chip when per-step
    compute is small — is amortized ``c``-fold. ``off`` is a traced
    scalar (no recompile per position); distinct ``c`` values compile
    once each (the loop only uses the handful its log/checkpoint
    boundaries require). Metrics are the last step's, like the
    reference's LoggingTensorHook (resnet_cifar_train.py:282-287)."""
    repl = NamedSharding(mesh, P())
    staged = NamedSharding(mesh, P(None, "data"))
    cache = {}

    def compiled(c: int):
        if c not in cache:
            def chunk(state, gi, gl, off):
                imgs = jax.lax.dynamic_slice_in_dim(gi, off, c, axis=0)
                labs = jax.lax.dynamic_slice_in_dim(gl, off, c, axis=0)
                if c == 1:
                    return base_step(state, imgs[0], labs[0])

                def body(s, xs):
                    s2, _ = base_step(s, xs[0], xs[1])
                    return s2, None

                state, _ = jax.lax.scan(
                    body, state, (imgs[:-1], labs[:-1]))
                return base_step(state, imgs[-1], labs[-1])

            if per_replica_bn:
                from tpu_resnet.train.step import per_replica_shard_map

                chunk = per_replica_shard_map(
                    chunk, mesh,
                    in_specs=(P(), P(None, "data"), P(None, "data"), P()))
            cache[c] = jax.jit(
                chunk,
                in_shardings=(repl, staged, staged, None),
                donate_argnums=(0,),
            )
        return cache[c]

    def run(state, gi, gl, off: int, c: int):
        return compiled(c)(state, gi, gl, jnp.int32(off))

    return run


def compile_resident_steps(base_step: Callable, ds: DeviceDataset,
                           mesh: Mesh, steps_per_call: int,
                           per_replica_bn: bool = False):
    """Returns ``run(state, k) -> (state, metrics)`` executing ``k`` steps
    (k ≤ steps_per_call) in one dispatch against the resident dataset.
    Distinct k values compile once each (the training loop only uses the
    handful of chunk sizes its log/checkpoint boundaries require).

    ``per_replica_bn`` wraps each chunk in ``shard_map`` (see
    train/step.py::shard_step); the epoch buffer's batch axis is sharded
    over 'data', so each replica slices its own local rows."""
    resident = make_resident_step(base_step, ds.steps_per_epoch)
    repl = NamedSharding(mesh, P())
    cache = {}

    def compiled(k: int):
        if k not in cache:
            chunk = make_chunked_step(resident, k)
            if per_replica_bn:
                from tpu_resnet.train.step import per_replica_shard_map

                chunk = per_replica_shard_map(
                    chunk, mesh,
                    in_specs=(P(), P(None, "data"), P(None, "data")))
            cache[k] = jax.jit(
                chunk,
                in_shardings=(repl, ds._buf_sharding, ds._buf_sharding),
                donate_argnums=(0,),
            )
        return cache[k]

    def run(state, step: int, k: int):
        """``step`` is the host-tracked step counter (avoids a device sync);
        the caller keeps chunks from crossing epoch boundaries."""
        if k > steps_per_call:
            raise ValueError(f"chunk of {k} steps exceeds steps_per_call="
                             f"{steps_per_call}; the host step counter "
                             f"would desync from state.step")
        ds.ensure_epoch(ds.epoch_of(step))
        return compiled(k)(state, ds.images, ds.labels)

    return run
