"""Device-resident dataset — the TPU-native answer to the reference's
16-thread host queue pipeline (reference cifar_input.py:81-103).

CIFAR-scale datasets (150 MB) are small next to TPU HBM, so instead of
streaming every batch over PCIe/host-link each step, the whole training
split is uploaded **once** and batches are cut on-device:

  flat uint8 dataset (replicated)
    ── once per epoch ──► jitted permutation → epoch buffer
                          shape (steps_per_epoch, batch, H, W, C),
                          batch axis sharded over the mesh 'data' axis
    ── every dispatch ──► ``dynamic_slice`` of the chunk's contiguous
                          ``(k, batch, ...)`` block + ``lax.scan`` over it

This removes all per-step host→device traffic (the reference moves every
batch through queue runners and feed dicts, resnet_cifar_train.py:204-247)
and keeps the input edge on the device timeline. Epoch shuffling is a pure
function of (seed, epoch) — same determinism contract as the host
``ShardedBatcher`` — computed by the TPU itself.

Fusing ``k`` steps per dispatch amortizes host→device command latency,
which dominates when the chip is fast and the per-step FLOPs are small
(exactly the CIFAR regime). The chunk program is shared with the
streaming path (``compile_staged_stream_steps``) — see
``compile_resident_steps`` for why the slice offset must not depend on
the scan carry.

Multi-host runs keep the streaming pipeline (each process owns a disjoint
record stripe that never leaves its host); this path is gated to
single-process meshes by ``should_use`` below.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def should_use(data_cfg) -> bool:
    """True when the resident path applies: policy 'on'/'auto', an
    in-memory dataset, a single-process run, and a split small enough for
    double-buffered residency (flat + epoch buffer). Policy 'on' raises
    when the path is impossible rather than silently streaming."""
    policy = getattr(data_cfg, "device_resident", "auto")
    if policy == "off":
        return False
    forced = policy == "on"
    if jax.process_count() != 1:
        if forced:
            raise ValueError("data.device_resident=on requires a "
                             "single-process run; multi-host uses the "
                             "streaming pipeline")
        return False
    if data_cfg.dataset not in ("cifar10", "cifar100", "synthetic"):
        if forced:
            raise ValueError(
                f"data.device_resident=on is unsupported for dataset "
                f"{data_cfg.dataset!r} (streams from TFRecord shards)")
        return False
    size = data_cfg.resolved_image_size
    nbytes = 2 * data_cfg.train_examples * size * size * 3  # flat + epoch buf
    return forced or nbytes <= data_cfg.resident_max_bytes


class DeviceDataset:
    """Training split resident in HBM with on-device epoch shuffling."""

    def __init__(self, mesh: Mesh, images: np.ndarray, labels: np.ndarray,
                 batch: int, seed: int = 0):
        n = len(images)
        if n < batch:  # tile tiny (smoke/synthetic) datasets up to one batch
            reps = -(-batch // n)
            images = np.concatenate([images] * reps)
            labels = np.concatenate([labels] * reps)
            n = len(images)
        self.n = n
        self.batch = batch
        self.steps_per_epoch = n // batch
        self.seed = seed
        self._epoch = None

        repl = NamedSharding(mesh, P())
        # Epoch buffer: (steps_per_epoch, batch, ...) with the *batch* axis
        # sharded over 'data' — each step's slice lands pre-sharded.
        self._buf_sharding = NamedSharding(mesh, P(None, "data"))
        self._flat_images = jax.device_put(images, repl)
        self._flat_labels = jax.device_put(labels.astype(np.int32), repl)

        spe, b = self.steps_per_epoch, batch

        def shuffle(flat_i, flat_l, epoch):
            rng = jax.random.fold_in(jax.random.PRNGKey(seed), epoch)
            order = jax.random.permutation(rng, n)[: spe * b]
            ib = jnp.take(flat_i, order, axis=0).reshape(
                (spe, b) + flat_i.shape[1:])
            lb = jnp.take(flat_l, order, axis=0).reshape((spe, b))
            return ib, lb

        self._shuffle = jax.jit(
            shuffle,
            in_shardings=(repl, repl, None),
            out_shardings=(self._buf_sharding, self._buf_sharding),
            static_argnums=(),
        )
        self.images = None
        self.labels = None

    def epoch_of(self, step: int) -> int:
        return step // self.steps_per_epoch

    def ensure_epoch(self, epoch: int) -> None:
        """(Re)build the shuffled epoch buffer if ``epoch`` changed — one
        on-device permutation per epoch (~ms), zero host traffic."""
        if epoch != self._epoch:
            self.images, self.labels = self._shuffle(
                self._flat_images, self._flat_labels, epoch)
            self._epoch = epoch


def make_chunk_fn(base_step: Callable, c: int):
    """The fused ``c``-step chunk program over a staged ``(stage, B, ...)``
    superbatch — ``chunk(state, gi, gl, off)`` scans steps ``off ..
    off + c``. Module-level (not a closure of the compile cache) so the
    config-matrix verifier can trace and golden-pin exactly the program
    the staged/double-buffered H2D path dispatches
    (tpu_resnet/analysis/configmatrix.py ``staged-chunk`` entries)."""

    def chunk(state, gi, gl, off):
        imgs = jax.lax.dynamic_slice_in_dim(gi, off, c, axis=0)
        labs = jax.lax.dynamic_slice_in_dim(gl, off, c, axis=0)
        if c == 1:
            return base_step(state, imgs[0], labs[0])

        def body(s, xs):
            s2, _ = base_step(s, xs[0], xs[1])
            return s2, None

        state, _ = jax.lax.scan(
            body, state, (imgs[:-1], labs[:-1]))
        return base_step(state, imgs[-1], labs[-1])

    return chunk


def staged_chunk_jit(base_step: Callable, mesh: Mesh, c: int,
                     per_replica_bn: bool = False,
                     donate_state: bool = True,
                     state_sharding=None):
    """THE jitted fused ``c``-step chunk program over a staged
    superbatch — the one constructor behind ``compile_staged_stream_steps``
    (the loop's streaming/double-buffered dispatch), the memory ledger's
    staged probe (obs/memory.py) and the golden memory-budget engine
    (analysis/memorybudget.py), so the check engines and the runtime can
    never compile different programs for the same key
    (tpu_resnet/programs/registry.py owns the key spelling)."""
    repl = NamedSharding(mesh, P())
    staged = NamedSharding(mesh, P(None, "data"))
    chunk = make_chunk_fn(base_step, c)
    if per_replica_bn:
        from tpu_resnet.train.step import per_replica_shard_map

        chunk = per_replica_shard_map(
            chunk, mesh,
            in_specs=(P(), P(None, "data"), P(None, "data"), P()))
    return jax.jit(
        chunk,
        in_shardings=(state_sharding if state_sharding is not None
                      else repl, staged, staged, None),
        donate_argnums=(0,) if donate_state else (),
    )


def compile_staged_stream_steps(base_step: Callable, mesh: Mesh,
                                per_replica_bn: bool = False,
                                donate_state: bool = True,
                                state_sharding=None,
                                program_hook=None):
    """Fused multi-step dispatch for the *streaming* input path — the
    counterpart of ``compile_resident_steps`` for data that arrives as
    staged ``(stage, B, ...)`` superbatches
    (pipeline.staged_superbatch_prefetch). ``donate_state=False`` is the
    sweep harness's donation knob (tools/sweep.py) — production callers
    keep the default in-place update.

    Returns ``run(state, gi, gl, off, c) -> (state, metrics)`` executing
    steps ``off .. off+c`` of the superbatch in ONE dispatch (a
    ``lax.scan`` over the stage rows): per-dispatch host↔device command
    latency — which dominates on a remote-attached chip when per-step
    compute is small — is amortized ``c``-fold. ``off`` is a traced
    scalar (no recompile per position); distinct ``c`` values compile
    once each (the loop only uses the handful its log/checkpoint
    boundaries require). Metrics are the last step's, like the
    reference's LoggingTensorHook (resnet_cifar_train.py:282-287).

    ``state_sharding`` is the TrainState-shaped sharding tree from
    ``parallel.StatePartitioner.state_shardings`` (None = fully
    replicated, the historical layout) — the zero1 loop passes its
    sharded tree so the chunk program's optimizer-slot arguments compile
    to per-shard buffers.

    ``program_hook(c, jitted) -> callable`` lets the program registry
    (tpu_resnet/programs/registry.py) intercept each per-``c`` jit for
    its persistent AOT executable cache; None (the default) keeps the
    exact historical jit objects."""
    cache = {}

    def compiled(c: int):
        if c not in cache:
            jitted = staged_chunk_jit(base_step, mesh, c,
                                      per_replica_bn=per_replica_bn,
                                      donate_state=donate_state,
                                      state_sharding=state_sharding)
            cache[c] = (program_hook(c, jitted)
                        if program_hook is not None else jitted)
        return cache[c]

    def run(state, gi, gl, off: int, c: int):
        return compiled(c)(state, gi, gl, jnp.int32(off))

    return run


def compile_resident_steps(base_step: Callable, ds: DeviceDataset,
                           mesh: Mesh, steps_per_call: int,
                           per_replica_bn: bool = False,
                           state_sharding=None,
                           program_hook=None):
    """Returns ``run(state, step, k) -> (state, metrics)`` executing ``k``
    steps (k ≤ steps_per_call) in one dispatch against the resident
    dataset.

    The chunk is the same program as the streaming path's
    (``compile_staged_stream_steps``): a contiguous ``(k, batch, ...)``
    block is ``dynamic_slice``d out of the epoch buffer at the *traced*
    host-step offset, then a ``lax.scan`` consumes its rows. An earlier
    design instead indexed the epoch buffer per step with
    ``state.step % steps_per_epoch`` *inside* the scan — on a real TPU
    that measured ~2.8x slower per step (4.9 ms vs 1.7, v5e, ResNet-50
    CIFAR b128): the slice index hangs off the scan carry, so each HBM
    read serializes behind the previous step's full update instead of
    being prefetched ahead of the loop. Slicing at a scan-independent
    offset restores the pipelining and unifies the two input edges.

    Chunks never cross an epoch boundary (the loop's ``_chunk_len`` and
    the bench's plans both guarantee it), so one contiguous slice always
    covers the chunk. ``per_replica_bn`` compiles the shard_map variant;
    the epoch buffer's batch axis is sharded over 'data', so each replica
    slices its own local rows."""
    run_staged = compile_staged_stream_steps(base_step, mesh,
                                             per_replica_bn=per_replica_bn,
                                             state_sharding=state_sharding,
                                             program_hook=program_hook)

    def run(state, step: int, k: int):
        """``step`` is the host-tracked step counter (avoids a device sync);
        it must equal ``state.step`` (the resume path restores both)."""
        if k > steps_per_call:
            raise ValueError(f"chunk of {k} steps exceeds steps_per_call="
                             f"{steps_per_call}; the host step counter "
                             f"would desync from state.step")
        off = step % ds.steps_per_epoch
        if off + k > ds.steps_per_epoch:
            raise ValueError(f"chunk [{step}, {step + k}) crosses the "
                             f"epoch boundary (steps_per_epoch="
                             f"{ds.steps_per_epoch})")
        ds.ensure_epoch(ds.epoch_of(step))
        return run_staged(state, ds.images, ds.labels, off, k)

    return run
