"""Host → device input pipeline.

Replaces the reference's queue-runner threads (cifar_input.py:81-103) and
tf.data one-shot iterators (resnet_cifar_train.py:204-247) with a small
explicit pipeline:

  numpy source (per-host shard) → background-thread batcher →
  ``jax.make_array_from_process_local_data`` → double-buffered device queue

Two deliberate fixes over the reference:
- **Per-host sharding.** Every reference worker reads and shuffles the whole
  dataset independently — sharding is "hope the shuffles differ"
  (resnet_cifar_train.py:216-222, SURVEY.md §2.3). Here each process owns a
  disjoint record stripe, and the global batch is assembled from process-
  local shards.
- **Deterministic order.** Shuffles are a pure function of (seed, epoch), so
  restarts reproduce the stream.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

Batch = Tuple[np.ndarray, np.ndarray]

# BackgroundIterator liveness knobs (module-level so tests can tighten
# them): how long the consumer's get() waits between producer-liveness
# checks, and how long an erroring producer tries the ordered put before
# freeing a slot (drain-then-put).
GET_POLL_SEC = 1.0
ERROR_PUT_TIMEOUT_SEC = 2.0


class ShardedBatcher:
    """Infinite shuffled batches over a per-process shard of an in-memory
    array source."""

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 local_batch: int, seed: int = 0, shuffle: bool = True,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 start_step: int = 0):
        pi = jax.process_index() if process_index is None else process_index
        pc = jax.process_count() if process_count is None else process_count
        # Record-level striping: process i owns records i, i+pc, i+2pc, …
        self.images = images[pi::pc]
        self.labels = labels[pi::pc]
        self.local_batch = local_batch
        self.seed = seed
        self.shuffle = shuffle
        self.n = len(self.images)
        if self.n < local_batch:
            reps = -(-local_batch // self.n)
            self.images = np.concatenate([self.images] * reps)
            self.labels = np.concatenate([self.labels] * reps)
            self.n = len(self.images)
        self.start_step = start_step

    def __iter__(self) -> Iterator[Batch]:
        # Fast-forward to start_step so a resumed run continues the exact
        # stream an uninterrupted run would have seen (the shuffle is a pure
        # function of (seed, epoch), so no batches need replaying).
        batches_per_epoch = self.n // self.local_batch
        epoch = self.start_step // batches_per_epoch
        pos = (self.start_step % batches_per_epoch) * self.local_batch
        order = (np.random.default_rng((self.seed, epoch)).permutation(self.n)
                 if self.shuffle else np.arange(self.n))
        epoch += 1
        while True:
            if pos + self.local_batch > self.n:
                if self.shuffle:
                    order = np.random.default_rng(
                        (self.seed, epoch)).permutation(self.n)
                epoch += 1
                pos = 0
            idx = order[pos:pos + self.local_batch]
            pos += self.local_batch
            yield self.images[idx], self.labels[idx]


def eval_batches(images: np.ndarray, labels: np.ndarray,
                 batch: int) -> Iterator[Batch]:
    """Sequential full pass; the last partial batch is zero-padded and the
    true count carried via a mask column in labels' companion array."""
    n = len(images)
    for start in range(0, n, batch):
        img = images[start:start + batch]
        lab = labels[start:start + batch]
        if len(img) < batch:
            pad = batch - len(img)
            img = np.concatenate([img, np.zeros((pad,) + img.shape[1:],
                                                img.dtype)])
            lab = np.concatenate([lab, np.full((pad,), -1, lab.dtype)])
        yield img, lab


class BackgroundIterator:
    """Runs an iterator in a daemon thread with a bounded queue — the analog
    of the reference's QueueRunner prefetching (cifar_input.py:99-100), one
    thread being enough since augmentation moved on-device.

    Right for sources that are cheap per item (in-memory CIFAR batch
    slicing): one producer thread and a queue of owned arrays. CPU-heavy
    sources (ImageNet JPEG decode) use its multi-worker generalization,
    tpu_resnet/data/engine.py::HostDataEngine — N thread/process workers
    over a preallocated slot ring with the same consumer-facing contract
    (close(), external_stop, producer-death raises). Do NOT stack this on
    top of an engine: the queue would hold more ring views than the
    engine's recycle window allows."""

    def __init__(self, it: Iterator, capacity: int = 4,
                 external_stop: Optional[threading.Event] = None):
        """``external_stop``: an event whose set() ends iteration at the
        consumer within ~GET_POLL_SEC even while the producer is stalled —
        the hook that lets a graceful preemption stop (tpu_resnet/
        resilience) unblock a loop stuck in next() on a dead data source
        and still save its final checkpoint inside the grace window."""
        self._q: queue.Queue = queue.Queue(maxsize=capacity)
        self._it = it
        self._stop = threading.Event()
        self._external_stop = external_stop
        self._thread = threading.Thread(target=self._fill, daemon=True)
        self._thread.start()

    def _fill(self):
        try:
            for item in self._it:
                if not self._put(item):
                    return
        except Exception as e:  # surface loader errors to the consumer
            # Error path must never deadlock against a full queue (the old
            # unconditional put(e) could block forever against a consumer
            # that stopped draining). Preserve ordering when there is
            # room; if the queue stays full, drop the buffered batches —
            # the error is terminal anyway — and enqueue the exception
            # into the freed slot.
            try:
                self._q.put(e, timeout=ERROR_PUT_TIMEOUT_SEC)
            except queue.Full:
                self._drain()
                try:
                    self._q.put_nowait(e)
                except queue.Full:  # pragma: no cover - sole producer
                    pass
            return  # no StopIteration after an error: the consumer raises
        self._put(StopIteration)

    def _put(self, item) -> bool:
        """Stop-aware bounded put; False when close() was requested."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _drain(self):
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                return

    def close(self):
        """Release the producer thread and its buffered items (for
        consumers that stop early, e.g. benchmark warm-ups)."""
        self._stop.set()
        self._drain()
        self._thread.join(timeout=5)

    def __iter__(self):
        return self

    def __next__(self):
        # Bounded-timeout get with a producer-liveness check: a producer
        # thread that dies without enqueueing its exception (killed
        # interpreter-side, raised something Exception doesn't catch) must
        # surface as an error here, not block the training loop forever.
        while True:
            try:
                item = self._q.get(timeout=GET_POLL_SEC)
                break
            except queue.Empty:
                if (self._external_stop is not None
                        and self._external_stop.is_set()):
                    raise StopIteration  # preemption: stop waiting for data
                if self._thread.is_alive():
                    continue  # slow source, live producer: keep waiting
                try:  # producer exited; take anything it managed to leave
                    item = self._q.get_nowait()
                    break
                except queue.Empty:
                    raise RuntimeError(
                        "BackgroundIterator producer thread died without "
                        "yielding a result or an error") from None
        if item is StopIteration:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item


def to_global_arrays(batch: Batch, sharding) -> Tuple[jax.Array, jax.Array]:
    """Assemble a global (mesh-sharded) array from this process's local
    batch shard."""
    images, labels = batch
    gi = jax.make_array_from_process_local_data(sharding, images)
    gl = jax.make_array_from_process_local_data(sharding, labels)
    return gi, gl


def device_prefetch(host_iter: Iterator[Batch], sharding,
                    depth: int = 2) -> Iterator[Tuple[jax.Array, jax.Array]]:
    """Keep ``depth`` batches in flight on device so H2D transfer overlaps
    with the previous step's compute (the reference's ``prefetch(2*batch)``,
    resnet_cifar_train.py:233, moved to the device edge)."""
    buf: collections.deque = collections.deque()
    it = iter(host_iter)
    try:
        while len(buf) < depth:
            buf.append(to_global_arrays(next(it), sharding))
    except StopIteration:
        pass
    while buf:
        nxt = buf.popleft()
        try:
            buf.append(to_global_arrays(next(it), sharding))
        except StopIteration:
            pass
        yield nxt


def staged_superbatch_prefetch(host_iter: Iterator[Batch], stage_sharding,
                               stage: int = 4, depth: int = 2
                               ) -> Iterator[Tuple[jax.Array, jax.Array, int]]:
    """Transfer ``stage`` batches per host→device copy and yield the whole
    ``(k, B, ...)`` superbatch plus its true length ``k`` — the consumer
    (train/loop.py) fuses the k steps into one dispatch
    (device_data.compile_staged_stream_steps). A final partial stage of a
    finite stream is yielded with its true k."""

    def superbatches():
        it = iter(host_iter)
        while True:
            imgs, labs = [], []
            try:
                while len(imgs) < stage:
                    im, lb = next(it)
                    imgs.append(im)
                    labs.append(lb)
            except StopIteration:
                pass
            if not imgs:
                return
            yield (np.stack(imgs), np.stack(labs))

    buf: collections.deque = collections.deque()
    sb = superbatches()

    def load():
        imgs, labs = next(sb)
        gi = jax.make_array_from_process_local_data(stage_sharding, imgs)
        gl = jax.make_array_from_process_local_data(stage_sharding, labs)
        return gi, gl, len(imgs)

    try:
        while len(buf) < depth:
            buf.append(load())
    except StopIteration:
        pass
    while buf:
        nxt = buf.popleft()
        try:
            buf.append(load())  # refill before yielding the current stage
        except StopIteration:
            pass
        yield nxt


def staged_device_prefetch(host_iter: Iterator[Batch], stage_sharding,
                           stage: int = 4, depth: int = 2
                           ) -> Iterator[Tuple[jax.Array, jax.Array]]:
    """Like ``device_prefetch`` but transfers ``stage`` batches per
    host→device copy and cuts per-step batches on-device.

    Each transfer pays a fixed command/latency cost on top of bandwidth;
    when the interconnect to the device is latency-bound (remote-attached
    TPU, small batches) per-batch transfers serialize against compute.
    Staging k batches into one ``(k, B, ...)`` array amortizes that cost
    k-fold; the per-step slice is one cheap on-device ``dynamic_slice``.
    ``stage_sharding`` must shard the *batch* axis, i.e. ``P(None,
    'data')`` over axis 1. A final partial stage (end of a finite stream)
    is transferred with its true length.

    Thin per-step view over ``staged_superbatch_prefetch`` — the training
    loop consumes the superbatches directly (fused multi-step dispatch);
    this form serves consumers that want a per-batch iterator."""
    take = jax.jit(
        lambda a, i: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False))
    for gi, gl, k in staged_superbatch_prefetch(host_iter, stage_sharding,
                                                stage=stage, depth=depth):
        for i in range(k):
            yield take(gi, i), take(gl, i)
