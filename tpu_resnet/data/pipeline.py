"""Host → device input pipeline.

Replaces the reference's queue-runner threads (cifar_input.py:81-103) and
tf.data one-shot iterators (resnet_cifar_train.py:204-247) with a small
explicit pipeline:

  numpy source (per-host shard) → background-thread batcher →
  ``jax.make_array_from_process_local_data`` → double-buffered device queue

Two deliberate fixes over the reference:
- **Per-host sharding.** Every reference worker reads and shuffles the whole
  dataset independently — sharding is "hope the shuffles differ"
  (resnet_cifar_train.py:216-222, SURVEY.md §2.3). Here each process owns a
  disjoint record stripe, and the global batch is assembled from process-
  local shards.
- **Deterministic order.** Shuffles are a pure function of (seed, epoch), so
  restarts reproduce the stream.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

Batch = Tuple[np.ndarray, np.ndarray]

# BackgroundIterator liveness knobs (module-level so tests can tighten
# them): how long the consumer's get() waits between producer-liveness
# checks, and how long an erroring producer tries the ordered put before
# freeing a slot (drain-then-put).
GET_POLL_SEC = 1.0
ERROR_PUT_TIMEOUT_SEC = 2.0


class ShardedBatcher:
    """Infinite shuffled batches over a per-process shard of an in-memory
    array source."""

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 local_batch: int, seed: int = 0, shuffle: bool = True,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 start_step: int = 0):
        pi = jax.process_index() if process_index is None else process_index
        pc = jax.process_count() if process_count is None else process_count
        # Record-level striping: process i owns records i, i+pc, i+2pc, …
        self.images = images[pi::pc]
        self.labels = labels[pi::pc]
        self.local_batch = local_batch
        self.seed = seed
        self.shuffle = shuffle
        self.n = len(self.images)
        if self.n < local_batch:
            reps = -(-local_batch // self.n)
            self.images = np.concatenate([self.images] * reps)
            self.labels = np.concatenate([self.labels] * reps)
            self.n = len(self.images)
        self.start_step = start_step

    def __iter__(self) -> Iterator[Batch]:
        # Fast-forward to start_step so a resumed run continues the exact
        # stream an uninterrupted run would have seen (the shuffle is a pure
        # function of (seed, epoch), so no batches need replaying).
        batches_per_epoch = self.n // self.local_batch
        epoch = self.start_step // batches_per_epoch
        pos = (self.start_step % batches_per_epoch) * self.local_batch
        order = (np.random.default_rng((self.seed, epoch)).permutation(self.n)
                 if self.shuffle else np.arange(self.n))
        epoch += 1
        while True:
            if pos + self.local_batch > self.n:
                if self.shuffle:
                    order = np.random.default_rng(
                        (self.seed, epoch)).permutation(self.n)
                epoch += 1
                pos = 0
            idx = order[pos:pos + self.local_batch]
            pos += self.local_batch
            yield self.images[idx], self.labels[idx]


def eval_batches(images: np.ndarray, labels: np.ndarray,
                 batch: int) -> Iterator[Batch]:
    """Sequential full pass; the last partial batch is zero-padded and the
    true count carried via a mask column in labels' companion array."""
    n = len(images)
    for start in range(0, n, batch):
        img = images[start:start + batch]
        lab = labels[start:start + batch]
        if len(img) < batch:
            pad = batch - len(img)
            img = np.concatenate([img, np.zeros((pad,) + img.shape[1:],
                                                img.dtype)])
            lab = np.concatenate([lab, np.full((pad,), -1, lab.dtype)])
        yield img, lab


class BackgroundIterator:
    """Runs an iterator in a daemon thread with a bounded queue — the analog
    of the reference's QueueRunner prefetching (cifar_input.py:99-100), one
    thread being enough since augmentation moved on-device.

    Right for sources that are cheap per item (in-memory CIFAR batch
    slicing): one producer thread and a queue of owned arrays. CPU-heavy
    sources (ImageNet JPEG decode) use its multi-worker generalization,
    tpu_resnet/data/engine.py::HostDataEngine — N thread/process workers
    over a preallocated slot ring with the same consumer-facing contract
    (close(), external_stop, producer-death raises). Do NOT stack this on
    top of an engine: the queue would hold more ring views than the
    engine's recycle window allows."""

    def __init__(self, it: Iterator, capacity: int = 4,
                 external_stop: Optional[threading.Event] = None):
        """``external_stop``: an event whose set() ends iteration at the
        consumer within ~GET_POLL_SEC even while the producer is stalled —
        the hook that lets a graceful preemption stop (tpu_resnet/
        resilience) unblock a loop stuck in next() on a dead data source
        and still save its final checkpoint inside the grace window."""
        self._q: queue.Queue = queue.Queue(maxsize=capacity)
        self._it = it
        self._stop = threading.Event()
        self._external_stop = external_stop
        self._thread = threading.Thread(target=self._fill, daemon=True)
        self._thread.start()

    def _fill(self):
        try:
            for item in self._it:
                if not self._put(item):
                    return
        except Exception as e:  # surface loader errors to the consumer
            # Error path must never deadlock against a full queue (the old
            # unconditional put(e) could block forever against a consumer
            # that stopped draining). Preserve ordering when there is
            # room; if the queue stays full, drop the buffered batches —
            # the error is terminal anyway — and enqueue the exception
            # into the freed slot.
            try:
                self._q.put(e, timeout=ERROR_PUT_TIMEOUT_SEC)
            except queue.Full:
                self._drain()
                try:
                    self._q.put_nowait(e)
                except queue.Full:  # pragma: no cover - sole producer
                    pass
            return  # no StopIteration after an error: the consumer raises
        self._put(StopIteration)

    def _put(self, item) -> bool:
        """Stop-aware bounded put; False when close() was requested."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _drain(self):
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                return

    def close(self):
        """Release the producer thread and its buffered items (for
        consumers that stop early, e.g. benchmark warm-ups)."""
        self._stop.set()
        self._drain()
        self._thread.join(timeout=5)

    def __iter__(self):
        return self

    def __next__(self):
        # Bounded-timeout get with a producer-liveness check: a producer
        # thread that dies without enqueueing its exception (killed
        # interpreter-side, raised something Exception doesn't catch) must
        # surface as an error here, not block the training loop forever.
        while True:
            try:
                item = self._q.get(timeout=GET_POLL_SEC)
                break
            except queue.Empty:
                if (self._external_stop is not None
                        and self._external_stop.is_set()):
                    raise StopIteration  # preemption: stop waiting for data
                if self._thread.is_alive():
                    continue  # slow source, live producer: keep waiting
                try:  # producer exited; take anything it managed to leave
                    item = self._q.get_nowait()
                    break
                except queue.Empty:
                    raise RuntimeError(
                        "BackgroundIterator producer thread died without "
                        "yielding a result or an error") from None
        if item is StopIteration:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item


def to_global_arrays(batch: Batch, sharding) -> Tuple[jax.Array, jax.Array]:
    """Assemble a global (mesh-sharded) array from this process's local
    batch shard."""
    images, labels = batch
    gi = jax.make_array_from_process_local_data(sharding, images)
    gl = jax.make_array_from_process_local_data(sharding, labels)
    return gi, gl


def device_prefetch(host_iter: Iterator[Batch], sharding,
                    depth: int = 2) -> Iterator[Tuple[jax.Array, jax.Array]]:
    """Keep ``depth`` batches in flight on device so H2D transfer overlaps
    with the previous step's compute (the reference's ``prefetch(2*batch)``,
    resnet_cifar_train.py:233, moved to the device edge)."""
    buf: collections.deque = collections.deque()
    it = iter(host_iter)
    try:
        while len(buf) < depth:
            buf.append(to_global_arrays(next(it), sharding))
    except StopIteration:
        pass
    while buf:
        nxt = buf.popleft()
        try:
            buf.append(to_global_arrays(next(it), sharding))
        except StopIteration:
            pass
        yield nxt


def staged_superbatch_prefetch(host_iter: Iterator[Batch], stage_sharding,
                               stage: int = 4, depth: int = 2
                               ) -> Iterator[Tuple[jax.Array, jax.Array, int]]:
    """Transfer ``stage`` batches per host→device copy and yield the whole
    ``(k, B, ...)`` superbatch plus its true length ``k`` — the consumer
    (train/loop.py) fuses the k steps into one dispatch
    (device_data.compile_staged_stream_steps). A final partial stage of a
    finite stream is yielded with its true k."""

    def superbatches():
        it = iter(host_iter)
        while True:
            imgs, labs = [], []
            try:
                while len(imgs) < stage:
                    im, lb = next(it)
                    imgs.append(im)
                    labs.append(lb)
            except StopIteration:
                pass
            if not imgs:
                return
            yield (np.stack(imgs), np.stack(labs))

    buf: collections.deque = collections.deque()
    sb = superbatches()

    def load():
        imgs, labs = next(sb)
        gi = jax.make_array_from_process_local_data(stage_sharding, imgs)
        gl = jax.make_array_from_process_local_data(stage_sharding, labs)
        return gi, gl, len(imgs)

    try:
        while len(buf) < depth:
            buf.append(load())
    except StopIteration:
        pass
    while buf:
        nxt = buf.popleft()
        try:
            buf.append(load())  # refill before yielding the current stage
        except StopIteration:
            pass
        yield nxt


class DoubleBufferedH2D:
    """Double-buffered staged H2D prefetch — the transfer-overlap form of
    :func:`staged_superbatch_prefetch`.

    The generator form assembles and transfers each superbatch on the
    CONSUMER thread between dispatches: with async PJRT transfers the
    copy usually overlaps compute anyway, but the np.stack assembly and
    the transfer *enqueue* serialize with dispatch, and nothing measures
    whether the link kept up. This class moves the whole stage onto a
    producer thread and makes the overlap an explicit, gauged contract:

    - the producer assembles the next ``(stage, B, ...)`` superbatch,
      issues its host→device transfer and BLOCKS until the copy lands —
      transfer wall time and bytes are measured per stage;
    - an explicit two-slot device buffer bounds in-flight HBM: one
      superbatch being consumed, one ready/landing — independent of
      ``data.prefetch`` (the queue is clamped to one ready slot; during
      the handoff instant a third superbatch can be live transiently:
      consuming + ready + just-landed-blocked-on-put). The consumer
      dropping its reference at the stage end releases the slot the next
      transfer fills (donated between stages via buffer refcount);
    - ``stats()`` reports interval ``h2d_bytes_per_sec`` and
      ``h2d_overlap_frac`` (fraction of transfer wall time hidden under
      consumer compute: 1 − consumer-blocked-time ∕ transfer-time,
      clamped to [0, 1]) — the loop publishes both as gauges;
    - ``drain_transfers()`` hands finished (start, end, bytes, k)
      records to the loop, which lays them on the trace-export transfer
      lane (``h2d_transfer`` spans; docs/OBSERVABILITY.md).

    Superbatch CONTENTS are identical to the generator form (same stream,
    same np.stack), so staged-vs-unstaged loss bit-equality is preserved
    (tests/test_data.py). The assembly look-back into a shm-ring source
    is unchanged (one superbatch: ``stage`` draws, copied out at stack
    time), so the engine's ``hold = stage + 1`` contract still covers the
    extra in-flight transfer.

    Consumer contract matches the generator plus ``close()``/``stats()``:
    iterate for ``(gi, gl, k)``; a producer error re-raises at the
    consumer; ``external_stop`` ends iteration within ~GET_POLL_SEC even
    mid-stall (the preemption contract BackgroundIterator documents).
    """

    _DONE = object()

    def __init__(self, host_iter: Iterator[Batch], stage_sharding,
                 stage: int = 4, depth: int = 2,
                 external_stop: Optional[threading.Event] = None):
        self._time = time.perf_counter
        self._stage = max(1, int(stage))
        self._sharding = stage_sharding
        self._it = iter(host_iter)
        # Two-slot contract: ONE ready superbatch in the queue, one in
        # flight at the producer — the staging-HBM bound must not scale
        # with data.prefetch (``depth`` is accepted for signature parity
        # with the generator form but deliberately does not widen the
        # queue: at ImageNet scale each extra slot is hundreds of MB of
        # device memory behind a knob documented as host-side buffering).
        del depth
        self._q: queue.Queue = queue.Queue(maxsize=1)
        self._stop = threading.Event()
        self._external_stop = external_stop
        self._lock = threading.Lock()
        self._events = []           # finished transfers: (t0, t1, bytes, k)
        self._bytes = 0             # interval accumulators for stats()
        self._transfer_sec = 0.0
        self._wait_sec = 0.0
        self._last_stats = self._time()
        self._thread = threading.Thread(target=self._fill, daemon=True,
                                        name="tpu-resnet-h2d")
        self._thread.start()

    # ------------------------------------------------------------ producer
    def _assemble(self):
        imgs, labs = [], []
        while len(imgs) < self._stage:
            try:
                im, lb = next(self._it)
            except StopIteration:
                break
            imgs.append(im)
            labs.append(lb)
        if not imgs:
            return None
        return np.stack(imgs), np.stack(labs)

    def _fill(self):
        try:
            while not self._stop.is_set():
                stacked = self._assemble()
                if stacked is None:
                    self._put(self._DONE)
                    return
                imgs, labs = stacked
                t0 = self._time()
                gi = jax.make_array_from_process_local_data(
                    self._sharding, imgs)
                gl = jax.make_array_from_process_local_data(
                    self._sharding, labs)
                # Land the copy HERE, on the producer: the consumer never
                # blocks on an in-flight transfer, and (t1 - t0) is the
                # honest transfer wall time this thread observed.
                jax.block_until_ready((gi, gl))
                t1 = self._time()
                nbytes = imgs.nbytes + labs.nbytes
                with self._lock:
                    self._events.append((t0, t1, nbytes, len(imgs)))
                    self._bytes += nbytes
                    self._transfer_sec += t1 - t0
                if not self._put((gi, gl, len(imgs))):
                    return
        except Exception as e:  # surface loader/transfer errors in order
            try:
                self._q.put(e, timeout=ERROR_PUT_TIMEOUT_SEC)
            except queue.Full:
                self._drain()
                try:
                    self._q.put_nowait(e)
                except queue.Full:  # pragma: no cover - sole producer
                    pass

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _drain(self):
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                return

    # ------------------------------------------------------------ consumer
    def __iter__(self):
        return self

    def __next__(self):
        t0 = self._time()
        while True:
            try:
                item = self._q.get(timeout=GET_POLL_SEC)
                break
            except queue.Empty:
                if (self._external_stop is not None
                        and self._external_stop.is_set()):
                    raise StopIteration  # preemption: stop waiting
                if self._thread.is_alive():
                    continue
                try:
                    item = self._q.get_nowait()
                    break
                except queue.Empty:
                    raise RuntimeError(
                        "DoubleBufferedH2D producer thread died without "
                        "yielding a result or an error") from None
        with self._lock:
            self._wait_sec += self._time() - t0
        if item is self._DONE:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item

    def close(self) -> None:
        """Release the producer thread and the buffered device slots.
        Idempotent; sits in the loop's closer chain like the engine."""
        self._stop.set()
        self._drain()
        self._thread.join(timeout=5)

    # --------------------------------------------------------------- stats
    def drain_transfers(self):
        """Finished transfer records since the last drain, as
        ``(start, end, bytes, k)`` in this host's perf_counter domain
        plus the matching wall-clock offset — the loop converts them to
        ``h2d_transfer`` spans (single-threaded span writer by design)."""
        with self._lock:
            events, self._events = self._events, []
        offset = time.time() - self._time()
        return [(t0 + offset, t1 + offset, nbytes, k)
                for t0, t1, nbytes, k in events]

    def stats(self) -> dict:
        """Interval gauges since the previous stats() call (the loop
        calls it at log boundaries, same cadence as the engine's)."""
        now = self._time()
        with self._lock:
            dt = max(now - self._last_stats, 1e-9)
            rate = self._bytes / dt
            overlap = (max(0.0, 1.0 - self._wait_sec / self._transfer_sec)
                       if self._transfer_sec > 0 else 0.0)
            self._bytes = 0
            self._transfer_sec = 0.0
            self._wait_sec = 0.0
            self._last_stats = now
        return {"h2d_bytes_per_sec": round(rate, 1),
                "h2d_overlap_frac": round(min(overlap, 1.0), 6)}


def staged_device_prefetch(host_iter: Iterator[Batch], stage_sharding,
                           stage: int = 4, depth: int = 2
                           ) -> Iterator[Tuple[jax.Array, jax.Array]]:
    """Like ``device_prefetch`` but transfers ``stage`` batches per
    host→device copy and cuts per-step batches on-device.

    Each transfer pays a fixed command/latency cost on top of bandwidth;
    when the interconnect to the device is latency-bound (remote-attached
    TPU, small batches) per-batch transfers serialize against compute.
    Staging k batches into one ``(k, B, ...)`` array amortizes that cost
    k-fold; the per-step slice is one cheap on-device ``dynamic_slice``.
    ``stage_sharding`` must shard the *batch* axis, i.e. ``P(None,
    'data')`` over axis 1. A final partial stage (end of a finite stream)
    is transferred with its true length.

    Thin per-step view over ``staged_superbatch_prefetch`` — the training
    loop consumes the superbatches directly (fused multi-step dispatch);
    this form serves consumers that want a per-batch iterator."""
    take = jax.jit(
        lambda a, i: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False))
    for gi, gl, k in staged_superbatch_prefetch(host_iter, stage_sharding,
                                                stage=stage, depth=depth):
        for i in range(k):
            yield take(gi, i), take(gl, i)
