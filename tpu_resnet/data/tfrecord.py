"""Self-contained TFRecord + tf.train.Example codec (numpy/stdlib only).

The reference's ImageNet path reads Inception-style TFRecord shards through
TF's C++ tf.data stack (reference resnet_imagenet_train.py:117-158: parse
``image/encoded``, ``image/class/label`` from serialized Examples;
:105-114: 1024 train / 128 validation shards). This framework keeps the
wire formats — so existing datasets work unchanged — but owns the decode:

- TFRecord framing: ``uint64 length | uint32 masked_crc32c(length) |
  bytes data | uint32 masked_crc32c(data)``.
- Masked CRC: ``((crc >> 15 | crc << 17) + 0xa282ead8) & 0xffffffff`` over
  the Castagnoli (CRC-32C) polynomial.
- ``Example`` protobuf subset: Example{features=1} → Features{feature map=1}
  → entries key=1/value=2 → Feature{bytes_list=1|float_list=2|int64_list=3}.

A C++ fast path (tpu_resnet/native) accelerates bulk record splitting; this
module is the always-available reference implementation and the writer used
by tests and dataset tooling.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Iterator, List, Union

import numpy as np

# ------------------------------------------------------------------ crc32c
_CRC32C_POLY = 0x82F63B78


def _make_table() -> np.ndarray:
    table = np.zeros(256, np.uint32)
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (_CRC32C_POLY if crc & 1 else 0)
        table[i] = crc
    return table


_TABLE = _make_table()


def crc32c(data: bytes) -> int:
    # Hot-path CRC lives in the native reader; this is the writer/fallback.
    table = _TABLE
    crc_val = 0xFFFFFFFF
    for b in data:
        crc_val = (crc_val >> 8) ^ int(table[(crc_val ^ b) & 0xFF])
    return crc_val ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def masked_crc32c_fast(data: bytes) -> int:
    """masked_crc32c through the native C table when built (~200x the
    python loop) — for verification on hot read paths."""
    try:
        from tpu_resnet.native import available, loader
        if available():
            crc = loader.crc32c(data)
            return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF
    except Exception:
        pass
    return masked_crc32c(data)


# ----------------------------------------------------------- record framing
def write_records(path: str, records: List[bytes]) -> None:
    with open(path, "wb") as f:
        for rec in records:
            length = struct.pack("<Q", len(rec))
            f.write(length)
            f.write(struct.pack("<I", masked_crc32c(length)))
            f.write(rec)
            f.write(struct.pack("<I", masked_crc32c(rec)))


def record_index(path: str) -> List[tuple]:
    """[(payload_offset, payload_length)] for every record — a seek-only
    framing scan that reads 12 header bytes per record and skips payloads,
    so indexing a shard costs header IO only. Powers the resume
    fast-forward (data/imagenet.py): record counts and random access
    without decoding anything."""
    out = []
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        pos = 0
        while pos < size:
            f.seek(pos)
            header = f.read(12)
            if len(header) < 12:
                raise ValueError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            out.append((pos + 12, length))
            pos += 12 + length + 4
    if pos != size:
        raise ValueError(f"{path}: trailing bytes after last record")
    return out


def read_records(path: str, verify_crc: bool = False) -> Iterator[bytes]:
    """Stream raw record payloads from a TFRecord file."""
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise ValueError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            if verify_crc:
                (want,) = struct.unpack("<I", header[8:12])
                if masked_crc32c(header[:8]) != want:
                    raise ValueError(f"{path}: length CRC mismatch")
            data = f.read(length)
            if len(data) < length:
                raise ValueError(f"{path}: truncated record body")
            footer = f.read(4)
            if verify_crc:
                (want,) = struct.unpack("<I", footer)
                if masked_crc32c(data) != want:
                    raise ValueError(f"{path}: data CRC mismatch")
            yield data


# ------------------------------------------------------- protobuf wire codec
def _encode_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _decode_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _tag(field: int, wire: int) -> bytes:
    return _encode_varint((field << 3) | wire)


def _len_delimited(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _encode_varint(len(payload)) + payload


FeatureValue = Union[List[bytes], List[int], List[float]]


def encode_example(features: Dict[str, FeatureValue]) -> bytes:
    """Dict → serialized tf.train.Example. Value type picks the Feature kind:
    bytes → bytes_list, int → int64_list, float → float_list."""
    feat_entries = b""
    for key, values in features.items():
        if not isinstance(values, (list, tuple)):
            values = [values]
        if all(isinstance(v, bytes) for v in values):
            inner = b"".join(_len_delimited(1, v) for v in values)
            feature = _len_delimited(1, inner)
        elif all(isinstance(v, (int, np.integer)) for v in values):
            inner = b""
            for v in values:
                inner += _tag(1, 0) + _encode_varint(int(v) & (2**64 - 1))
            feature = _len_delimited(3, inner)
        elif all(isinstance(v, (float, np.floating)) for v in values):
            # float_list: packed floats under field 1
            packed = np.asarray(values, "<f4").tobytes()
            feature = _len_delimited(2, _len_delimited(1, packed))
        else:
            raise TypeError(f"mixed/unsupported feature values for {key!r}")
        entry = _len_delimited(1, key.encode()) + _len_delimited(2, feature)
        feat_entries += _len_delimited(1, entry)
    return _len_delimited(1, feat_entries)


def _parse_feature(buf: bytes):
    """Feature message → python list (bytes/ints/floats)."""
    pos = 0
    while pos < len(buf):
        tag, pos = _decode_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        ln, pos = _decode_varint(buf, pos)
        payload = buf[pos:pos + ln]
        pos += ln
        if field == 1:  # BytesList
            out, p = [], 0
            while p < len(payload):
                t, p = _decode_varint(payload, p)
                l2, p = _decode_varint(payload, p)
                out.append(payload[p:p + l2])
                p += l2
            return out
        if field == 2:  # FloatList (packed under field 1)
            out, p = [], 0
            while p < len(payload):
                t, p = _decode_varint(payload, p)
                f2, w2 = t >> 3, t & 7
                if w2 == 2:
                    l2, p = _decode_varint(payload, p)
                    out.extend(np.frombuffer(payload[p:p + l2],
                                             "<f4").tolist())
                    p += l2
                else:  # unpacked single float
                    out.append(np.frombuffer(payload[p:p + 4],
                                             "<f4")[0].item())
                    p += 4
            return out
        if field == 3:  # Int64List
            out, p = [], 0
            while p < len(payload):
                t, p = _decode_varint(payload, p)
                w2 = t & 7
                if w2 == 2:  # packed
                    l2, p = _decode_varint(payload, p)
                    end = p + l2
                    while p < end:
                        v, p = _decode_varint(payload, p)
                        out.append(v - 2**64 if v >= 2**63 else v)
                else:
                    v, p = _decode_varint(payload, p)
                    out.append(v - 2**64 if v >= 2**63 else v)
            return out
    return []


def parse_example(serialized: bytes) -> Dict[str, list]:
    """Serialized Example → {key: list-of-values} for the subset of the wire
    format Inception/ImageNet shards use."""
    out: Dict[str, list] = {}
    pos = 0
    buf = serialized
    while pos < len(buf):
        tag, pos = _decode_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire != 2:
            raise ValueError(f"unexpected wire type {wire} at top level")
        ln, pos = _decode_varint(buf, pos)
        features_buf = buf[pos:pos + ln]
        pos += ln
        if field != 1:
            continue
        fpos = 0
        while fpos < len(features_buf):
            ftag, fpos = _decode_varint(features_buf, fpos)
            fln, fpos = _decode_varint(features_buf, fpos)
            entry = features_buf[fpos:fpos + fln]
            fpos += fln
            # map entry: key=1 (string), value=2 (Feature)
            key = None
            value: list = []
            epos = 0
            while epos < len(entry):
                etag, epos = _decode_varint(entry, epos)
                eln, epos = _decode_varint(entry, epos)
                payload = entry[epos:epos + eln]
                epos += eln
                if etag >> 3 == 1:
                    key = payload.decode()
                elif etag >> 3 == 2:
                    value = _parse_feature(payload)
            if key is not None:
                out[key] = value
    return out
