"""Multiprocess (or thread) host data engine over a shared-memory ring.

BENCH_r04 measured the input wall directly: one v5e chip consumes ~3,032
images/sec at batch 128 while the thread-pool host decode tops out at
~372 — the GIL serializes everything around the JPEG decode (parse,
crop bookkeeping, batch assembly), so adding threads stopped paying long
before the chip was fed. This engine is the classic answer (the tf.data
multi-worker prefetch architecture, arxiv 1605.08695; the MLPerf input
bottleneck, arxiv 1909.09756) rebuilt for the explicit pipeline:

- The parent pre-slices the deterministic record stream into **work
  orders**: ``(seq, slot, count, entries)`` where ``entries`` are
  ``(file_idx, offset, length)`` record positions. No worker ever touches
  a shared iterator — batch ``seq`` has the same contents for 1, 2 or N
  workers, and a resumed run re-derives the identical orders (the
  determinism fix the old thread pool acknowledged it lacked).
- N workers — OS **processes** (mode="process", GIL-free) or threads
  (mode="thread", the CIFAR-cheap default) — pull orders from a task
  queue, read+decode the records, and write pixels **directly into** the
  preallocated ring slot (tpu_resnet/data/shm_ring.py): zero pickle,
  zero per-batch ``images.copy()``. Only ``(seq, slot, count)`` tuples
  cross the result queue.
- The consumer (``__next__``) reassembles strictly in ``seq`` order,
  holding out-of-order completions aside, and hands out **views** into
  the ring. A slot is recycled ``hold`` batches after it was yielded, so
  the consumer contract is: a yielded batch stays valid for the next
  ``hold - 1`` calls (the training loop passes ``hold = transfer_stage
  + 1``, covering the staged superbatch assembly's look-back).

Failure semantics: a worker that dies (segfault, OOM-kill) surfaces as a
RuntimeError at the consumer within one poll interval — the training
loop's supervise/watchdog stack sees a loud crash, never a silent hang.
A decode error inside a worker is reported against its ``seq`` and
raised when that batch's turn comes, preserving ordering. ``close()``
(idempotent, wired into the train loop's closer chain and the engine's
own end-of-stream/error paths) stops workers and unlinks the shared
memory; an ``atexit`` backstop in shm_ring covers paths that die harder.

Per-image randomness is keyed ``(seed, _DECODE_STREAM, seq, j)`` — a pure
function of the batch sequence number and the position in the batch, so
worker count, scheduling and resume cannot change a single crop.
"""

from __future__ import annotations

# check: disable-file=unguarded-shared-write
# Justification: the engine is single-consumer BY CONTRACT (module
# docstring): every consumer-side field (_next_yield, _ready, _free,
# _leased, _closed, _broken, the stats counters) is touched only from
# the loop thread that iterates it — the same thread that runs close()
# in the loop's closer chain. Workers communicate exclusively through
# the task/result queues and the shared decode counter (its own lock);
# __del__ is a GC backstop onto an idempotent close(). The per-class
# thread-context graph cannot see that contract, so the rule is
# disabled file-wide rather than sprinkling per-line pragmas over
# single-threaded state.

import os
import queue
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from tpu_resnet.data.shm_ring import ArrayRing, ShmRing

# RNG stream tag separating per-image decode draws from every other
# (seed, ...)-keyed stream in the codebase.
_DECODE_STREAM = 0x1DEC0DE

# Consumer poll interval between worker-liveness checks (module level so
# tests can tighten it, mirroring pipeline.GET_POLL_SEC).
RESULT_POLL_SEC = 0.5

# Open shard handles kept per worker (LRU) — sized to cover the set of
# files the shuffle-buffer window interleaves.
_FH_CACHE_SIZE = 64

Entry = Tuple[int, int, int]  # (file_idx, payload_offset, payload_length)


# --------------------------------------------------------------- decode core
def _decode_order(ring, slot: int, seq: int, count: int,
                  entries: Sequence[Entry], files: Sequence[str],
                  params: dict, fh_cache: dict) -> None:
    """Fill ring slot ``slot`` from record positions — the shared inner
    loop of both worker kinds. ``params`` carries the decode knobs
    (seed/train/resize/verify/use_native/image_size)."""
    from tpu_resnet.data import tfrecord
    from tpu_resnet.data.imagenet import decode_and_crop, parse_record

    images = ring.images(slot)
    labels = ring.labels(slot)
    seed = params["seed"]
    verify = params["verify_records"]
    for j, (fi, off, length) in enumerate(entries):
        path = files[fi]
        fh = fh_cache.get(path)
        if fh is not None:
            fh_cache.pop(path)      # re-insert below: LRU recency order
        else:
            # Bounded per-worker LRU of open handles: shuffled train
            # orders interleave every shard inside the shuffle-buffer
            # window (~40 files at the default 50k buffer), so a
            # single-handle cache would reopen a file for almost every
            # record — ruinous on network-mounted data_dirs where open()
            # costs milliseconds. 64 comfortably covers the window.
            if len(fh_cache) >= _FH_CACHE_SIZE:
                fh_cache.pop(next(iter(fh_cache))).close()
            fh = open(path, "rb")
        fh_cache[path] = fh
        fh.seek(off)
        payload = fh.read(length)
        if verify:
            (want,) = np.frombuffer(fh.read(4), "<u4")
            if tfrecord.masked_crc32c_fast(payload) != int(want):
                raise ValueError(f"{path}: record at offset {off} CRC "
                                 "mismatch")
        jpeg, label = parse_record(payload)
        rng = np.random.default_rng((seed, _DECODE_STREAM, seq, j))
        images[j] = decode_and_crop(
            jpeg, params["train"], rng,
            params["resize_min"], params["resize_max"],
            eval_resize=params["eval_resize"],
            out_size=params["image_size"],
            use_native=params["use_native"])
        labels[j] = label - 1  # 1-based shard labels → 0-based
    if count < ring.local_batch:  # finite stream's final partial batch:
        images[count:] = 0        # zero-pad, labels=-1 (eval contract)
        labels[count:] = -1


def _worker_loop(ring, files, params, task_q, result_q, should_abort,
                 decoded_add) -> None:
    """Pull orders until a ``None`` sentinel or abort; report per-order.
    The bounded get keeps the abort check live even when the parent can
    no longer send sentinels (crashed consumer, SIGKILLed trainer)."""
    fh_cache: dict = {}
    try:
        while True:
            try:
                order = task_q.get(timeout=1.0)
            except queue.Empty:
                if should_abort():
                    break
                continue
            if order is None or should_abort():
                break
            seq, slot, count, entries = order
            try:
                _decode_order(ring, slot, seq, count, entries, files,
                              params, fh_cache)
            except Exception as e:  # reported against its seq, in order
                result_q.put(("error", seq, slot,
                              f"{type(e).__name__}: {e}"))
                continue
            decoded_add(count)
            result_q.put(("ok", seq, slot, count))
    finally:
        for fh in fh_cache.values():
            fh.close()


def _process_worker_main(ring_name: str, ring_slots: int, local_batch: int,
                         image_size: int, files, params, task_q, result_q,
                         stop_evt, counter) -> None:
    """Spawn entry point (top-level: must be picklable). Imports stay
    light — numpy/PIL/native loader, never jax."""
    ring = ShmRing(ring_slots, local_batch, image_size, name=ring_name,
                   create=False)
    parent = os.getppid()

    def should_abort():
        # Orphaned worker (parent SIGKILLed: ppid reparents to init) must
        # exit rather than block on the queue forever.
        return stop_evt.is_set() or os.getppid() != parent

    def add(n):
        with counter.get_lock():
            counter.value += n

    try:
        _worker_loop(ring, files, params, task_q, result_q, should_abort,
                     add)
    finally:
        ring.close()


# ------------------------------------------------------------------- engine
class HostDataEngine:
    """Sequence-ordered batch stream over N decode workers and a slot ring.

    ``orders``: iterator of entry-lists (each ≤ ``local_batch`` long);
    finite for eval, infinite for training. Batch ``i`` of the stream is
    assigned ``seq = first_seq + i`` — pass the resume step as
    ``first_seq`` so decode randomness lines up with the uninterrupted
    run.

    Iterator protocol matches BackgroundIterator where it matters to the
    loop: ``close()`` is idempotent and safe mid-stream; a set
    ``external_stop`` event ends iteration within ~RESULT_POLL_SEC even
    while producers are wedged (the preemption hook); producer death
    raises instead of hanging.
    """

    def __init__(self, orders: Iterator[Sequence[Entry]], *,
                 files: Sequence[str], local_batch: int, image_size: int,
                 seed: int = 0, train: bool = True, resize_min: int = 256,
                 resize_max: int = 512, eval_resize: int = 256,
                 verify_records: bool = False, use_native: bool = True,
                 mode: str = "thread", workers: int = 2,
                 ring_slots: int = 0, hold: int = 1, first_seq: int = 0,
                 external_stop: Optional[threading.Event] = None):
        if mode not in ("thread", "process"):
            raise ValueError(f"engine mode must be thread|process: {mode!r}")
        self.mode = mode
        self.workers = max(1, int(workers))
        self.hold = max(1, int(hold))
        # Ring sizing: `hold` slots may be leased to the consumer, and
        # every free slot is a dispatchable work order — the workers'
        # prefetch depth. ~3 orders in flight per worker keeps them fed
        # across the result→recycle→dispatch round trip (measured on the
        # CPU rehearsal box: 1 worker at ring 6 ran at 65% of its ring-12
        # rate — thin rings starve workers, not memory). RAM cost is
        # slots × batch bytes (b128@224 ≈ 19 MB/slot); override with
        # data.ring_slots when that budget matters.
        self.ring_slots = int(ring_slots) or (self.hold + 3 * self.workers
                                              + 2)
        if self.ring_slots < self.hold + 2:
            raise ValueError(
                f"ring_slots={self.ring_slots} too small for hold="
                f"{self.hold}: need >= hold + 2 so a slot is always free "
                "to decode into")
        self.local_batch = int(local_batch)
        self._orders = iter(orders)
        self._files = list(files)
        self._params = dict(seed=seed, train=train, resize_min=resize_min,
                            resize_max=resize_max, eval_resize=eval_resize,
                            verify_records=verify_records,
                            use_native=use_native, image_size=image_size)
        self._external_stop = external_stop
        self._next_dispatch = first_seq
        self._next_yield = first_seq
        self._orders_done = False
        self._ready: Dict[int, tuple] = {}
        self._leased: List[Tuple[int, int]] = []  # (seq, slot) fifo
        self._free = list(range(self.ring_slots))
        self._closed = False
        self._broken: Optional[str] = None
        # stats (consumer-thread updated; decoded counter worker-shared)
        self._consumed_images = 0
        self._stats_wall = time.monotonic()
        self._stats_decoded = 0

        if mode == "process":
            import multiprocessing as mp

            ctx = mp.get_context("spawn")  # fork-unsafe after jax init
            self._ring = ShmRing(self.ring_slots, self.local_batch,
                                 self._params["image_size"])
            self._task_q = ctx.Queue()
            self._result_q = ctx.Queue()
            self._stop_evt = ctx.Event()
            self._counter = ctx.Value("q", 0)
            self._procs = [
                ctx.Process(
                    target=_process_worker_main,
                    args=(self._ring.name, self.ring_slots,
                          self.local_batch, self._params["image_size"],
                          self._files, self._params, self._task_q,
                          self._result_q, self._stop_evt, self._counter),
                    daemon=True, name=f"tpures-decode-{i}")
                for i in range(self.workers)]
            for p in self._procs:
                p.start()
            self._threads = []
        else:
            self._ring = ArrayRing(self.ring_slots, self.local_batch,
                                   self._params["image_size"])
            self._task_q = queue.Queue()
            self._result_q = queue.Queue()
            self._stop_evt = threading.Event()
            self._counter_lock = threading.Lock()
            self._counter_val = 0

            def add(n):
                with self._counter_lock:
                    self._counter_val += n

            self._threads = [
                threading.Thread(
                    target=_worker_loop,
                    args=(self._ring, self._files, self._params,
                          self._task_q, self._result_q,
                          self._stop_evt.is_set, add),
                    daemon=True, name=f"tpures-decode-{i}")
                for i in range(self.workers)]
            for t in self._threads:
                t.start()
            self._procs = []
        self._pump()

    # ------------------------------------------------------------ dispatch
    def _pump(self) -> None:
        """Hand out work while free slots remain."""
        while self._free and not self._orders_done:
            try:
                entries = next(self._orders)
            except StopIteration:
                self._orders_done = True
                break
            slot = self._free.pop()
            self._task_q.put((self._next_dispatch, slot, len(entries),
                              list(entries)))
            self._next_dispatch += 1

    def _decoded_total(self) -> int:
        if self.mode == "process":
            return int(self._counter.value)
        with self._counter_lock:
            return self._counter_val

    def _check_workers(self) -> None:
        for p in self._procs:
            if not p.is_alive() and not self._stop_evt.is_set():
                raise RuntimeError(
                    f"data engine worker {p.name} died (exitcode "
                    f"{p.exitcode}) — host decode cannot continue")
        for t in self._threads:
            if not t.is_alive() and not self._stop_evt.is_set():
                raise RuntimeError(
                    f"data engine worker thread {t.name} died")

    # ------------------------------------------------------------ consume
    def __iter__(self):
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._closed or self._broken:
            raise StopIteration
        # Recycle slots that have aged out of the hold window; their
        # views are now reusable decode targets.
        horizon = self._next_yield - self.hold
        while self._leased and self._leased[0][0] < horizon:
            self._free.append(self._leased.pop(0)[1])
        self._pump()
        seq = self._next_yield
        while seq not in self._ready:
            if self._orders_done and seq >= self._next_dispatch:
                self.close()  # finite stream fully drained
                raise StopIteration
            if (self._external_stop is not None
                    and self._external_stop.is_set()):
                raise StopIteration  # preemption: stop waiting for data
            try:
                kind, rseq, slot, info = self._result_q.get(
                    timeout=RESULT_POLL_SEC)
            except queue.Empty:
                try:
                    self._check_workers()
                except RuntimeError:
                    self.close()
                    raise
                continue
            self._ready[rseq] = (kind, slot, info)
        kind, slot, info = self._ready.pop(seq)
        self._next_yield += 1
        if kind == "error":
            self._broken = str(info)
            self.close()
            raise RuntimeError(f"data engine decode failed at batch "
                               f"{seq}: {info}")
        self._leased.append((seq, slot))
        self._consumed_images += info
        return self._ring.images(slot), self._ring.labels(slot)

    # -------------------------------------------------------------- stats
    def stats(self) -> Dict[str, float]:
        """Telemetry snapshot; the decode rate covers the interval since
        the previous stats() call (the loop calls it at log boundaries)."""
        now = time.monotonic()
        decoded = self._decoded_total()
        dt = max(now - self._stats_wall, 1e-9)
        rate = (decoded - self._stats_decoded) / dt
        self._stats_wall, self._stats_decoded = now, decoded
        # Occupancy = decoded batches the consumer hasn't taken yet:
        # out-of-order completions stashed in _ready PLUS results still
        # queued (a device-bound run drains each result on first get, so
        # _ready alone would read 0 exactly when the ring is fullest).
        try:
            queued = self._result_q.qsize()
        except (NotImplementedError, OSError):  # qsize absent on some
            queued = 0                          # platforms (macOS mp)
        return {
            "data_ring_occupancy": float(len(self._ready) + queued),
            "data_ring_slots": float(self.ring_slots),
            "data_decode_images_per_sec": round(rate, 1),
            # Next work-order sequence the consumer will yield — batch
            # contents are a pure function of (seed, seq), so this gauge
            # is the deterministic-stream position. Across an elastic
            # reshape (resilience/elastic.py) the resumed run's first
            # logged value must equal resume_step + batches consumed:
            # the work-order slicing depends only on the per-process
            # batch (global batch is the invariant), never the mesh.
            "data_stream_seq": float(self._next_yield),
        }

    # -------------------------------------------------------------- close
    def close(self) -> None:
        """Stop workers and unlink the shared memory. Idempotent; sits in
        the train loop's closer chain and fires on end-of-stream/error."""
        if self._closed:
            return
        self._closed = True
        self._stop_evt.set()
        for _ in range(self.workers):  # one sentinel per worker
            self._task_q.put(None)
        deadline = time.monotonic() + 5.0
        for w in self._procs + self._threads:
            w.join(timeout=max(0.1, deadline - time.monotonic()))
        for p in self._procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=2.0)
        if self.mode == "process":
            # Unblock mp.Queue feeder threads so interpreter exit can't
            # hang on unflushed queue buffers.
            for q in (self._task_q, self._result_q):
                try:
                    q.cancel_join_thread()
                    q.close()
                except (OSError, AttributeError):
                    pass
        self._ready.clear()
        self._leased.clear()
        self._ring.unlink()

    def __del__(self):  # abandoned-iterator hygiene; close() is the API
        try:
            self.close()
        except Exception:
            pass


# ------------------------------------------------------------ decode probe
def synthetic_photo_jpeg(size=(640, 480), quality=90, rng=None,
                         freqs=(8.0, 6.0)) -> bytes:
    """A photo-like test JPEG: smooth structure + mild noise compresses
    ~10:1 like real ImageNet photos (uniform noise is the pathological
    ~1.5:1 worst case that hides every decode-path win). Shared premise
    for bench.py's host_decode section, tools/input_edge.py and
    ``doctor --data-bench``."""
    import io

    from PIL import Image

    if rng is None:
        rng = np.random.default_rng(0)
    xs = np.linspace(0, freqs[0] * np.pi, size[0])
    ys = np.linspace(0, freqs[1] * np.pi, size[1])
    base = (np.sin(xs)[None, :, None] * np.cos(ys)[:, None, None] * 0.5
            + 0.5) * 255
    arr = (base + rng.integers(0, 30, (size[1], size[0], 3))).clip(
        0, 255).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, "JPEG", quality=quality)
    return buf.getvalue()


def _cycled_orders(n_records: int, local_batch: int):
    """Infinite order stream cycling over one probe shard's records."""
    pos = 0
    while True:
        idxs = [(i % n_records) for i in range(pos, pos + local_batch)]
        pos = (pos + local_batch) % n_records
        yield idxs


def decode_scaling_probe(proc_counts: Sequence[int] = (1, 0),
                         seconds: float = 4.0, local_batch: int = 32,
                         image_size: int = 224, n_records: int = 48,
                         warmup_batches: int = 2) -> dict:
    """Decode-throughput scaling probe: images/sec through the process
    engine at each worker count, plus a single-process inline baseline —
    the ~20s answer to "is this host chip-bound or host-bound" without a
    full bench run. A ``0`` in ``proc_counts`` means ``os.cpu_count()``.

    Reports ``implied_max_steps_per_sec_b128``: the training steps/sec a
    host decoding at the best measured rate could sustain at global batch
    128 — directly comparable to the bench's step-rate entries.
    """
    import tempfile

    from tpu_resnet.data import tfrecord
    from tpu_resnet.data.imagenet import decode_and_crop

    cpu = os.cpu_count() or 1
    # The 0 sentinel caps at 8 workers: a TPU-VM host reports 200+ vCPUs
    # and a per-vCPU spawn sweep would turn the ~20s probe into minutes
    # of process churn; 8 matches the bench curve's cap and is enough to
    # show whether scaling headroom exists.
    counts = sorted({(c if c > 0 else min(8, cpu)) for c in proc_counts})
    rng = np.random.default_rng(0)
    jpeg_bytes = [synthetic_photo_jpeg(rng=rng) for _ in range(4)]
    out = {"cpu_count": cpu, "local_batch": local_batch,
           "jpeg_kind": "synthetic_photo_640x480"}

    # Inline baseline: raw decode_and_crop in this process, no engine.
    d_rng = np.random.default_rng(1)
    decode_and_crop(jpeg_bytes[0], True, d_rng, out_size=image_size)
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < min(seconds, 3.0):
        decode_and_crop(jpeg_bytes[n % 4], True, d_rng,
                        out_size=image_size)
        n += 1
    base_rate = n / (time.perf_counter() - t0)
    out["single_process_images_per_sec"] = round(base_rate, 1)

    with tempfile.TemporaryDirectory(prefix="tpures_databench_") as d:
        shard = os.path.join(d, "probe-shard")
        records = [tfrecord.encode_example({
            "image/encoded": [jpeg_bytes[i % 4]],
            "image/class/label": [1 + (i % 1000)],
        }) for i in range(n_records)]
        tfrecord.write_records(shard, records)
        index = tfrecord.record_index(shard)
        scaling = {}
        for nproc in counts:
            orders = ([(0,) + index[i] for i in idxs]
                      for idxs in _cycled_orders(len(index), local_batch))
            eng = HostDataEngine(
                orders, files=[shard], local_batch=local_batch,
                image_size=image_size, seed=0, train=True,
                mode="process", workers=nproc, hold=1)
            try:
                for _ in range(warmup_batches):  # absorb spawn + first IO
                    next(eng)
                t0 = time.perf_counter()
                images = 0
                while time.perf_counter() - t0 < seconds:
                    next(eng)
                    images += local_batch
                scaling[str(nproc)] = round(
                    images / (time.perf_counter() - t0), 1)
            finally:
                eng.close()
        out["engine_images_per_sec_by_procs"] = scaling
    best = max(scaling.values()) if scaling else base_rate
    out["best_images_per_sec"] = best
    out["scaling_vs_single_process"] = round(best / max(base_rate, 1e-9), 2)
    out["implied_max_steps_per_sec_b128"] = round(best / 128.0, 2)
    return out
