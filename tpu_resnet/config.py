"""Typed run configuration — replaces the reference's per-script flag jungle.

The reference re-declares ~60 ``tf.app.flags`` in every entry script and
splits hyperparameters across four places: flags, the ``HParams`` namedtuple
(reference resnet_model.py:36-39), LR schedules embedded in session hooks
(resnet_cifar_train.py:291-311), and module constants
(resnet_cifar_train.py:98-100).  Here everything lives in one typed,
serializable tree of dataclasses with a flat ``--section.field=value`` CLI
override syntax and named presets matching the reference's published
configurations (BASELINE.md).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from typing import Any, Mapping, Sequence


@dataclasses.dataclass
class DataConfig:
    """Input pipeline configuration.

    Mirrors the knobs of reference cifar_input.py:25-119 and the tf.data
    ``input_fn`` copies (resnet_cifar_train.py:204-247,
    resnet_imagenet_train.py:161-187) — minus the per-worker-reads-everything
    design: this pipeline shards files/records per host.
    """

    dataset: str = "cifar10"  # cifar10 | cifar100 | imagenet | synthetic
    data_dir: str = ""
    # synthetic only: derive labels from image content (a brightened band)
    # so training must genuinely learn — the no-download stand-in for
    # real-data convergence runs (data/cifar.py::synthetic_data).
    synthetic_learnable: bool = False
    # synthetic+learnable only: "bands" = easy linear-probe task (smoke
    # gates); "freq100" = 100-class frequency-pair task with random phase
    # (augmentation-invariant features required; convergence evidence —
    # see data/cifar.py::synthetic_data).
    synthetic_task: str = "bands"
    # freq100 only: fraction of TRAIN labels resampled uniformly (eval
    # stays clean). Makes the decayed tail of a piecewise LR schedule
    # measurably matter.
    synthetic_label_noise: float = 0.0
    # synthetic only: class count (smoke-test any head size, e.g. the
    # WRN-28-10 CIFAR-100 shape, without the real dataset bytes).
    synthetic_classes: int = 10
    # synthetic only: split sizes (0 = defaults 1024/256). Convergence
    # runs on the freq100 task need real split sizes (e.g. 20k/2k).
    synthetic_train_examples: int = 0
    synthetic_eval_examples: int = 0
    # Number of worker threads in the host loader (reference uses 16 queue
    # threads, cifar_input.py:99-100; and num_parallel_calls=4 tf.data maps).
    num_workers: int = 4
    # Host data engine worker kind for CPU-heavy sources (ImageNet JPEG
    # decode; data/engine.py). "thread" keeps decode in-process — fine
    # when the native GIL-free decoder carries the load, and the only
    # sensible choice for in-memory CIFAR (which bypasses the engine
    # entirely). "process" runs N decode *processes* over a shared-memory
    # ring — the fix when the step breakdown shows data_wait high and
    # host decode is the ceiling (BENCH_r04: one v5e consumes ~3032
    # img/s at b128 while the GIL-bound host decoded ~372).
    engine: str = "thread"  # thread | process
    # Decode worker processes when data.engine=process (0 = num_workers).
    num_decode_procs: int = 0
    # Engine ring slots — batch-sized decode targets preallocated up
    # front (shared memory in process mode). 0 = auto: hold window +
    # 3*workers + 2 (~3 orders in flight per worker; thinner rings
    # starve workers — see engine.py). RAM = slots × batch bytes
    # (b128@224 ≈ 19 MB/slot); hold covers the staged-transfer
    # look-back (transfer_stage + 1).
    ring_slots: int = 0
    # Batches buffered ahead on host + device (prefetch 2x in reference,
    # resnet_cifar_train.py:233).
    prefetch: int = 2
    shuffle_buffer: int = 50_000
    # ImageNet only: VGG-style resize-side jitter bounds for training
    # (vgg_preprocessing.py:306-309) and eval resize side (:330).
    resize_min: int = 256
    resize_max: int = 512
    eval_resize: int = 256
    image_size: int = 0  # 0 = dataset default (32 cifar / 224 imagenet)
    # Use the native C++ loader when the shared library is built.
    use_native_loader: bool = True
    # Verify the masked CRC32C of every TFRecord read. Near-free with the
    # native plane (919 MB/s, r3 bench; the pure-python CRC is ~4 MB/s),
    # so corrupted shards fail loudly instead of feeding garbage JPEGs.
    verify_records: bool = False
    # Device-resident dataset (data/device_data.py): upload the whole
    # training split to HBM once and cut batches on-device — removes all
    # per-step host→device traffic. "auto" enables it for single-process
    # in-memory datasets under ``resident_max_bytes``; "on" forces, "off"
    # always streams through the host pipeline. Measured (v5e, r3,
    # fetch-verified): resident 203.3 st/s vs streaming ≤104.4 on the
    # same CIFAR rn50 b128 step — resident wins wherever it applies.
    device_resident: str = "auto"  # auto | on | off
    resident_max_bytes: int = 2 << 30
    # Streaming path: batches staged per host→device transfer (amortizes
    # per-transfer command latency; per-step batches are cut on-device).
    # 1 = one transfer per batch. Measured sweep (v5e r3, CIFAR rn50
    # b128, bandwidth-bound link): stage 4/8/16 → 88.1/96.4/104.4 st/s;
    # 8 takes most of the amortization at half 16's staging HBM.
    transfer_stage: int = 8
    # Double-buffered H2D prefetch (data/pipeline.py::DoubleBufferedH2D):
    # a producer thread assembles the NEXT staged superbatch and runs its
    # host->device transfer to completion while the loop dispatches
    # compute on the current one — an explicit two-slot device buffer,
    # recycled between stages. Gauges h2d_bytes_per_sec /
    # h2d_overlap_frac and the trace-export transfer lane make the
    # overlap visible (docs/OBSERVABILITY.md). Off = the plain staged
    # generator (transfer serialized with superbatch assembly on the
    # consumer thread). Superbatch CONTENTS are identical either way —
    # loss streams are bit-equal (tests/test_data.py).
    h2d_double_buffer: bool = True

    @property
    def num_classes(self) -> int:
        if self.dataset == "synthetic":
            return self.synthetic_classes
        return {"cifar10": 10, "cifar100": 100,
                "imagenet": 1000}[self.dataset]

    @property
    def default_image_size(self) -> int:
        return 224 if self.dataset == "imagenet" else 32

    @property
    def resolved_image_size(self) -> int:
        return self.image_size or self.default_image_size

    @property
    def train_examples(self) -> int:
        if self.dataset == "synthetic":
            return self.synthetic_train_examples or 1024
        return {"cifar10": 50_000, "cifar100": 50_000,
                "imagenet": 1_281_167}[self.dataset]

    @property
    def eval_examples(self) -> int:
        if self.dataset == "synthetic":
            return self.synthetic_eval_examples or 256
        return {"cifar10": 10_000, "cifar100": 10_000,
                "imagenet": 50_000}[self.dataset]


@dataclasses.dataclass
class ModelConfig:
    """Model selection.

    ``resnet_size`` semantics follow the reference exactly: for CIFAR the
    network is the 6n+2 basic-block ResNet-v2 and size must satisfy
    ``size % 6 == 2`` (resnet_model_official.py:233-236); for ImageNet the
    size must be one of 18/34/50/101/152/200 (resnet_model_official.py:352-358).
    ``width_multiplier`` > 1 turns the CIFAR net into a Wide-ResNet
    (e.g. WRN-28-10 = resnet_size 28, width 10).
    """

    name: str = "resnet"  # resnet | mlp
    resnet_size: int = 50
    width_multiplier: int = 1
    # bf16 compute on the MXU with fp32 params/BN stats. "float32" for
    # bit-exact CPU tests.
    compute_dtype: str = "bfloat16"
    # True (default): BN moments over the global batch — the natural
    # semantics of one auto-sharded SPMD program. False: per-replica BN,
    # the reference's semantics (each worker's update_ops ran on its own
    # batch, resnet_model.py:120-122), compiled via shard_map with
    # explicit pmean of grads/stats. The reference's distributed accuracy
    # gap (README.md:36) is partly this; both are offered so the delta
    # can be measured.
    sync_bn: bool = True
    # Execute the ImageNet 7x7/2 stem as a 4x4 conv over space-to-depth
    # input — identical math and identical parameters/checkpoints, much
    # better MXU utilization (models/resnet.py::SpaceToDepthStem).
    stem_space_to_depth: bool = True
    # Rematerialize residual blocks in backward (activation memory
    # O(depth)): enables batches past the HBM ceiling (e.g. b512 @224)
    # at ~33% block recompute cost. Off by default.
    remat: bool = False
    # Hybrid fused-Pallas block dispatch (CIFAR basic-block nets only):
    # stride-1 identity blocks run as single VMEM-resident Pallas kernels
    # (models/resnet.py::FusedBuildingBlock), transition blocks stay XLA.
    # Checkpoint-compatible with the XLA path (identical param tree).
    # Default OFF pending battery stage 05_fused_block_ab's live A/B
    # (docs/PERF.md "CIFAR is overhead-bound"); single-device validated.
    fused_blocks: bool = False
    # Forward batch tile of the fused kernels (backward tile derives from
    # it); tunable from tools/fused_model_ab.py --batch-tile.
    fused_block_tile: int = 16
    # Fused Pallas conv epilogues (ops/epilogue.py): every BN+ReLU site
    # runs as one VMEM-resident scale-bias-ReLU kernel over the conv
    # output instead of XLA's separate fused loops. "auto": the loop
    # probes each stage shape at startup (ops.probe_model_epilogues) and
    # only shapes with a measured win dispatch to Pallas — unprofitable
    # shapes keep the identical XLA math. "on" forces the kernel
    # everywhere (tests / forced runs); "off" keeps nn.BatchNorm.
    # Multi-chip: supported via the per-replica-BN shard_map path only
    # (model.sync_bn=false), same rule as fused_blocks — the train loop
    # and the config matrix both enforce it (train/step.py
    # check_step_config).
    fused_epilogue: str = "off"  # off | on | auto
    # MLP sanity model (reference logist_model.py:11) hidden units.
    mlp_hidden_units: int = 100


@dataclasses.dataclass
class OptimConfig:
    """Optimizer + schedule.

    Defaults reproduce the reference recipe: momentum 0.9
    (resnet_model.py:96-99), L2 weight decay summed over all trainable
    variables and added to the loss (resnet_model.py:85-86), piecewise LR
    0.1/0.01/0.001/0.0001 at steps 40k/60k/80k for CIFAR
    (resnet_cifar_train.py:302-311) or the Intel-Caffe warmup recipe for
    ImageNet (resnet_imagenet_train.py:236-260).
    """

    optimizer: str = "momentum"  # sgd | momentum
    momentum: float = 0.9
    schedule: str = "cifar_piecewise"  # cifar_piecewise | imagenet_warmup | constant | cosine
    base_lr: float = 0.1
    weight_decay: float = 0.0002  # reference _WEIGHT_DECAY for cifar
    # Reference applies L2 to *all* trainables incl. BN scale/bias
    # (resnet_model.py:85-86 uses tf.trainable_variables()); set False for the
    # modern no-decay-on-BN/bias variant.
    weight_decay_on_bn: bool = True
    label_smoothing: float = 0.0
    # Fused Pallas softmax-xent kernel (tpu_resnet/ops) on TPU backends;
    # the optax chain always serves CPU and label_smoothing != 0.
    # "auto" (default): a compile-time per-shape A/B probe
    # (ops/autotune.py + softmax_xent.ensure_xent_probe) times both
    # lowerings at step-build time and dispatches the measured winner —
    # the BENCH_r04 0.901x regression class auto-falls back to XLA.
    # "on" forces the (retuned, lane-tiled) kernel; "off" forces XLA.
    use_pallas_xent: str = "auto"  # auto | on | off
    # warmup schedule knobs (imagenet_warmup)
    warmup_steps: int = 6240
    warmup_init_lr: float = 0.1
    boundaries: tuple = ()  # override schedule boundaries; () = schedule default
    values: tuple = ()      # override schedule values


@dataclasses.dataclass
class MeshConfig:
    """Device mesh. ``data`` is the only axis needed for reference parity
    (its three distribution modes — PS-sync, async-PS, Horovod — are all data
    parallelism, SURVEY.md §2.3); ``model`` is there so tensor-style sharding
    composes without redesign."""

    data: int = -1   # -1 = all remaining devices
    model: int = 1
    axis_names: tuple = ("data", "model")
    # State partitioning scheme (tpu_resnet/parallel/partition.py — the
    # single owner of every TrainState sharding decision):
    # "replicated" keeps a full parameter + optimizer copy per device
    # (classic data parallelism); "zero1" shards the optimizer slots and
    # the weight update over the data axis via sharding annotations
    # (arXiv:2004.13336) — ~N× less optimizer HBM per device on an N-way
    # data axis, at the cost of an all-gather of the updated parameters
    # per step (docs/PARALLELISM.md has the tradeoff and the golden
    # memory-budget proof). Validated against the mesh at startup;
    # requires model.sync_bn=true on multi-chip meshes (the shard_map
    # per-replica-BN path cannot carry sharding constraints).
    partition: str = "replicated"  # replicated | zero1


@dataclasses.dataclass
class TrainConfig:
    """Training loop parameters (reference trainer flags + hook constants)."""

    train_dir: str = "/tmp/tpu_resnet/train"
    train_steps: int = 100_000
    # Global batch across the whole mesh. The reference is ambiguous between
    # global (Cori: 128/num_nodes per node, submit_ps_cifar_cori_dist.sh:27-31)
    # and per-worker (ImageNet: 128/node, README.md:39-40); we make global the
    # source of truth and derive per-device.
    global_batch_size: int = 128
    eval_batch_size: int = 100  # reference resnet_cifar_eval.py: batch 100
    log_every: int = 20          # LoggingTensorHook interval (resnet_cifar_train.py:282-287)
    summary_every: int = 100     # SummarySaverHook interval (:275-280)
    # Augmented input-batch image summaries (reference cifar_input.py:118
    # wrote the training batch to TensorBoard with every summary). Here a
    # small grid every N steps (0 = off); heavier than scalars, so the
    # default matches the checkpoint cadence rather than summary_every.
    image_summary_every: int = 1000
    checkpoint_every: int = 1000  # save_checkpoint_steps (:335)
    keep_checkpoints: int = 5
    seed: int = 0
    # Continuous-eval sidecar (resnet_cifar_eval.py:140-143)
    eval_interval_secs: int = 60
    eval_once: bool = False
    # Steps fused into one dispatch via lax.scan (amortizes host→device
    # command latency) — governs BOTH fused paths: device-resident chunks
    # and staged streaming superbatches (there additionally capped by
    # data.transfer_stage). 1 = one dispatch per step; chunks are clipped
    # to log/checkpoint/epoch boundaries so all intervals are honored
    # exactly. Measured (v5e r3, resident CIFAR rn50 b128): k=10 →
    # 203.3 st/s, k=50 → 195.8 — the curve is flat past 10, and 10 keeps
    # log/checkpoint clipping cheap.
    steps_per_call: int = 10
    # Profiling (tools/profiling.py): port for the live jax.profiler
    # service (0 = off) and an optional "start:stop" step window traced
    # into <train_dir>/profile.
    profiler_port: int = 0
    profile_steps: str = ""
    # Telemetry HTTP server (tpu_resnet/obs/server.py), one per host:
    # /metrics (Prometheus text) + /healthz (liveness & heartbeat age).
    # -1 = off, 0 = OS-assigned ephemeral port (recorded in
    # <train_dir>/telemetry.json), >0 = fixed port.
    telemetry_port: int = -1
    # /healthz reports ok=false (HTTP 503) when the last heartbeat is
    # older than this many seconds.
    telemetry_stale_sec: float = 300.0
    # MFU accounting (tpu_resnet/obs/mfu.py): measure the train step's
    # per-step FLOPs once at first dispatch (abstract re-trace + HLO cost
    # analysis — no second XLA compile) and publish live
    # model_flops_per_sec / mfu gauges plus <train_dir>/flops.json.
    # Purely host-side: does not change the compiled program (no new
    # config-matrix rows needed).
    mfu_accounting: bool = True
    # Memory ledger (tpu_resnet/obs/memory.py): extract the compiled
    # train step's HBM budget (argument/output/temp/alias bytes —
    # donation-credited) into <train_dir>/memory.json once at first
    # dispatch, and sample live hbm_* gauges from device.memory_stats()
    # at log boundaries. Unlike mfu accounting the budget needs a
    # COMPILED program, so this pays ONE extra XLA compile at startup
    # (charged to the compile window, excluded from throughput);
    # failures degrade to absent, never kill training. Host-side only:
    # no compiled-program change, no new config-matrix rows.
    memory_ledger: bool = True
    # Comms ledger (tpu_resnet/obs/comms.py): extract the compiled train
    # step's collective-communication summary (op multiset, analytic
    # bytes-on-wire per mesh axis, predicted time-on-wire from the
    # per-chip ICI table) into <train_dir>/comms.json once at first
    # dispatch, plus a predicted_comms_fraction gauge. Pays ONE extra
    # XLA compile at startup, same contract as memory_ledger; degrades
    # to absent, never kills training. Host-side only.
    comms_ledger: bool = True


@dataclasses.dataclass
class ResilienceConfig:
    """Fault tolerance (tpu_resnet/resilience): recovery behavior and the
    deterministic fault-injection drill knobs. Recovery is ON by default —
    a preemptible-pod trainer that only recovers when asked recovers
    never; injection is OFF by default and costs nothing when off."""

    # SIGTERM/SIGINT → stop at the next chunk boundary, save a final
    # checkpoint, exit with preempt_exit_code (tools/supervise.py resumes).
    graceful_shutdown: bool = True
    preempt_exit_code: int = 42  # resilience/exitcodes.py PREEMPTED
    # Non-finite loss at a log boundary (already host-synced there — zero
    # extra device syncs): roll back to the last checkpoint, advance the
    # data stream past the bad window, retry up to nan_max_retries times,
    # then raise DivergenceError.
    nan_guard: bool = True
    nan_max_retries: int = 2
    # No step progress for this many seconds → dump all-thread stacks to
    # <train_dir>/stall_stacks_N.txt and flip /healthz unhealthy until
    # progress resumes. 0 disables. Armed by the first completed dispatch,
    # so a long first compile can never false-trigger it.
    watchdog_stall_sec: float = 600.0
    # On an in-flight training-loop exception, attempt one guarded
    # ckpt.save(step, force=True) in the shutdown chain — a crash loses at
    # most the current interval, not everything since checkpoint_every.
    emergency_save: bool = True
    # Eval sidecar: retries (with exponential backoff) for a restore of a
    # just-committing checkpoint before the step is skipped-and-logged.
    eval_restore_retries: int = 3
    eval_restore_backoff_sec: float = 0.5
    # ---- fault injection (resilience/faultinject.py; drills only) ----
    # All off by default; TPU_RESNET_FAULT_{NAN_STEP,STALL_STEP,STALL_SEC,
    # SIGTERM_STEP,CORRUPT_CKPT,OOM_STEP} env vars override these fields.
    inject_nan_at_step: int = -1
    inject_stall_at_step: int = -1
    inject_stall_seconds: float = 0.0
    inject_sigterm_at_step: int = -1
    inject_corrupt_ckpt: bool = False
    # Raise a synthetic RESOURCE_EXHAUSTED (the XLA OOM status) at this
    # chunk boundary — the drill for the OOM-forensics path: the loop
    # must write <train_dir>/oom_report.json (ledger, gauge history,
    # live-array census) before re-raising (doctor --mem-probe).
    inject_oom_at_step: int = -1
    # Preemption burst: K SIGTERMs total ACROSS supervised restarts, each
    # fired inject_preempt_burst_every steps after its child's first
    # chunk boundary (count persisted in <train_dir>/fault_burst_state.
    # json — the firing kills the process that would remember it). The
    # deterministic drill for tools/supervise.py's downsize policy.
    inject_preempt_burst: int = 0
    inject_preempt_burst_every: int = 10
    # ---- serve-side faults (fleet chaos drills; docs/RESILIENCE.md) ----
    # Applied by the predict server (serve/server.py wraps the backend
    # infer / request admission). Env overrides: TPU_RESNET_FAULT_
    # {SERVE_SLOW_MS, SERVE_HANG_REQ, SERVE_KILL_REQ}.
    # Fixed extra latency per inference batch (slow-replica injection —
    # the router's passive latency tracking and hedging drill).
    inject_serve_slow_ms: float = 0.0
    # Accept requests normally, then hang the inference worker forever
    # starting at the Nth predict request (-1 off): the accept-then-hang
    # replica the router must evict on probe/deadline, not crash on.
    inject_serve_hang_at_request: int = -1
    # SIGKILL this serve process at the Nth predict request (-1 off):
    # the hard replica death mid-traffic the failover drill rides.
    inject_serve_kill_at_request: int = -1
    # Abruptly close the client connection (no HTTP response) at the Nth
    # predict request, once (-1 off): the router↔replica connection-drop
    # the router's retry-once failover must absorb without a client-
    # visible failure. Env override: TPU_RESNET_FAULT_SERVE_DROP_REQ.
    inject_serve_drop_at_request: int = -1


@dataclasses.dataclass
class ServeConfig:
    """Online inference server (tpu_resnet/serve; docs/SERVING.md).

    The serving shape the training side never needed: requests arrive one
    at a time, the hardware wants batches — the dynamic micro-batcher
    coalesces the request queue into a small set of bucketed batch shapes
    compiled ahead of time at startup, so no client mix ever triggers a
    mid-traffic recompile."""

    # HTTP port: 0 = OS-assigned ephemeral (recorded in
    # <train_dir>/serve.json like the telemetry discovery file), >0 fixed.
    port: int = 0
    host: str = "0.0.0.0"
    # "checkpoint": serve live weights from train.train_dir with
    # hot-reload (poll for new steps, atomic swap between batches).
    # "export": serve a frozen StableHLO bundle from ``export_dir``
    # (weights baked in — no reload; the .pb-serving analog).
    backend: str = "checkpoint"  # checkpoint | export
    export_dir: str = ""
    # Micro-batcher: coalesce queued requests until ``max_batch`` images
    # or ``max_wait_ms`` since the oldest queued request, whichever first.
    # max_wait_ms bounds the latency cost of batching for a lone request.
    max_batch: int = 16
    max_wait_ms: float = 5.0
    # Batch shapes compiled at startup. () = auto: powers of two up to
    # max_batch (1,2,4,...). Every batch pads up to the smallest bucket
    # that fits (pad fraction is exported as a gauge); requests larger
    # than max_batch are split across batches.
    batch_buckets: tuple = ()
    # Admission control: max requests queued ahead of the batcher. A full
    # queue rejects with HTTP 429 (backpressure) instead of letting the
    # tail latency grow without bound; a draining server rejects with 503.
    max_queue: int = 256
    # Hot-reload poll interval (checkpoint backend; 0 disables reload).
    # Restore retries/backoff reuse resilience.eval_restore_* — the same
    # mid-commit-checkpoint hazard the eval sidecar has.
    reload_interval_secs: float = 10.0
    # SIGTERM drain: stop accepting, flush the queue, then exit 0. After
    # this many seconds still-queued requests fail with 503 and the
    # server exits anyway (a second signal aborts immediately).
    drain_timeout_secs: float = 30.0
    # Latency ring: recent per-request latencies kept for the p50/p95/p99
    # gauges on /metrics.
    latency_ring: int = 1024
    # /healthz staleness for the SERVING heartbeat (the batcher loop
    # ticks it every batch and every idle tick, so any gap of seconds
    # means the inference worker is wedged). Much tighter than the
    # trainer's train.telemetry_stale_sec (300 s — sized for long
    # compiles): a hung replica must flip 503 fast enough that the
    # router's half-open probe cannot flap it back into rotation.
    healthz_stale_sec: float = 10.0
    # Colocation admission (resilience/elastic.py): estimated HBM bytes
    # this replica needs (weights + bucket activations). >0 gates startup
    # on the live device-memory gauges — a replica joining a trainer's
    # host starts only when the measured headroom fits it (exit code 3
    # when denied, so a scheduler can tell "no capacity here" from a
    # crash). 0 = no arbitration (single-tenant hosts).
    admission_hbm_bytes: int = 0
    # Fleet identity: when nonempty the discovery file is written as
    # <train_dir>/serve-<name>.json instead of serve.json, so N replicas
    # sharing one train_dir (same checkpoints, hot-reload in lockstep)
    # each announce their own port/pid and the router (serve/router.py)
    # discovers the whole fleet from one directory scan.
    replica_name: str = ""
    # Post-training quantization arm (ops/quant.py, serve/calibrate.py;
    # docs/SERVING.md "Quantized arm"). "int8": symmetric per-output-
    # channel int8 weight quantization + a calibrated per-tensor input
    # scale; the quantized tree is the PROGRAM ARGUMENT of a separate
    # registry program family (`_q8` key suffix), so buckets, AOT cache
    # entries, memory ledgers and golden twins all see it as its own
    # canonical program. Parity is gated (argmax >= 99% vs the f32/bf16
    # twin on the calibration set; tests/test_quant.py).
    quantize: str = "off"  # off | int8
    # Calibration (int8 only): N deterministic eval-split batches of
    # this size feed range collection; the result is digest-stamped into
    # <train_dir>/calibration.json and reused when present.
    calibration_batches: int = 4
    calibration_batch: int = 64


@dataclasses.dataclass
class RouteConfig:
    """Multi-replica serving router (tpu_resnet/serve/router.py;
    docs/SERVING.md "Serving fleet"). A stdlib-HTTP front that spreads
    /predict traffic over N serve replicas with active health probing,
    per-replica circuit breakers, bounded failover retries under a
    per-request deadline budget, optional hedged sends, and SLO-aware
    lane shedding — the production shape one replica process never had."""

    # Router HTTP port: 0 = OS-assigned ephemeral (recorded in
    # <discover_dir>/route.json), >0 fixed.
    port: int = 0
    host: str = "0.0.0.0"
    # Static replica list: base URLs ("http://127.0.0.1:8500", ...).
    # Named r0..rN-1 in rotation order. Empty = discovery only.
    replicas: tuple = ()
    # Discovery directory: scanned every probe round for the replicas'
    # serve.json / serve-<name>.json announcements (serve.replica_name).
    # A replica that restarts on a new port is re-resolved within one
    # probe interval. Also where route.json and route_events.jsonl land.
    discover_dir: str = ""
    # Active health: /healthz (+ /info queue depth) probed per replica
    # every probe_interval_secs with probe_timeout_secs. A killed or
    # hung replica is out of rotation within one probe interval.
    probe_interval_secs: float = 1.0
    probe_timeout_secs: float = 2.0
    # Circuit breaker: fail_threshold consecutive failures (probe or
    # passive request failures) open the circuit; after open_secs the
    # breaker goes half-open and the next successful probe readmits.
    fail_threshold: int = 2
    open_secs: float = 5.0
    # Per-request deadline budget (ms): the failover retry only fires
    # when enough budget remains, so a retry never blows the client SLO.
    # Clients can tighten per request with an X-Deadline-Ms header.
    deadline_ms: float = 10_000.0
    # Hedged sends: 0 = off (default). >0 = duplicate a request to a
    # second healthy replica after this many ms without a response;
    # -1 = auto (hedge at the router's rolling p99, floor 10 ms). First
    # response wins; gauged as route_hedges_total / route_hedge_wins.
    hedge_ms: float = 0.0
    # SLO-aware admission: 0 = shedding off. >0 = when the router's own
    # rolling p99 over the recent ring exceeds slo_ms, batch-lane
    # requests (X-Lane: batch) are shed with 429 + Retry-After; past
    # slo_ms * shed_hard_factor the interactive lane sheds too — never
    # queue-collapse, always an explicit retryable rejection.
    slo_ms: float = 0.0
    shed_hard_factor: float = 2.0
    # Recent end-to-end latencies kept for the rolling p50/p99 (the shed
    # and hedge signals, and the route_p99_ms gauge).
    latency_ring: int = 2048
    # Admin drain (route --drain NAME / POST /admin/drain): seconds to
    # wait for the drained replica's in-flight requests, then SIGTERM
    # (pid from its discovery record) and wait for the PR 2/5 drain.
    drain_timeout_secs: float = 30.0
    # Merit-gated dynamic membership (route --watch-discovery): a
    # replica whose discovery record APPEARS after router boot enters
    # rotation only after its first successful health probe (a
    # "pending" probation), instead of the default blind admission with
    # a fresh closed breaker. The autoscaler path relies on this: a
    # freshly spawned replica must not receive traffic before it has
    # proven /healthz once.
    watch_discovery: bool = False


@dataclasses.dataclass
class FleetConfig:
    """Fleet telemetry aggregator (tpu_resnet/obs/fleet.py;
    docs/OBSERVABILITY.md "Fleet"). ``fleetmon`` is a jax-free
    control-plane process that discovers every serving/telemetry
    endpoint from the discovery files in one directory, scrapes all
    /metrics on an interval into an append-only on-disk timeseries,
    merges per-replica latency histograms bucket-wise into true fleet
    percentiles, and tracks SLO error-budget burn rates — the sensor a
    future autoscaler reads."""

    # fleetmon's own HTTP port: 0 = OS-assigned ephemeral (recorded in
    # <discover_dir>/fleetmon.json), >0 fixed, <0 disabled.
    port: int = 0
    host: str = "0.0.0.0"
    # Directory scanned for serve*.json / route.json / telemetry*.json
    # announcements. "" = train.train_dir (the colocated default).
    discover_dir: str = ""
    # Scrape cadence and per-endpoint timeout.
    scrape_interval_secs: float = 2.0
    scrape_timeout_secs: float = 2.0
    # Fleet latency SLO: requests slower than slo_ms spend error budget.
    # 0 disables burn tracking (scraping/merging still runs).
    slo_ms: float = 0.0
    # Fraction of requests that must meet the SLO (0.999 = 0.1% budget).
    slo_target: float = 0.999
    # Multiwindow burn-rate alerting (the SRE-workbook shape): the alert
    # fires only when BOTH windows burn hot — the fast window catches
    # the spike, the slow window keeps a transient blip from paging.
    fast_window_secs: float = 60.0
    slow_window_secs: float = 600.0
    burn_alert_fast: float = 14.0
    burn_alert_slow: float = 6.0
    # Scrape rounds kept in memory for windowed burn math (the on-disk
    # timeseries is unbounded/append-only; this ring only needs to span
    # slow_window_secs of rounds).
    ring: int = 4096


@dataclasses.dataclass
class AutopilotConfig:
    """Traffic-driven autoscaling control plane (tpu_resnet/autopilot/;
    docs/AUTOPILOT.md). ``tpu_resnet autopilot`` is a jax-free control
    process that scrapes the router + fleetmon signal plane, feeds a
    deterministic target-replica policy (hysteresis bands, cooldowns,
    min/max bounds, step limits — a pure function of one signal
    snapshot, so recorded traces replay bit-identically), and actuates
    through the existing contracts: scale-up spawns a replica via the
    supervise/discovery path (colocation-admission exit 3 is a policy
    input, not a crash), scale-down drains via the router's
    /admin/drain rolling contract."""

    # Autopilot's own telemetry port: 0 = OS-assigned ephemeral
    # (recorded in <discover_dir>/autopilot.json), >0 fixed,
    # <0 disabled.
    port: int = 0
    host: str = "0.0.0.0"
    # Directory holding the fleet's discovery files (route.json,
    # fleetmon.json, serve-<name>.json) — also where the decision
    # ledger autopilot_events.jsonl and autopilot_status.json land.
    # "" = train.train_dir (the colocated default).
    discover_dir: str = ""
    # Control-loop cadence and per-scrape HTTP timeout.
    poll_interval_secs: float = 1.0
    scrape_timeout_secs: float = 2.0
    # Replica-count bounds the policy can never leave.
    min_replicas: int = 1
    max_replicas: int = 4
    # Latency SLO the policy scales against, ms. 0 = adopt the router's
    # advertised route.slo_ms from its /info (the usual colocated case).
    slo_ms: float = 0.0
    # Hysteresis bands as fractions of the SLO: p99 above
    # slo*up_band is scale-up pressure, p99 below slo*down_band is
    # scale-down pressure, and the corridor between them is a hold — a
    # p99 oscillating around one threshold can never flap the fleet.
    up_band: float = 0.9
    down_band: float = 0.5
    # Consecutive pressured rounds required before acting (the second
    # anti-flap stage: one noisy scrape is never a decision).
    up_rounds: int = 2
    down_rounds: int = 5
    # Non-latency scale-up pressure: total queued requests per healthy
    # replica (router /info), and the fleetmon fast-window burn rate.
    queue_high: float = 8.0
    burn_high: float = 6.0
    # Cooldowns (seconds of snapshot time) after an actuation before
    # the same direction may fire again. Scale-down is deliberately the
    # longer one: adding capacity is cheap, thrashing drains is not.
    scale_up_cooldown_secs: float = 10.0
    scale_down_cooldown_secs: float = 60.0
    # Per-decision step limits (replicas added/removed at once).
    max_step_up: int = 1
    max_step_down: int = 1
    # After a spawn exits with the colocation-admission NO_CAPACITY
    # code (3), hold all scale-ups this long — this host said no, and
    # asking again immediately would just be denied again.
    admission_backoff_secs: float = 30.0
    # Replica spawn command template, shlex-split; "" = observe-only
    # mode (decisions are ledgered and gauged but nothing is spawned or
    # drained). Placeholders: {python} -> sys.executable, {name} -> the
    # replica name the actuator minted (serve.replica_name={name} makes
    # the new replica discoverable), {i} -> the spawn ordinal.
    spawn_cmd: str = ""
    # Wrap spawns in tools/supervise.py --stop-codes 3 so crashes
    # restart with decorrelated-jitter backoff while the admission
    # verdict stays terminal (and observable as the wrapper's exit 3).
    spawn_supervised: bool = True
    # Names minted for autopilot-spawned replicas: <prefix><ordinal>.
    replica_prefix: str = "ap"
    # Budget (seconds) for spawn -> healthy-in-router; a spawn that
    # blows it is abandoned (process terminated, slot released) and
    # counted as a spawn failure. This is the advertised scale-up
    # latency the autoscale scenarios gate.
    ready_timeout_secs: float = 120.0
    # Capacity handoff: on scale-down write <dir>/capacity_lease.json
    # granting the freed capacity to a colocated trainer; the next
    # scale-up revokes the lease BEFORE spawning (docs/AUTOPILOT.md
    # "Capacity handoff").
    capacity_lease: bool = True


@dataclasses.dataclass
class ProgramsConfig:
    """Compiled-program registry (tpu_resnet/programs/registry.py;
    docs/PERF.md "Cold start"). One owner for the canonical program-key
    spelling and the persistent cross-process AOT executable cache that
    kills cold-start compiles across serve-replica restarts, elastic
    resume, and repeated sweep points."""

    # "auto" (default): the cache is ON for serve replicas (cold start
    # IS their cost model — the rolling-upgrade window) and ON for
    # train/eval/sweep only when a cache directory is configured here or
    # via TPU_RESNET_PROGRAM_CACHE_DIR. "on" forces it everywhere
    # (directory defaults to <train_dir>/progcache); "off" disables.
    # The TPU_RESNET_PROGRAM_CACHE=0 env kill-switch overrides all of
    # this — the operator's hard off-switch when a jaxlib's executable
    # deserialization is suspect (the PR 1 incident class; the cache
    # additionally fingerprint-verifies every entry and never
    # deserializes the same entry twice in one process).
    cache: str = "auto"  # auto | on | off
    # "" = <train_dir>/progcache when the cache is enabled. Replicas and
    # restarts sharing one train_dir share entries; a shared explicit
    # dir is the sweep/fleet-wide lever.
    cache_dir: str = ""


@dataclasses.dataclass
class RunConfig:
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    optim: OptimConfig = dataclasses.field(default_factory=OptimConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    resilience: ResilienceConfig = dataclasses.field(
        default_factory=ResilienceConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    route: RouteConfig = dataclasses.field(default_factory=RouteConfig)
    fleet: FleetConfig = dataclasses.field(default_factory=FleetConfig)
    autopilot: AutopilotConfig = dataclasses.field(
        default_factory=AutopilotConfig)
    programs: ProgramsConfig = dataclasses.field(
        default_factory=ProgramsConfig)

    # ---------------------------------------------------------- serialization
    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, default=list)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "RunConfig":
        cfg = cls()
        for section_name, section_val in d.items():
            section = getattr(cfg, section_name)
            for k, v in section_val.items():
                if not hasattr(section, k):
                    raise ValueError(f"unknown config field {section_name}.{k}")
                cur = getattr(section, k)
                if isinstance(cur, tuple) and isinstance(v, list):
                    v = tuple(v)
                setattr(section, k, v)
        return cfg

    # ------------------------------------------------------------------- CLI
    def apply_overrides(self, overrides: Sequence[str]) -> "RunConfig":
        """Apply ``section.field=value`` strings (the CLI surface)."""
        for ov in overrides:
            if "=" not in ov:
                raise ValueError(f"override must be section.field=value: {ov!r}")
            key, raw = ov.split("=", 1)
            parts = key.lstrip("-").split(".")
            if len(parts) != 2:
                raise ValueError(f"override key must be section.field: {key!r}")
            section_name, field = parts
            section = getattr(self, section_name, None)
            if section is None or not hasattr(section, field):
                raise ValueError(f"unknown config field {key!r}")
            cur = getattr(section, field)
            setattr(section, field, _parse_value(raw, cur))
        return self


def _parse_value(raw: str, current: Any) -> Any:
    if isinstance(current, bool):
        if raw.lower() in ("1", "true", "yes"):
            return True
        if raw.lower() in ("0", "false", "no"):
            return False
        raise ValueError(f"bad bool {raw!r}")
    if isinstance(current, int):
        return int(raw)
    if isinstance(current, float):
        return float(raw)
    if isinstance(current, tuple):
        s = raw.strip()
        if s.startswith("(") and s.endswith(")"):  # accept Python-style
            s = "[" + s[1:-1].rstrip(",") + "]"    # tuples, not just JSON
        return tuple(json.loads(s))
    return raw


# ---------------------------------------------------------------- presets
def _cifar_local() -> RunConfig:
    """Reference 'local' config: ResNet-50(6n+2) CIFAR-10, batch 128,
    piecewise LR, ~80k steps → 93.6% (README.md:28)."""
    cfg = RunConfig()
    cfg.data.dataset = "cifar10"
    cfg.model.resnet_size = 50
    cfg.optim.schedule = "cifar_piecewise"
    cfg.optim.weight_decay = 0.0002
    cfg.train.train_steps = 90_000
    cfg.train.global_batch_size = 128
    return cfg


def _cifar100() -> RunConfig:
    cfg = _cifar_local()
    cfg.data.dataset = "cifar100"
    return cfg


def _wrn_28_10_cifar100() -> RunConfig:
    """Wide-ResNet-28-10 on CIFAR-100 (BASELINE.json configs[3])."""
    cfg = _cifar_local()
    cfg.data.dataset = "cifar100"
    cfg.model.resnet_size = 28
    cfg.model.width_multiplier = 10
    cfg.optim.weight_decay = 0.0005
    return cfg


def _imagenet() -> RunConfig:
    """ResNet-50 ImageNet, Intel-Caffe 8-node recipe: global batch 1024,
    warmup 0.1→0.4 over 6240 steps then /10 at 37440/74880/99840, weight
    decay 1e-4, 90 epochs = 112600 steps
    (resnet_imagenet_train.py:236-260, submit_imagenet_daint_dist.sh:38-40)."""
    cfg = RunConfig()
    cfg.data.dataset = "imagenet"
    cfg.model.resnet_size = 50
    cfg.optim.schedule = "imagenet_warmup"
    cfg.optim.weight_decay = 1e-4
    cfg.train.train_steps = 112_600
    cfg.train.global_batch_size = 1024
    cfg.train.eval_batch_size = 125
    return cfg


def _smoke() -> RunConfig:
    """Laptop-scale smoke config — the reference's only integration test
    (mkl-scripts/submit_mac_dist.sh: batch 10, 100 steps)."""
    cfg = RunConfig()
    cfg.data.dataset = "synthetic"
    cfg.model.resnet_size = 8
    cfg.model.compute_dtype = "float32"
    cfg.train.train_steps = 100
    cfg.train.global_batch_size = 16
    cfg.train.checkpoint_every = 50
    cfg.optim.schedule = "constant"
    cfg.optim.base_lr = 0.01
    return cfg


# The supported config space (these presets × mesh/dtype/fused/remat/
# engine variations) is certified statically: tpu_resnet/analysis/
# configmatrix.py traces the compiled train/eval program of every
# combination in its MATRIX and pins it to a golden jaxpr hash, and the
# unsupported combinations are must-raise entries there. Adding a field
# here that changes the compiled step means adding/regenerating matrix
# rows (`python -m tpu_resnet check --update-golden`; docs/CHECKS.md).
PRESETS = {
    "cifar10": _cifar_local,
    "cifar100": _cifar100,
    "wrn28_10_cifar100": _wrn_28_10_cifar100,
    "imagenet": _imagenet,
    "smoke": _smoke,
}


def load_config(preset: str = "", config_file: str = "",
                overrides: Sequence[str] = ()) -> RunConfig:
    if preset:
        if preset not in PRESETS:
            raise ValueError(f"unknown preset {preset!r}; have {sorted(PRESETS)}")
        cfg = PRESETS[preset]()
    elif config_file:
        with open(config_file) as f:
            cfg = RunConfig.from_dict(json.load(f))
    else:
        cfg = RunConfig()
    return cfg.apply_overrides(overrides)


def build_arg_parser(description: str = "") -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--preset", default="", help=f"one of {sorted(PRESETS)}")
    p.add_argument("--config", default="", help="JSON config file")
    p.add_argument("overrides", nargs="*",
                   help="section.field=value overrides")
    return p
