"""Unified CLI — replaces the reference's nine overlapping entry scripts
(resnet_single.py, resnet_cifar_train.py, resnet_cifar_main.py,
resnet_imagenet_train.py, the eval sidecars and predict tools — SURVEY.md §1
L4) with one command:

    python -m tpu_resnet train --preset cifar10 train.train_dir=/tmp/run
    python -m tpu_resnet eval  --preset cifar10 train.train_dir=/tmp/run
    python -m tpu_resnet info  --preset imagenet
"""

from __future__ import annotations

import argparse
import json
import logging
import sys


def _setup_logging():
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s",
        datefmt="%H:%M:%S",
        stream=sys.stderr,
    )


def main(argv=None):
    _setup_logging()
    raw = sys.argv[1:] if argv is None else list(argv)
    if raw[:1] == ["check"]:
        # Delegated wholesale: the analysis CLI owns its flag surface
        # (argparse.REMAINDER can't forward leading --flags), and this
        # path must not import jax until it decides to.
        from tpu_resnet.analysis.cli import main as check_main
        return check_main(raw[1:])
    if raw[:1] == ["trace-export"]:
        # Same delegation: stdlib-only timeline export (obs/trace.py) —
        # never imports jax, works on a machine with no backend.
        from tpu_resnet.obs.trace import main as trace_main
        return trace_main(raw[1:])
    if raw[:1] == ["scenario"]:
        # Same delegation: the chaos-scenario conductor is jax-free by
        # contract — its CHILDREN are the processes that touch jax.
        from tpu_resnet.scenario.cli import main as scenario_main
        return scenario_main(raw[1:])
    parser = argparse.ArgumentParser(prog="tpu_resnet")
    sub = parser.add_subparsers(dest="command", required=True)
    for name, help_text in [
        ("train", "run the training loop"),
        ("train_and_eval", "train with an in-process eval sidecar"),
        ("eval", "continuous checkpoint-polling evaluation (or --once)"),
        ("info", "print resolved config, param count and per-step FLOPs"),
        ("export", "freeze a checkpoint into a serialized inference artifact"),
        ("predict", "run a frozen artifact over the eval split"),
        ("serve", "online inference: dynamic-batching HTTP predict server "
                  "with checkpoint hot-reload (docs/SERVING.md)"),
        ("route", "serving-fleet front router: spread /predict over N "
                  "serve replicas with health-probed failover, SLO-aware "
                  "load shedding and rolling drains (docs/SERVING.md)"),
        ("fleetmon", "fleet telemetry aggregator: discover every "
                     "serve/route/train endpoint in a dir, scrape all "
                     "/metrics on an interval into an on-disk "
                     "timeseries, merge per-replica latency histograms "
                     "into true fleet p50/p95/p99, page on SLO "
                     "error-budget burn (docs/OBSERVABILITY.md)"),
        ("autopilot", "traffic-driven autoscaling control plane: scrape "
                      "the router + fleetmon signals, run the "
                      "deterministic target-replica policy (hysteresis "
                      "bands, cooldowns, min/max), spawn replicas via "
                      "supervise/discovery gated by colocation "
                      "admission, drain via the router's rolling "
                      "contract (docs/AUTOPILOT.md)"),
        ("inspect", "list arrays in a checkpoint (tf_saver equivalent)"),
        ("plot", "render precision/loss/throughput curves from metrics.jsonl"),
        ("trace-export", "merge a run's spans/metrics/eval/serve events "
                         "into one Chrome-trace JSON (open in "
                         "ui.perfetto.dev; docs/OBSERVABILITY.md)"),
        ("fetch", "download + verify + extract a dataset (cifar10/cifar100)"),
        ("doctor", "environment triage: backend probe, CPU mesh smoke, "
                   "native plane, dataset layout, run telemetry"),
        ("check", "static analysis: JAX/TPU AST lints + config-matrix "
                  "abstract verifier (docs/CHECKS.md)"),
    ]:
        p = sub.add_parser(name, help=help_text)
        if name not in ("fetch", "doctor", "check",
                        "trace-export"):  # no run config
            p.add_argument("--preset", default="")
            p.add_argument("--config", default="")
            p.add_argument("overrides", nargs="*")
        if name == "eval":
            p.add_argument("--once", action="store_true",
                           help="evaluate latest checkpoint once and exit")
        if name == "route":
            p.add_argument("--drain", default="",
                           help="rolling operations: ask a RUNNING "
                                "router to drain replica NAME (exclude "
                                "from rotation, wait out in-flight, "
                                "SIGTERM per the drain contract) and "
                                "exit — instead of starting a router")
            p.add_argument("--router-url", default="",
                           help="with --drain: the running router's "
                                "base url (default: discovered from "
                                "route.json in route.discover_dir)")
            p.add_argument("--watch-discovery", action="store_true",
                           help="merit-gated dynamic membership: a "
                                "replica whose discovery record appears "
                                "after boot enters rotation only after "
                                "its first successful health probe "
                                "(shorthand for "
                                "route.watch_discovery=true; the "
                                "autopilot's spawn path relies on it)")
        if name == "info":
            p.add_argument("--layers", action="store_true",
                           help="per-parameter table (tfprof-style dump)")
        if name == "export":
            p.add_argument("--out", required=True,
                           help="output directory for the frozen artifact")
            p.add_argument("--step", type=int, default=None)
            p.add_argument("--batch-size", type=int, default=0,
                           help="0 = dynamic batch dimension")
        if name == "predict":
            p.add_argument("--export-dir", required=True)
            p.add_argument("--out", default="/tmp/tpu_resnet_predict")
            p.add_argument("--num-examples", type=int, default=256)
            p.add_argument("--label-file", default="",
                           help="imagenet idx→name map file")
        if name == "inspect":
            p.add_argument("--dir", required=True, help="train/ckpt dir")
            p.add_argument("--step", type=int, default=None)
            p.add_argument("--peek", default=None,
                           help="print stats+head of one array by path")
        if name == "plot":
            p.add_argument("--dir", required=True, help="train dir")
            p.add_argument("--out", default=None, help="output PNG path")
            p.add_argument("--csv", default=None,
                           help="also export merged series as CSV")
        if name == "fetch":
            p.add_argument("dataset",
                           choices=["cifar10", "cifar100", "imagenet"])
            p.add_argument("--out", required=True, help="dataset directory")
            p.add_argument("--keep-archive", action="store_true")
        if name == "doctor":
            p.add_argument("--list-probes", action="store_true",
                           help="enumerate every scenario-backed drill "
                                "(scenarios/*.json) and every legacy "
                                "bespoke probe, then exit")
            p.add_argument("--check", action="store_true",
                           help="also run the static-analysis suite "
                                "(lints + config-matrix verifier)")
            p.add_argument("--dataset", default="",
                           help="with --data-dir: layout to validate")
            p.add_argument("--data-dir", default="")
            p.add_argument("--train-dir", default="",
                           help="running run's dir: check its telemetry "
                                "server answers /metrics + /healthz")
            p.add_argument("--probe-timeout", type=int, default=60)
            p.add_argument("--mesh-devices", type=int, default=8)
            p.add_argument("--fault-drill", action="store_true",
                           help="run a live SIGTERM+resume drill against "
                                "a temp train_dir (~30s tiny CPU run): "
                                "preemption exit code, final checkpoint, "
                                "exact-step resume")
            p.add_argument("--serve-probe", action="store_true",
                           help="live predict-server smoke (~60s tiny CPU "
                                "run): train a small model, serve it on "
                                "an ephemeral port, fire requests, check "
                                "/healthz readiness and the SIGTERM "
                                "drain exit-code contract")
            p.add_argument("--coldstart-probe", action="store_true",
                           help="cold-vs-warm serve restart drill "
                                "(~3min scrubbed CPU): train a small "
                                "ResNet, serve it cold, SIGTERM, "
                                "restart warm on the same train_dir — "
                                "zero XLA compiles on the warm pass "
                                "(all bucket programs are persistent-"
                                "cache hits), time-to-ready >= 3x "
                                "faster, perfwatch ingests both points")
            p.add_argument("--fleet-probe", action="store_true",
                           help="serving-fleet resilience drill (~2min "
                                "scrubbed CPU): 2 serve replicas + the "
                                "front router on ephemeral ports, "
                                "SIGKILL one replica mid-traffic -> "
                                "zero failed requests, circuit opens "
                                "within a probe interval, hot-reload on "
                                "the survivor, rolling admin drain, "
                                "exit-code contract, trace-export "
                                "router+replica lanes")
            p.add_argument("--data-bench", action="store_true",
                           help="~20s synthetic-JPEG decode throughput "
                                "probe: images/sec at 1 vs N decode "
                                "processes + implied max steps/sec — "
                                "tells host-bound from chip-bound "
                                "without a full bench run")
            p.add_argument("--trace-probe", action="store_true",
                           help="live observability drill (~60s tiny CPU "
                                "run): scrape the live mfu gauge + "
                                "train_step_ms histogram mid-run, then "
                                "trace-export and schema-check the "
                                "merged Chrome trace")
            p.add_argument("--perfwatch", action="store_true",
                           help="perf-regression verdict over the "
                                "archived BENCH_*.json trajectory "
                                "(tools/perfwatch.py)")
            p.add_argument("--sweep-probe", action="store_true",
                           help="~30s scrubbed-CPU drill of the per-knob "
                                "sweep harness: 2-point sweep end-to-end "
                                "— child deadlines honored, complete "
                                "RESULT_JSON trajectory, perfwatch "
                                "ingestion")
            p.add_argument("--mem-probe", action="store_true",
                           help="memory-observability drill (~60s tiny "
                                "CPU runs): live hbm gauge scrape + "
                                "memory.json ledger matching flops.json "
                                "keys, then a fault-injected "
                                "RESOURCE_EXHAUSTED that must leave a "
                                "schema-valid oom_report.json")
            p.add_argument("--partition-probe", action="store_true",
                           help="ZeRO-1 partitioner drill (~90s tiny CPU "
                                "runs on an 8-device fakepod): zero1 "
                                "optimizer-slot ledger bytes < 0.3x the "
                                "replicated twin's, SIGTERM + exact-step "
                                "resume under zero1, perfwatch peak-HBM "
                                "ingestion")
            p.add_argument("--fleetmon-probe", action="store_true",
                           help="fleet-observability drill (~2min "
                                "scrubbed CPU): 2 replicas + router + "
                                "fleetmon, one replica fault-slowed -> "
                                "zero failed requests, traced requests "
                                "attribute the tail to the slow "
                                "replica's inference segment, fleet-"
                                "merged p99 > healthy replica's own "
                                "p99, burn-rate alert span fires, "
                                "perfwatch ingests fleet latency")
            p.add_argument("--autoscale-probe", action="store_true",
                           help="autoscaling drill (~3min scrubbed "
                                "CPU): 1 replica + watch-discovery "
                                "router + fleetmon + autopilot; a "
                                "traffic burst overruns the replica -> "
                                "autopilot spawns a second via "
                                "supervise/discovery, admitted on "
                                "merit within the advertised scale-up "
                                "latency; calm traffic -> drains back "
                                "to min and leases the freed capacity "
                                "to a colocated trainer; perfwatch "
                                "gates the scale-up-latency / SLO-"
                                "violation / utilization series")
            p.add_argument("--reshape-drill", action="store_true",
                           help="elastic-capacity drill (~2min tiny CPU "
                                "runs): mesh8 train preempted by an "
                                "injected SIGTERM, resumed on a 4-device "
                                "child as zero1 — loss stream equal to "
                                "an uninterrupted mesh8 reference within "
                                "1e-6 at every logged step, "
                                "topology_change span recorded, "
                                "perfwatch ingests pre/post steps/s")
    args = parser.parse_args(argv)

    if args.command == "fetch":
        from tpu_resnet.tools.datasets import fetch
        fetch(args.dataset, args.out, keep_archive=args.keep_archive)
        return 0

    if args.command == "doctor":
        if args.list_probes:
            # The scenario catalog owns the probe inventory — the same
            # listing `tpu_resnet scenario list` prints.
            from tpu_resnet.scenario.cli import main as scenario_main
            return scenario_main(["list", "--paths"])
        from tpu_resnet.tools.doctor import run_doctor
        if args.dataset and not args.data_dir:
            parser.error("doctor --dataset requires --data-dir")
        summary = run_doctor(dataset=args.dataset, data_dir=args.data_dir,
                             train_dir=args.train_dir,
                             probe_timeout=args.probe_timeout,
                             mesh_devices=args.mesh_devices,
                             fault_drill=args.fault_drill,
                             data_bench=args.data_bench,
                             check=args.check,
                             serve_probe=args.serve_probe,
                             coldstart_probe=args.coldstart_probe,
                             fleet_probe=args.fleet_probe,
                             fleetmon_probe=args.fleetmon_probe,
                             trace_probe=args.trace_probe,
                             perfwatch=args.perfwatch,
                             sweep_probe=args.sweep_probe,
                             mem_probe=args.mem_probe,
                             partition_probe=args.partition_probe,
                             reshape_drill=args.reshape_drill,
                             autoscale_probe=args.autoscale_probe)
        return 0 if summary["ok"] else 1

    from tpu_resnet.config import load_config
    cfg = load_config(args.preset, args.config, args.overrides)

    if args.command == "train":
        from tpu_resnet import parallel
        from tpu_resnet.resilience import Preempted
        from tpu_resnet.train import train
        parallel.initialize()
        try:
            train(cfg)
        except Preempted as e:
            # Distinct exit code: a supervisor (tools/supervise.py, or any
            # restart policy) resumes on this code instead of backing off
            # as for a crash. The final checkpoint is already on disk.
            logging.getLogger("tpu_resnet").warning(
                "%s — exiting %d", e, cfg.resilience.preempt_exit_code)
            return cfg.resilience.preempt_exit_code
        return 0

    if args.command == "train_and_eval":
        from tpu_resnet import parallel
        from tpu_resnet.evaluation import train_and_eval
        from tpu_resnet.resilience import Preempted
        parallel.initialize()
        try:
            train_and_eval(cfg)
        except Preempted as e:
            logging.getLogger("tpu_resnet").warning(
                "%s — exiting %d", e, cfg.resilience.preempt_exit_code)
            return cfg.resilience.preempt_exit_code
        return 0

    if args.command == "eval":
        from tpu_resnet import parallel
        from tpu_resnet.evaluation import evaluate
        parallel.initialize()
        if args.once:
            cfg.train.eval_once = True
        evaluate(cfg)
        return 0

    if args.command == "info":
        from tpu_resnet.tools.analysis import print_model_info
        print_model_info(cfg, layers=args.layers)
        return 0

    if args.command == "export":
        from tpu_resnet.export import export_from_checkpoint
        out = export_from_checkpoint(cfg, args.out, step=args.step,
                                     batch_size=args.batch_size)
        print(f"exported inference artifact to {out}")
        return 0

    if args.command == "predict":
        from tpu_resnet.tools.predict import predict_from_export
        predict_from_export(cfg, args.export_dir, args.out,
                            num_examples=args.num_examples,
                            label_file=args.label_file)
        return 0

    if args.command == "serve":
        from tpu_resnet import parallel
        from tpu_resnet.serve import serve as serve_fn
        parallel.initialize()
        return serve_fn(cfg)

    if args.command == "route":
        # The router is pure host code — it must come up (and stay up)
        # on a machine whose accelerator stack is the thing that is
        # broken, so no parallel.initialize() here.
        from tpu_resnet.serve.router import (read_route_port,
                                             request_drain, route)
        if args.drain:
            url = args.router_url
            if not url:
                port = read_route_port(cfg.route.discover_dir
                                       or cfg.train.train_dir)
                if port is None:
                    parser.error("route --drain: no route.json found; "
                                 "pass --router-url or "
                                 "route.discover_dir=<dir>")
                url = f"http://127.0.0.1:{port}"
            result = request_drain(url, args.drain)
            print(json.dumps(result))
            return 0 if result.get("ok") else 1
        if args.watch_discovery:
            cfg.route.watch_discovery = True
        return route(cfg)

    if args.command == "fleetmon":
        # Control-plane sensor, same host-isolation contract as the
        # router: stdlib-only scraping, no parallel.initialize() — it
        # must keep reporting while the data plane is on fire.
        from tpu_resnet.obs.fleet import fleetmon
        return fleetmon(cfg)

    if args.command == "autopilot":
        # The autoscaling control plane shares the host-isolation
        # contract: it must keep steering the fleet while the
        # accelerator stack is the thing that is melting, so no
        # parallel.initialize() — only its CHILD serve processes may
        # touch jax.
        from tpu_resnet.autopilot.cli import autopilot as autopilot_fn
        return autopilot_fn(cfg)

    if args.command == "inspect":
        from tpu_resnet.tools.inspect_ckpt import main as inspect_main
        inspect_main(args.dir, step=args.step, peek=args.peek)
        return 0

    if args.command == "plot":
        from tpu_resnet.tools.plot_metrics import plot
        out = plot(args.dir, out=args.out, csv_out=args.csv)
        print(f"wrote {out}")
        return 0

    parser.error(f"unknown command {args.command}")


if __name__ == "__main__":
    sys.exit(main())
