"""The training loop — the replacement for every reference trainer's
``while not mon_sess.should_stop(): mon_sess.run(train_op)``
(reference resnet_cifar_train.py:343-344) plus its hook stack:

- logging every ``log_every`` steps (LoggingTensorHook,
  resnet_cifar_train.py:282-287),
- metrics/summaries every ``summary_every`` steps (SummarySaverHook, :275-280),
- checkpoint every ``checkpoint_every`` steps (save_checkpoint_steps=1000,
  :335) with automatic resume from the latest checkpoint on restart
  (MonitoredTrainingSession contract, resnet_imagenet_train.py:267-270),
- stop at ``train_steps`` (StopAtStepHook, :289).

One function serves every execution mode of the reference (single, PS-sync,
async-PS, Horovod — SURVEY.md §2.3): the mesh decides the distribution.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpu_resnet import obs, parallel, programs, resilience
from tpu_resnet.config import RunConfig
from tpu_resnet.data import augment as aug_lib
from tpu_resnet.data import device_data
from tpu_resnet.data import pipeline
from tpu_resnet.models import build_model
from tpu_resnet.tools import profiling
from tpu_resnet.train import schedule as sched_lib
from tpu_resnet.train.checkpoint import CheckpointManager
from tpu_resnet.train.metrics_io import MetricsWriter, ThroughputMeter
from tpu_resnet.train.state import init_partitioned_state, param_count
from tpu_resnet.train.step import (check_step_config, make_train_step,
                                   shard_step)

log = logging.getLogger("tpu_resnet")


def build_train_iterator(cfg: RunConfig, mesh, start_step: int = 0,
                         injector=None, stop_event=None):
    """Host pipeline: per-process shard → background batcher → device
    prefetch queue. With ``transfer_stage`` > 1 the iterator yields whole
    ``(stage, B, ...)`` superbatches (one transfer each) plus their length;
    the loop fuses those steps into single dispatches.

    Returns ``(device_iter, stage, host_iter)``; the ``host_iter`` handle
    (HostDataEngine for ImageNet, BackgroundIterator otherwise) lets the
    NaN-rollback path release the producers before rebuilding the stream
    past the bad window, and joins the shutdown closer chain (engine
    close unlinks its shared-memory ring). ``injector``
    (resilience.FaultInjector) wraps the host batch stream with its
    planned data faults; a default (inactive) plan returns the stream
    object untouched."""
    import tpu_resnet.data as data_lib
    from tpu_resnet.data.engine import HostDataEngine

    local_bs = parallel.local_batch_size(cfg.train.global_batch_size, mesh)
    stage = max(1, cfg.data.transfer_stage)
    # hold = stage + 1: the staged superbatch assembly looks back at most
    # `stage - 1` engine views while collecting one transfer's batches.
    batches = data_lib.train_batches(cfg.data, local_bs, seed=cfg.train.seed,
                                     start_step=start_step, hold=stage + 1,
                                     external_stop=stop_event)
    if isinstance(batches, HostDataEngine):
        # The engine is its own background prefetcher (ring slots ahead of
        # the consumer) — wrapping it in BackgroundIterator would both
        # stack a redundant thread AND buffer more ring views than the
        # hold window allows. The fault injector's wrapper holds nothing.
        host_iter = batches
        stream = (injector.wrap_host_batches(batches, start_step=start_step)
                  if injector is not None else batches)
    else:
        if injector is not None:
            batches = injector.wrap_host_batches(batches,
                                                 start_step=start_step)
        host_iter = pipeline.BackgroundIterator(
            batches, capacity=stage * cfg.data.prefetch + 2,
            external_stop=stop_event)
        stream = host_iter
    if stage > 1:
        if cfg.data.h2d_double_buffer:
            # Double-buffered H2D (pipeline.DoubleBufferedH2D): a producer
            # thread assembles + lands the next superbatch transfer while
            # this thread dispatches the current one; explicit two-slot
            # device buffer, h2d_* gauges, trace transfer lane. Contents
            # are identical to the generator form (loss bit-equality
            # pinned by tests/test_data.py).
            return pipeline.DoubleBufferedH2D(
                stream, parallel.staged_batch_sharding(mesh),
                stage=stage, depth=cfg.data.prefetch,
                external_stop=stop_event), stage, host_iter
        return pipeline.staged_superbatch_prefetch(
            stream, parallel.staged_batch_sharding(mesh),
            stage=stage, depth=cfg.data.prefetch), stage, host_iter
    if isinstance(host_iter, HostDataEngine):
        # Unstaged path: device_prefetch hands each batch straight to an
        # ASYNC host→device transfer and keeps `depth` in flight — a ring
        # view could be recycled (hold counts draws, not transfer
        # completions) while PJRT is still reading it. Copy out of the
        # ring here; the staged path needs no copy because np.stack
        # materializes the superbatch synchronously.
        stream = ((img.copy(), lab.copy()) for img, lab in stream)
    return pipeline.device_prefetch(stream, parallel.batch_sharding(mesh),
                                    depth=cfg.data.prefetch), 1, host_iter


def _chunk_len(step: int, total: int, train_cfg, steps_per_epoch: int,
               extra_boundaries: tuple = ()) -> int:
    """Steps to run in the next fused dispatch: at most ``steps_per_call``,
    clipped so the chunk ends exactly on the next log/summary/checkpoint/
    epoch/stop boundary — every interval fires at precisely the same steps
    a one-dispatch-per-step loop would fire them. ``extra_boundaries`` are
    absolute steps (e.g. a profiler trace window) chunks must not straddle."""
    k = max(1, train_cfg.steps_per_call)
    for interval in (train_cfg.log_every, train_cfg.summary_every,
                     train_cfg.image_summary_every,
                     train_cfg.checkpoint_every, steps_per_epoch):
        if interval > 0:
            k = min(k, interval - step % interval)
    for b in extra_boundaries:
        if b > step:
            k = min(k, b - step)
    return min(k, total - step)


def _local_image_slice(batch, n: int = 4) -> np.ndarray:
    """First ``n`` images of a batch as host numpy, multi-host safe: a
    batch-sharded global array spans non-addressable devices, so slice
    this process's own shard instead of the global array (device_get of a
    global slice raises on non-primary-addressable data). Accepts the
    resident path's host array, a [B,...] device batch, or a staged
    [stage,B,...] superbatch."""
    if isinstance(batch, np.ndarray):
        arr = batch
    elif getattr(batch, "is_fully_addressable", True):
        arr = np.asarray(jax.device_get(batch))
    else:
        arr = np.asarray(jax.device_get(batch.addressable_shards[0].data))
    if arr.ndim == 5:  # staged superbatch: first stage row
        arr = arr[0]
    return arr[:n]


def train(cfg: RunConfig, mesh=None, metrics: Optional[MetricsWriter] = None,
          max_steps: Optional[int] = None):
    """Run training to ``cfg.train.train_steps``; returns the final state."""
    elastic_ctx = None
    if mesh is None:
        # Elastic resume (resilience/elastic.py): derive the mesh from
        # the devices that actually exist — an explicit mesh.data that no
        # longer fits downsizes instead of dying, and a topology that
        # differs from <train_dir>/topology.json becomes a recorded
        # topology_change (span + manifest entry + gauge) below. A
        # caller-supplied mesh opts out: the caller owns its topology.
        elastic_ctx = resilience.elastic.resolve(cfg)
        mesh = elastic_ctx.mesh
    parallel.check_divisible(cfg.train.global_batch_size, mesh)

    model = build_model(cfg)
    schedule = sched_lib.build_schedule(cfg.optim, cfg.train)
    augment_fn, _ = aug_lib.get_augment_fns(cfg.data.dataset)

    rng = jax.random.PRNGKey(cfg.train.seed)
    init_rng, step_rng = jax.random.split(rng)
    size = cfg.data.resolved_image_size
    sample = jnp.zeros((1, size, size, 3), jnp.float32)
    # The partitioner (parallel/partition.py) owns every TrainState
    # sharding decision: cfg.mesh.partition=replicated reproduces the
    # historical full-copy device_put; zero1 validates the rule set
    # against the real state tree (must-raise on unshardable leaves,
    # BEFORE any compile is paid) and lands the optimizer slots in their
    # data-axis shards.
    partitioner = parallel.make_partitioner(cfg.mesh, mesh)
    state = init_partitioned_state(model, cfg.optim, schedule, init_rng,
                                   sample, partitioner)
    n_params = param_count(state.params)

    # Observability (tpu_resnet/obs): event spans + run manifest + the
    # per-host telemetry server. Spans/manifest are primary-only like
    # every other writer; the HTTP server runs on EVERY host so a pod can
    # be scraped for stragglers. The run_id (minted once per train_dir,
    # reused across resumes) correlates this run's artifacts with the
    # eval sidecar, serve and loadgen on one trace-export timeline.
    run_id = (obs.ensure_run_id(cfg.train.train_dir)
              if parallel.is_primary()
              else obs.read_run_id(cfg.train.train_dir))
    spans = obs.SpanTracer(cfg.train.train_dir,
                           enabled=parallel.is_primary(), run_id=run_id)
    obs.write_manifest(
        cfg.train.train_dir, cfg, mesh, run_id=run_id,
        extra=({"topology_change": elastic_ctx.attrs()}
               if elastic_ctx is not None and elastic_ctx.changed
               else None))
    from tpu_resnet.obs.server import CORE_HISTOGRAMS
    telemetry = obs.TelemetryRegistry(
        stale_after_sec=cfg.train.telemetry_stale_sec,
        histograms=CORE_HISTOGRAMS)
    telemetry.heartbeat(0)  # alive from startup; re-fired with the real
    server = obs.TelemetryServer.maybe_start(  # step once state is known
        cfg.train.telemetry_port, telemetry, train_dir=cfg.train.train_dir)

    # Everything from here (resilience install, restore, step compile,
    # iterator construction) runs INSIDE the try: a setup failure — a
    # bad restore, a config ValueError, an iterator error — must still
    # run the closer chain, or the process-global signal handlers, the
    # watchdog thread, the telemetry server and the spans file leak
    # into the (in-process) caller.
    rcfg = cfg.resilience
    shutdown = watchdog = ckpt = tracer = host_iter = data_iter = None
    m = None
    run_wall0 = None
    step = last_ckpt_step = 0
    total = None
    # Memory-forensics state must exist before the try: the OOM closer
    # reads it even when setup fails ahead of the first dispatch.
    mem_ledger = obs.memory.MemoryLedger()
    mem_key = None
    mem_ring = obs.memory.MemorySampleRing()
    try:
        # Fault-tolerance layer (tpu_resnet/resilience): preemption-graceful
        # shutdown, NaN rollback, hang watchdog — and, drills only, the
        # deterministic fault injector (inactive plan = zero overhead).
        injector = resilience.FaultInjector(
            resilience.FaultPlan.from_config(rcfg),
            train_dir=cfg.train.train_dir)
        if injector.plan.preempt_burst > 0:
            # Cumulative across supervised restarts (state file in the
            # train_dir) — a resumed child reports the burst so far.
            telemetry.set("fault_preempt_burst",
                          float(injector.burst_fired))
        shutdown = resilience.ShutdownCoordinator(
            enabled=rcfg.graceful_shutdown).install()
        sentinel = resilience.NaNSentinel(rcfg.nan_max_retries,
                                          enabled=rcfg.nan_guard)
        watchdog = resilience.HangWatchdog.maybe_start(
            rcfg.watchdog_stall_sec, cfg.train.train_dir,
            telemetry=telemetry, spans=spans)

        injector.maybe_corrupt_checkpoint(cfg.train.train_dir)
        ckpt = CheckpointManager(
            cfg.train.train_dir, keep=cfg.train.keep_checkpoints,
            spans=spans,
            topology=(elastic_ctx.current if elastic_ctx is not None
                      else resilience.elastic.topology_record(
                          mesh, partitioner.mode,
                          cfg.train.global_batch_size)))
        # topology.json must name the topology that wrote the NEWEST
        # checkpoints, so it is written on this run's FIRST successful
        # save (all three save sites call this), never at startup — a
        # reshaped resume that dies before saving leaves the record on
        # the old topology, keeping the next resume's reshape detection
        # and restore-error hints truthful. The wait() pins that to the
        # save's COMMIT, not its async enqueue (a SIGKILL between
        # enqueue and commit must not leave a record without its
        # checkpoint); once per run, so the sync cost never recurs.
        topology_recorded = False

        def record_topology():
            nonlocal topology_recorded
            if not topology_recorded:
                topology_recorded = True
                ckpt.wait()
                resilience.elastic.write_topology(
                    cfg.train.train_dir, mesh, partitioner.mode,
                    cfg.train.global_batch_size)

        latest = ckpt.latest_step()
        if latest is not None:
            # restore() falls back through all_steps() past corrupt/torn
            # checkpoints to the newest restorable one; as the directory's
            # owner, the trainer also discards the steps that failed (the run
            # will re-reach those step numbers and must be able to save them).
            # The template is the CURRENT topology's partitioned state, so a
            # checkpoint written on a different mesh/partition restores
            # through an explicit cross-topology reshard (orbax stores
            # global logical arrays) — value-identical, never corrupted.
            state = ckpt.restore(state, discard_failed=True)
            log.info("resumed from step %d in %s",
                     int(jax.device_get(state.step)), cfg.train.train_dir)
        if elastic_ctx is not None and elastic_ctx.changed:
            # The reshape as a first-class event: a span on the run
            # timeline (trace-export renders capacity waves), a gauge,
            # and — written above — a manifest entry.
            spans.event("topology_change",
                        step=int(jax.device_get(state.step)),
                        **elastic_ctx.attrs())
            telemetry.set("topology_changes", 1.0)

        if metrics is None:
            metrics = MetricsWriter(cfg.train.train_dir,
                                    enabled=parallel.is_primary())

        # Per-replica BN (reference semantics, model.sync_bn=False) runs the
        # step inside shard_map with explicit pmeans; the default is global-
        # batch BN under auto-sharded jit.
        per_replica_bn = (not cfg.model.sync_bn) and mesh.shape["data"] > 1
        # Shared with the static config-matrix verifier (analysis/) so a
        # combination it certifies is exactly one this loop accepts.
        check_step_config(cfg, mesh.shape["data"])
        # Compile-time A/B probes (ops/autotune.py): fused_epilogue="auto"
        # times the epilogue kernels at this model's stage shapes and
        # enables Pallas only where it measured a win; the xent "auto"
        # probe runs inside make_train_step. Host code before the first
        # dispatch — it rides in the compile window, never a throughput
        # interval. Failures degrade to the XLA paths, never kill
        # training.
        from tpu_resnet import ops
        if cfg.model.fused_epilogue == "auto" and ops.is_tpu_backend():
            t_probe = time.time()
            try:
                kernel_batch = (cfg.train.global_batch_size
                                // mesh.shape["data"] if per_replica_bn
                                else cfg.train.global_batch_size)
                ops.probe_model_epilogues(cfg, kernel_batch)
                spans.record("autotune_probe", t_probe, time.time(),
                             op="epilogue")
            except Exception as e:  # noqa: BLE001 - probe must not kill
                log.warning("epilogue autotune probe failed (%s: %s) — "
                            "all epilogue sites stay on XLA",
                            type(e).__name__, e)
        # The xent kernel always sees the PER-DEVICE batch (shard_mapped
        # over 'data' under auto-jit, the local shard under per-replica
        # BN, the full batch only on one device) — probe at that shape,
        # not the global one (b1024-probe/b128-execute would decide at
        # the wrong point of the speedup curve).
        base_step = make_train_step(model, cfg.optim, schedule,
                                    cfg.data.num_classes, augment_fn,
                                    base_rng=step_rng, mesh=mesh,
                                    grad_axis="data" if per_replica_bn else None,
                                    xent_probe_batch=max(
                                        1, cfg.train.global_batch_size
                                        // mesh.shape["data"]),
                                    partitioner=partitioner)
        # zero1 compiles with the partitioner's state layout so the
        # optimizer-slot arguments are per-shard buffers; replicated
        # passes None and keeps the exact historical program.
        state_sharding = (partitioner.state_shardings(state)
                          if partitioner.is_sharded else None)
        # Program registry (tpu_resnet/programs): every program this
        # loop dispatches is constructed through it — identity
        # pass-through (the exact historical jit objects) unless the
        # persistent AOT executable cache is enabled
        # (programs.cache/cache_dir or TPU_RESNET_PROGRAM_CACHE_DIR —
        # the elastic-resume cold-start lever), in which case each
        # program is AOT-compiled over its real avals and round-tripped
        # through <cache_dir>, so a resumed process re-reaches its
        # topology's programs without re-paying XLA.
        prog_reg = programs.ProgramRegistry(cfg, mesh, telemetry=telemetry,
                                            spans=spans, context="train")
        state_avals = programs.state_avals(state)
        if parallel.is_primary() and ops.autotune.decisions():
            # The run's dispatch choices as a reviewable artifact.
            ops.autotune.dump(cfg.train.train_dir)

        step = int(jax.device_get(state.step))
        total = max_steps if max_steps is not None else cfg.train.train_steps

        # Input edge: device-resident (whole split in HBM, batches cut
        # on-device, multi-step dispatch) when it applies, else the streaming
        # host pipeline.
        resident = device_data.should_use(cfg.data)
        host_iter = None
        if resident:
            import tpu_resnet.data as data_lib

            images_np, labels_np = data_lib.load_split(cfg.data, train=True)
            ds = device_data.DeviceDataset(mesh, images_np, labels_np,
                                           cfg.train.global_batch_size,
                                           seed=cfg.train.seed)
            run_chunk = device_data.compile_resident_steps(
                base_step, ds, mesh, max(1, cfg.train.steps_per_call),
                per_replica_bn=per_replica_bn,
                state_sharding=state_sharding,
                program_hook=(programs.staged_chunk_hook(
                                  prog_reg, state_avals,
                                  ds.steps_per_epoch)
                              if prog_reg.cache_enabled else None))
            data_iter = None
        else:
            data_iter, stage, host_iter = build_train_iterator(
                cfg, mesh, start_step=step, injector=injector,
                stop_event=shutdown.event)
            if stage > 1:
                run_staged = device_data.compile_staged_stream_steps(
                    base_step, mesh, per_replica_bn=per_replica_bn,
                    state_sharding=state_sharding,
                    program_hook=(programs.staged_chunk_hook(
                                      prog_reg, state_avals, stage)
                                  if prog_reg.cache_enabled else None))
            else:
                train_step = shard_step(base_step, mesh,
                                        per_replica_bn=per_replica_bn,
                                        state_sharding=state_sharding)
                if prog_reg.cache_enabled:
                    train_step = programs.wrap_train_step(
                        prog_reg, train_step, state_avals)

        meter = ThroughputMeter(cfg.train.global_batch_size,
                                num_chips=mesh.size)
        log.info("training %s/%s to step %d | params %.2fM | mesh %s | "
                 "partition %s | global batch %d | input %s",
                 cfg.model.name, cfg.data.dataset,
                 total, n_params / 1e6, dict(mesh.shape),
                 partitioner.describe(), cfg.train.global_batch_size,
                 "device-resident" if resident else "streaming")

        profiling.maybe_start_server(cfg.train.profiler_port)
        tracer = profiling.StepTracer(cfg.train.train_dir,
                                      cfg.train.profile_steps, spans=spans)

        # Step-time breakdown (tpu_resnet/obs/breakdown.py): data_wait /
        # dispatch / sampled device backlog per log interval, compile time of
        # the first dispatch reported separately. Sampling reuses the existing
        # log boundaries (chunks already end exactly there), so it never
        # changes fusion behavior.
        breakdown = obs.StepBreakdown()
        telemetry.heartbeat(step)
        run_wall0 = time.time()
        start_step = step
        last_ckpt_step = step  # resumed or fresh: the last synced point
        last_log_step = step   # for the per-interval step-time histogram
        first_dispatch = True
        # MFU accounting (obs/mfu.py): per-step FLOPs measured once at
        # first dispatch; converted to model_flops_per_sec / mfu at every
        # log boundary (pure host arithmetic — no device syncs).
        step_flops = None
        device_kind = mesh.devices.flat[0].device_kind
        # Memory ledger (obs/memory.py): the step's HBM budget measured
        # once at first dispatch; live hbm_* gauges sampled at log
        # boundaries; mem_ledger/mem_key/mem_ring (initialized above the
        # try) feed the OOM report in the closer chain.

        meter.rate(step)
        last_summary = step
        last_sync = step  # last step the host fully drained the device at
        m = None  # metrics of the newest dispatched chunk
        stage_buf = None  # current streaming superbatch: (gi, gl, k, offset)
        # Raw input images for the image-summary channel (reference
        # cifar_input.py:118): the resident split's head, or the newest
        # streamed batch; augmented at write time so the summary shows what
        # the model actually saw.
        last_inputs = images_np[:4] if resident else None
        while step < total:
            injector.maybe_sigterm(step)
            injector.maybe_oom(step)  # OOM-forensics drill (doctor)
            if shutdown.requested:
                break  # stop at the chunk boundary; final save below
            tracer.before(step)
            if resident:
                k = _chunk_len(step, total, cfg.train, ds.steps_per_epoch,
                               tracer.boundaries())
                with breakdown.dispatch():
                    state, m = run_chunk(state, step, k)
                step += k
            elif stage > 1:
                if stage_buf is None:
                    with breakdown.data_wait():
                        try:
                            gi, gl, k = next(data_iter)
                        except StopIteration:
                            if shutdown.requested:
                                break  # preempted mid-data-wait: save below
                            raise
                    stage_buf = (gi, gl, k, 0)
                gi, gl, k, off = stage_buf
                # Fuse up to the stage end, clipped to the next log/summary/
                # checkpoint/trace boundary so every hook fires at the exact
                # steps a one-dispatch-per-step loop would fire it.
                c = min(k - off,
                        _chunk_len(step, total, cfg.train, 0,
                                   tracer.boundaries()))
                with breakdown.dispatch():
                    state, m = run_staged(state, gi, gl, off, c)
                step += c
                off += c
                last_inputs = gi  # reference only; sliced at summary time
                stage_buf = None if off >= k else (gi, gl, k, off)
            else:
                with breakdown.data_wait():
                    try:
                        images, labels = next(data_iter)
                    except StopIteration:
                        if shutdown.requested:
                            break  # preempted mid-data-wait: save below
                        raise
                with breakdown.dispatch():
                    state, m = train_step(state, images, labels)
                step += 1
                last_inputs = images
            if watchdog is not None:
                watchdog.progress(step)
            if tracer.after(step, sync=m):
                # Closing a trace window drains the device mid-interval:
                # the backlog the next boundary sample sees only covers
                # steps dispatched since here.
                last_sync = step

            if first_dispatch:
                # The first dispatch pays jit tracing + XLA compile: report
                # it as compile_seconds and re-prime the throughput meter so
                # the first logged images/sec excludes compile time.
                first_dispatch = False
                compile_s = breakdown.first_dispatch_done(m)
                now = time.time()
                spans.record("compile", now - compile_s, now,
                             seconds=round(compile_s, 3), step=start_step)
                telemetry.set("compile_seconds", compile_s)
                if cfg.train.mfu_accounting:
                    # One abstract trace + HLO cost pass (no second XLA
                    # compile); charged to the compile window, not to any
                    # throughput interval — breakdown/meter re-prime below.
                    t_acct = time.time()
                    try:
                        entry = obs.mfu.account_train_step(
                            cfg, mesh, state, base_step,
                            per_replica_bn=per_replica_bn,
                            train_dir=(cfg.train.train_dir
                                       if parallel.is_primary() else None))
                        step_flops = entry.get("flops_per_step")
                        spans.record("mfu_account", t_acct, time.time(),
                                     flops_per_step=step_flops,
                                     source=entry.get("flops_source"))
                    except Exception as e:  # noqa: BLE001 - accounting
                        log.warning(            # must never kill training
                            "mfu accounting failed (%s: %s) — mfu gauges "
                            "stay 0", type(e).__name__, e)
                    breakdown.reset_interval()
                if cfg.train.memory_ledger:
                    # HBM budget of the compiled step (obs/memory.py).
                    # memory_analysis needs a COMPILED program and the
                    # AOT path shares no cache with the jit dispatch:
                    # this is ONE extra XLA compile, charged to the
                    # compile window (meter re-primed below, never a
                    # throughput interval). Degrades to absent.
                    t_mem = time.time()
                    try:
                        # Measure the program THIS run's input edge
                        # dispatches: the fused staged-chunk jit on the
                        # streaming stage>1 path, else the plain sharded
                        # step (the resident path's epoch-buffer chunk
                        # is approximated by its single-step twin —
                        # labeled so on the entry).
                        staged_run = not resident and stage > 1
                        entry = obs.memory.account_train_step(
                            cfg, mesh, state, base_step,
                            per_replica_bn=per_replica_bn,
                            partitioner=partitioner,
                            stage_rows=stage if staged_run else 1,
                            chunk_steps=(max(1, cfg.train.steps_per_call)
                                         if staged_run else 1),
                            variant=("single-step (resident epoch-buffer "
                                     "program approximated)" if resident
                                     else "single-step"),
                            ledger=mem_ledger,
                            train_dir=(cfg.train.train_dir
                                       if parallel.is_primary() else None))
                        mem_key = entry.get("program_key")
                        spans.record(
                            "memory_account", t_mem, time.time(),
                            program_key=mem_key,
                            temp_bytes=entry.get("temp_bytes"),
                            alias_bytes=entry.get("alias_bytes"),
                            peak_bytes=entry.get("peak_bytes"))
                    except Exception as e:  # noqa: BLE001 - accounting
                        log.warning(            # must never kill training
                            "memory ledger failed (%s: %s) — memory.json "
                            "absent for this run", type(e).__name__, e)
                    breakdown.reset_interval()
                if cfg.train.comms_ledger:
                    # Collective summary of the compiled step
                    # (obs/comms.py): op multiset + analytic bytes-on-
                    # wire per mesh axis from the post-partitioner HLO,
                    # plus predicted time-on-wire / comms-fraction from
                    # the per-chip ICI table (feeding step_flops from
                    # the mfu block above when it ran). Same contract
                    # as the memory ledger: ONE extra XLA compile,
                    # charged to the compile window, degrades to
                    # absent.
                    t_comm = time.time()
                    try:
                        staged_run = not resident and stage > 1
                        entry = obs.comms.account_train_step(
                            cfg, mesh, state, base_step,
                            per_replica_bn=per_replica_bn,
                            partitioner=partitioner,
                            stage_rows=stage if staged_run else 1,
                            chunk_steps=(max(1, cfg.train.steps_per_call)
                                         if staged_run else 1),
                            variant=("single-step (resident epoch-buffer "
                                     "program approximated)" if resident
                                     else "single-step"),
                            flops_per_step=step_flops,
                            train_dir=(cfg.train.train_dir
                                       if parallel.is_primary() else None))
                        frac = entry.get("predicted_comms_fraction")
                        if frac is not None:
                            telemetry.set("predicted_comms_fraction",
                                          float(frac))
                        spans.record(
                            "comms_account", t_comm, time.time(),
                            program_key=entry.get("program_key"),
                            collective_count=entry.get("collective_count"),
                            wire_bytes_per_device=entry.get(
                                "wire_bytes_per_device"),
                            predicted_comms_fraction=frac)
                    except Exception as e:  # noqa: BLE001 - accounting
                        log.warning(            # must never kill training
                            "comms ledger failed (%s: %s) — comms.json "
                            "absent for this run", type(e).__name__, e)
                    breakdown.reset_interval()
                meter.rate(step)
                last_sync = step
                last_log_step = step

            if step % cfg.train.log_every == 0 or step == total:
                breakdown.sample_device(m, step - last_sync)
                m = {k: float(v) for k, v in jax.device_get(m).items()}
                last_sync = step
                if sentinel.check(step, m["loss"]):
                    # Divergence rollback: restore the last checkpoint and
                    # (streaming path) advance the data stream past the bad
                    # window so the replayed steps see fresh batches. The
                    # check reuses this boundary's host-synced metrics —
                    # zero extra device syncs, fusion/chunking unchanged.
                    ckpt.wait()
                    if ckpt.latest_step() is None:
                        raise sentinel.no_checkpoint(step, m["loss"])
                    bad_step = step
                    state = ckpt.restore(state, discard_failed=True)
                    step = int(jax.device_get(state.step))
                    spans.event("nan_rollback", from_step=bad_step,
                                to_step=step, loss=str(m["loss"]),
                                retry=sentinel.rollbacks)
                    telemetry.set("fault_nan_rollbacks", sentinel.rollbacks)
                    if not resident:
                        # The stream is deterministic in (seed, step):
                        # restart it at bad_step so steps (to_step,
                        # bad_step] consume the batches *after* the bad
                        # window instead of replaying it.
                        if hasattr(data_iter, "close"):
                            data_iter.close()  # release the H2D producer
                        host_iter.close()
                        data_iter, stage, host_iter = build_train_iterator(
                            cfg, mesh, start_step=bad_step,
                            injector=injector, stop_event=shutdown.event)
                        stage_buf = None
                    m = None
                    breakdown.reset_interval()
                    meter.rate(step)  # re-prime the throughput baseline
                    last_sync = step
                    last_ckpt_step = step
                    last_log_step = step
                    telemetry.heartbeat(step)
                    continue
                rate = meter.rate(step)
                if rate:
                    m.update(rate)
                    # Step-time histogram: the interval's mean step time,
                    # weighted by its step count — the p50/p95/p99 the
                    # plot panel and /metrics expose.
                    telemetry.observe(
                        "train_step_ms", 1e3 / rate["steps_per_sec"],
                        n=max(1, step - last_log_step))
                    for q in (0.50, 0.95, 0.99):
                        m[f"train_step_ms_p{int(q * 100)}"] = round(
                            telemetry.hist_percentile("train_step_ms", q),
                            3)
                    if step_flops:
                        # Model FLOPs utilization (obs/mfu.py): achieved
                        # model FLOP/s vs the mesh's aggregate peak.
                        mfs = step_flops * rate["steps_per_sec"]
                        m["model_flops_per_sec"] = mfs
                        u = obs.mfu.mfu(mfs, device_kind, mesh.size)
                        if u is not None:
                            m["mfu"] = round(u, 4)
                last_log_step = step
                m.update(breakdown.interval())
                # Live device-memory gauges (obs/memory.py): pure host
                # introspection at this already-synced boundary — zero
                # extra device syncs; {} on backends without stats.
                hbm = obs.memory.sample_device_memory()
                if hbm:
                    m.update(hbm)
                    mem_ring.add(step, hbm)
                if host_iter is not None and hasattr(host_iter, "stats"):
                    # Engine cause-signal for data_wait: ring occupancy
                    # (0 while the step waits = producer-bound) and the
                    # interval decode rate.
                    m.update(host_iter.stats())
                if data_iter is not None and hasattr(data_iter, "stats"):
                    # Double-buffered H2D: interval transfer rate +
                    # overlap fraction, plus the finished transfers as
                    # spans for the trace-export transfer lane.
                    m.update(data_iter.stats())
                    for t0, t1, nbytes, c in data_iter.drain_transfers():
                        spans.record("h2d_transfer", t0, t1,
                                     bytes=nbytes, steps=c)
                telemetry.update(m)
                telemetry.set("checkpoint_lag_steps", step - last_ckpt_step)
                telemetry.heartbeat(step)
                log.info("step %d | loss %.4f | precision %.4f | lr %.4g%s"
                         " | wait %d%%",
                         step, m["loss"], m["precision"], m["learning_rate"],
                         f" | {m['steps_per_sec']:.2f} st/s "
                         f"({m['images_per_sec']:.0f} img/s)" if rate else "",
                         round(m["data_wait_frac"] * 100))
                # Summaries reuse the logged measurement, tagged with the
                # step it was measured at (never a stale value under a
                # different step).
                if (step - last_summary >= cfg.train.summary_every
                        or step == total):
                    metrics.write(step, m)
                    last_summary = step
            if (cfg.train.image_summary_every > 0 and metrics.enabled
                    and last_inputs is not None
                    and step % cfg.train.image_summary_every == 0):
                raw = _local_image_slice(last_inputs)
                aug = augment_fn(jax.random.PRNGKey(step), jnp.asarray(raw))
                metrics.write_images(step, jax.device_get(aug))
            if step % cfg.train.checkpoint_every == 0 or step == total:
                # A checkpoint boundary that is NOT a log boundary hasn't
                # had its loss checked (possible when checkpoint_every is
                # not a multiple of log_every): never persist NaN state —
                # it would become the rollback target. The scalar read
                # piggybacks on the save's own full-state sync, so this
                # adds no standalone device sync.
                if (sentinel.enabled and m is not None
                        and step % cfg.train.log_every != 0
                        and not math.isfinite(
                            float(jax.device_get(m["loss"])))):
                    log.warning("skipping checkpoint save at step %d: "
                                "non-finite loss — rollback engages at "
                                "the next log boundary", step)
                    spans.event("checkpoint_save_skipped_nonfinite",
                                step=step)
                elif ckpt.save(step, state):
                    last_ckpt_step = step
                    telemetry.set("checkpoint_lag_steps", 0)
                    record_topology()
        if shutdown.requested and step < total:
            # Preemption honored at the chunk boundary: force a final save
            # so the resume loses zero steps, then mark the event. The
            # Preempted raise (the supervisor's distinct exit code) happens
            # after the closer chain below has shut telemetry down cleanly.
            log.warning("preemption stop at step %d — saving a final "
                        "checkpoint before exit", step)
            spans.event("preempt_stop", step=step, signum=shutdown.signum)
            telemetry.set("fault_preemptions", 1.0)
            if injector.plan.preempt_burst > 0:
                telemetry.set("fault_preempt_burst",
                              float(injector.burst_fired))
            if step > last_ckpt_step and ckpt.save(step, state, force=True):
                last_ckpt_step = step
                record_topology()
    finally:
        # One shutdown path for clean exits AND exceptions. Each closer
        # runs even if an earlier one raises (a failed ckpt.wait must not
        # leave the run span unwritten or the telemetry server answering
        # /healthz for a dead loop); a closer error surfaces on a clean
        # exit but never masks an in-flight loop exception.
        import sys

        closer_errs = []

        def _close(fn):
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - shutdown must finish
                closer_errs.append(e)
                log.warning("shutdown closer %s failed: %s",
                            getattr(fn, "__name__", fn), e)

        exc_type, exc_val = sys.exc_info()[:2]
        if exc_val is not None and obs.memory.is_oom_error(exc_val):
            # OOM forensics FIRST (cheap, pure host writes): the ledger,
            # the recent hbm samples, a live-array census and the
            # offending program key land in <train_dir>/oom_report.json
            # before anything else touches the dying process — a pod OOM
            # becomes a diagnosable artifact, not a dead log line. The
            # original exception still propagates.
            _close(lambda: obs.memory.write_oom_report(
                cfg.train.train_dir, exc_val, context="train", step=step,
                program_key=mem_key, ledger=mem_ledger,
                samples=mem_ring.snapshot(), run_id=run_id))
            _close(lambda: spans.event("oom", step=step,
                                       program_key=mem_key))
        if (rcfg.emergency_save and exc_type is not None
                and ckpt is not None
                and not issubclass(exc_type, (resilience.DivergenceError,
                                              KeyboardInterrupt))
                and step > last_ckpt_step):
            # In-flight exception with unsaved progress: one guarded
            # best-effort save, so the crash loses at most the current
            # interval. Excluded: DivergenceError (the live state is NaN —
            # persisting it would poison the resume) and an operator's
            # escalated abort (they asked for NOW, not a slow save).
            def _emergency_save():
                if ckpt.save(step, state, force=True):
                    spans.event("emergency_save", step=step)
                    record_topology()
                    log.warning("emergency checkpoint saved at step %d "
                                "after in-flight %s", step,
                                exc_type.__name__)

            _close(_emergency_save)
        if tracer is not None:
            _close(lambda: tracer.close(sync=m))
        if ckpt is not None:
            _close(ckpt.wait)
        if run_wall0 is not None:  # the loop actually started
            _close(lambda: spans.record(
                "run", run_wall0, time.time(), start_step=start_step,
                stop_step=step, train_steps=total))
        _close(spans.close)
        if server is not None:
            _close(server.close)
        if metrics is not None:
            _close(metrics.close)
        if data_iter is not None and hasattr(data_iter, "close"):
            _close(data_iter.close)  # H2D producer thread + device slots
        if host_iter is not None:
            _close(host_iter.close)
        if watchdog is not None:
            _close(watchdog.close)
        if shutdown is not None:
            _close(shutdown.uninstall)
        if closer_errs and sys.exc_info()[0] is None:
            raise closer_errs[0]
    if shutdown is not None and shutdown.requested \
            and total is not None and step < total:
        raise resilience.Preempted(step, state=state, signum=shutdown.signum)
    return state
