"""Orbax checkpointing — replaces MonitoredTrainingSession's Saver
(reference resnet_cifar_train.py:330-342, ``save_checkpoint_steps=1000``)
and the implicit resume-on-restart contract
(resnet_imagenet_train.py:267-270).

Only process 0 drives saves (the reference's chief / Horovod rank-0 rule,
resnet_cifar_main.py:328) — orbax handles the multi-host coordination for
sharded arrays itself. Consumers: the train loop (periodic save + resume),
the polling evaluator (latest_step watching — the analog of
``tf.train.get_checkpoint_state`` polling, resnet_cifar_eval.py:102), the
export path and the inspector tool.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 5, spans=None):
        """``spans`` (an ``obs.SpanTracer``) records checkpoint_save /
        checkpoint_restore spans on the run's events.jsonl timeline."""
        self.directory = os.path.abspath(directory)
        self._spans = spans
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                create=True,
                enable_async_checkpointing=True,
            ),
        )

    def save(self, step: int, state, force: bool = False) -> bool:
        import time

        t0 = time.time()
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state),
                               force=force)
        if saved and self._spans is not None:
            # Async checkpointing: the span covers the blocking enqueue
            # (serialization handoff), not the background write.
            self._spans.record("checkpoint_save", t0, time.time(),
                               step=int(step), **{"async": True})
        return saved

    def restore(self, state_template, step: Optional[int] = None):
        """Restore into the structure/shardings of ``state_template``."""
        import time

        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                          state_template)
        t0 = time.time()
        restored = self._mgr.restore(step,
                                     args=ocp.args.StandardRestore(abstract))
        if self._spans is not None:
            self._spans.record("checkpoint_restore", t0, time.time(),
                               step=int(step))
        return restored

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return self._mgr.all_steps()

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()


def latest_step_in(directory: str) -> Optional[int]:
    """Cheap latest-checkpoint probe for pollers (the eval sidecar's analog
    of ``tf.train.get_checkpoint_state``, resnet_cifar_eval.py:102)."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = ocp.utils.checkpoint_steps(directory)
    return max(steps) if steps else None
