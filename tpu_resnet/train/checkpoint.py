"""Orbax checkpointing — replaces MonitoredTrainingSession's Saver
(reference resnet_cifar_train.py:330-342, ``save_checkpoint_steps=1000``)
and the implicit resume-on-restart contract
(resnet_imagenet_train.py:267-270).

Only process 0 drives saves (the reference's chief / Horovod rank-0 rule,
resnet_cifar_main.py:328) — orbax handles the multi-host coordination for
sharded arrays itself. Consumers: the train loop (periodic save + resume),
the polling evaluator (latest_step watching — the analog of
``tf.train.get_checkpoint_state`` polling, resnet_cifar_eval.py:102), the
export path and the inspector tool.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 5, spans=None,
                 topology: Optional[dict] = None):
        """``spans`` (an ``obs.SpanTracer``) records checkpoint_save /
        checkpoint_restore spans on the run's events.jsonl timeline.
        ``topology`` (a ``resilience.elastic`` topology record) names
        the mesh/partition THIS consumer restores into — joined with the
        directory's recorded save topology in restore errors, so a
        template/shard mismatch reads as "saved on mesh8 zero1, you
        asked for mesh4 replicated", not a raw pytree diff."""
        self.directory = os.path.abspath(directory)
        self._spans = spans
        self._topology = topology
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                create=True,
                enable_async_checkpointing=True,
            ),
        )

    def save(self, step: int, state, force: bool = False) -> bool:
        import time

        t0 = time.time()
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state),
                               force=force)
        if saved and self._spans is not None:
            # Async checkpointing: the span covers the blocking enqueue
            # (serialization handoff), not the background write.
            self._spans.record("checkpoint_save", t0, time.time(),
                               step=int(step), **{"async": True})
        return saved

    def restore(self, state_template, step: Optional[int] = None,
                fallback: Optional[bool] = None,
                discard_failed: bool = False):
        """Restore into the structure/shardings of ``state_template``.

        ``fallback`` (default: on exactly when ``step`` is None) is the
        corrupt-checkpoint recovery path: if the newest checkpoint fails to
        restore — torn write from a preempted host, bad storage — fall back
        through ``all_steps()`` to the newest *restorable* one (we keep
        ``keep``, default 5) instead of raising. An explicitly requested
        step (evaluator, export) fails loudly by default: silently serving
        an older step than asked for would corrupt eval curves.

        ``discard_failed`` additionally deletes/quarantines the steps that
        failed to restore once a fallback succeeds. Only the *trainer's*
        resume path sets it (the process that owns the directory and will
        re-reach those step numbers, colliding on save): a read-only
        consumer (export, a notebook) must never destroy a checkpoint that
        merely failed transiently for *it*."""
        import logging
        import time

        if fallback is None:
            fallback = step is None
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        candidates = [step]
        if fallback:
            candidates += sorted((s for s in self.all_steps() if s < step),
                                 reverse=True)
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                          state_template)
        log = logging.getLogger("tpu_resnet")
        last_err = None
        failed = []
        for i, cand in enumerate(candidates):
            t0 = time.time()
            try:
                restored = self._mgr.restore(
                    cand, args=ocp.args.StandardRestore(abstract))
            except Exception as e:  # noqa: BLE001 - any restore failure
                last_err = e
                failed.append(cand)
                log.warning("checkpoint step %d failed to restore (%s: %s)%s",
                            cand, type(e).__name__, e,
                            " — falling back to the previous step"
                            if i + 1 < len(candidates) else "")
                if self._spans is not None:
                    self._spans.record(
                        "checkpoint_restore_failed", t0, time.time(),
                        step=int(cand),
                        error=f"{type(e).__name__}: {e}"[:200])
                continue
            attrs = {"step": int(cand)}
            if cand != candidates[0]:
                attrs["fallback_from_step"] = int(candidates[0])
            if self._spans is not None:
                self._spans.record("checkpoint_restore", t0, time.time(),
                                   **attrs)
            if discard_failed:
                # Trainer resume: the unrestorable newer steps must go —
                # latest_step()/pollers would keep finding them, and the
                # resumed run will re-reach those step numbers and collide
                # with the corrupt directories on save.
                self._discard(failed, log)
            return restored
        raise RuntimeError(
            f"no restorable checkpoint in {self.directory}: all of "
            f"{candidates} failed; newest error: "
            f"{type(last_err).__name__}: {last_err}"
            f"{self._topology_hint()}") from last_err

    def _topology_hint(self) -> str:
        """Topology context for a failed restore: the directory's
        recorded save topology vs what this consumer asked for. A shard/
        template mismatch after a capacity change surfaces as an opaque
        pytree/sharding error without this — naming both topologies
        turns it into an actionable line (docs/RESILIENCE.md)."""
        from tpu_resnet.resilience import elastic

        saved = elastic.read_topology(self.directory)
        if saved is None and self._topology is None:
            return ""
        hint = (f"\ncheckpoint topology: {elastic.describe(saved)}"
                f"\nrequested topology:  {elastic.describe(self._topology)}")
        if saved and self._topology and any(
                saved.get(k) != self._topology.get(k)
                for k in ("mesh_shape", "partition", "global_batch")):
            hint += ("\nthe topologies differ — an elastic resume "
                     "reshards through the partitioner template "
                     "(resilience/elastic.py), but global array shapes "
                     "and the global batch must stay compatible")
        return hint

    def _discard(self, steps, log) -> None:
        """Remove checkpoints that failed to restore (delete via orbax so
        its step cache stays coherent; quarantine-rename as a fallback)."""
        for bad in steps:
            try:
                self._mgr.delete(bad)
                log.warning("removed unrestorable checkpoint step %d", bad)
            except Exception:  # noqa: BLE001 - best-effort quarantine
                src = os.path.join(self.directory, str(bad))
                try:
                    os.rename(src, src + ".corrupt")
                    log.warning("quarantined unrestorable checkpoint step "
                                "%d as %s.corrupt", bad, src)
                except OSError as e:
                    log.warning("could not remove corrupt checkpoint step "
                                "%d: %s", bad, e)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return self._mgr.all_steps()

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()


def partitioned_template(cfg, mesh, model=None):
    """Abstract TrainState restore template laid out by the run's
    partitioner — the ONE way every read-only consumer (eval sidecar,
    serve hot-reload, export) describes what restore should produce.

    Built with ``jax.eval_shape`` + sharded ShapeDtypeStructs, so no
    device buffer is ever allocated for the template itself, and orbax
    restores each leaf STRAIGHT into the layout ``cfg.mesh.partition``
    declares: a zero1 checkpoint restores into its optimizer-slot
    shards without materializing a replicated copy on any device.

    Cross-TOPOLOGY restores are an EXPLICIT reshard, never a silent
    corruption: orbax checkpoints store global logical arrays (layout-
    free), so restoring a zero1-saved checkpoint into a replicated
    template (or vice versa), or a mesh8-saved checkpoint into a mesh4
    template (or vice versa — ``mesh`` here is simply the mesh the
    CURRENT process built over the devices it actually has,
    resilience/elastic.py), reassembles the same global values in the
    template's layout — pinned by tests/test_partition.py and the
    tests/test_elastic.py cross-mesh matrix. A partition mode the
    partitioner cannot satisfy on this mesh raises its per-leaf
    ``validate`` error here, before any restore I/O."""
    import jax
    import jax.numpy as jnp

    from tpu_resnet import parallel
    from tpu_resnet.models import build_model
    from tpu_resnet.train import schedule as sched_lib
    from tpu_resnet.train.state import init_state

    if model is None:
        model = build_model(cfg)
    schedule = sched_lib.build_schedule(cfg.optim, cfg.train)
    size = cfg.data.resolved_image_size
    abstract = jax.eval_shape(
        lambda: init_state(model, cfg.optim, schedule,
                           jax.random.PRNGKey(0),
                           jnp.zeros((1, size, size, 3))))
    partitioner = parallel.make_partitioner(cfg.mesh, mesh)
    return partitioner.abstract_state(abstract)


def latest_step_in(directory: str) -> Optional[int]:
    """Cheap latest-checkpoint probe for pollers (the eval sidecar's analog
    of ``tf.train.get_checkpoint_state``, resnet_cifar_eval.py:102)."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = ocp.utils.checkpoint_steps(directory)
    return max(steps) if steps else None


def restore_with_retry(ckpt, template, step: int, retries: int = 3,
                       backoff_sec: float = 0.5, sleep=None):
    """Restore ``step`` with bounded exponential-backoff retries.

    The trainer's saves are async: a poller (eval sidecar, serve
    hot-reload) can see a step whose directory is still mid-commit, and a
    single transient restore failure used to kill the whole polling loop.
    Returns the restored state, or None after ``retries`` failures — the
    caller skips-and-logs the step instead of crashing; the next committed
    checkpoint restores fine. Shared by ``evaluation/evaluator.py`` and
    ``serve/backend.py`` (extracted so the backoff/skip-and-log logic
    can't drift between the two pollers)."""
    import logging
    import time

    if sleep is None:
        sleep = time.sleep
    log = logging.getLogger("tpu_resnet")
    for attempt in range(max(1, retries)):
        try:
            return ckpt.restore(template, step=step)
        except Exception as e:  # noqa: BLE001 - any restore failure
            wait = backoff_sec * (2 ** attempt)
            log.warning("restore of checkpoint step %d failed "
                        "(attempt %d/%d, %s: %s)%s", step, attempt + 1,
                        max(1, retries), type(e).__name__, e,
                        f"; retrying in {wait:.1f}s"
                        if attempt + 1 < max(1, retries) else "")
            if attempt + 1 < max(1, retries):
                sleep(wait)
    return None


class CheckpointPoller:
    """Newest-step watcher over a train dir — the shared poll half of the
    eval sidecar and the serve hot-reload loop. ``poll()`` returns a step
    exactly once: a step is reported only while it is the newest AND has
    not been marked seen (``mark_seen`` — callers mark both successful
    restores and skipped-after-retries steps so the poll never spins on a
    checkpoint that will not restore)."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        self.last_seen: Optional[int] = None

    def poll(self) -> Optional[int]:
        step = latest_step_in(self.directory)
        if step is not None and step != self.last_seen:
            return step
        return None

    def mark_seen(self, step: int) -> None:
        self.last_seen = int(step)
