"""Learning-rate schedules as pure functions of the global step.

The reference mutates the LR by rewriting a feed_dict inside a session hook
(reference resnet_cifar_train.py:291-311; warmup variant
resnet_imagenet_train.py:236-260) — impossible under jit. Here every schedule
is a jit-traceable ``step -> lr`` function, so the LR lives inside the
compiled train step.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def piecewise_constant(boundaries: Sequence[int],
                       values: Sequence[float]) -> Schedule:
    """lr = values[i] for boundaries[i-1] <= step < boundaries[i]."""
    if len(values) != len(boundaries) + 1:
        raise ValueError("need len(values) == len(boundaries) + 1")
    b = jnp.asarray(boundaries, jnp.int32)
    v = jnp.asarray(values, jnp.float32)

    def schedule(step):
        idx = jnp.sum(step >= b)
        return v[idx]

    return schedule


def cifar_piecewise(base_lr: float = 0.1) -> Schedule:
    """0.1 → 0.01 → 0.001 → 0.0001 at steps 40k/60k/80k
    (reference resnet_cifar_train.py:302-311, resnet_single.py:84-104)."""
    scale = base_lr / 0.1
    return piecewise_constant(
        (40_000, 60_000, 80_000),
        tuple(scale * x for x in (0.1, 0.01, 0.001, 0.0001)))


def imagenet_warmup(warmup_steps: int = 6240,
                    warmup_init_lr: float = 0.1,
                    peak_lr: float = 0.4,
                    boundaries: Sequence[int] = (37_440, 74_880, 99_840)) -> Schedule:
    """Intel-Caffe 8-node recipe: linear warmup 0.1→0.4 over 6240 steps, then
    0.4 / 0.04 / 0.004 / 0.0004 at 37440/74880/99840
    (reference resnet_imagenet_train.py:236-260, README.md:39-40)."""
    b = jnp.asarray(boundaries, jnp.int32)
    v = jnp.asarray([peak_lr, peak_lr * 0.1, peak_lr * 0.01, peak_lr * 0.001],
                    jnp.float32)

    def schedule(step):
        frac = jnp.minimum(step, warmup_steps) / max(warmup_steps, 1)
        warm = warmup_init_lr + (peak_lr - warmup_init_lr) * frac
        idx = jnp.sum(step >= b)
        return jnp.where(step < warmup_steps, warm, v[idx])

    return schedule


def constant(lr: float) -> Schedule:
    def schedule(step):
        del step
        return jnp.float32(lr)

    return schedule


def cosine(base_lr: float, total_steps: int, warmup_steps: int = 0,
           final_frac: float = 0.0) -> Schedule:
    """Linear warmup then cosine decay — not in the reference; provided as the
    modern default for TPU-scale runs."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = base_lr * step / max(warmup_steps, 1)
        progress = (step - warmup_steps) / max(total_steps - warmup_steps, 1)
        progress = jnp.clip(progress, 0.0, 1.0)
        cos = final_frac + (1 - final_frac) * 0.5 * (
            1 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < warmup_steps, warm, base_lr * cos)

    return schedule


def build_schedule(optim_cfg, train_cfg) -> Schedule:
    """Build from OptimConfig (+ TrainConfig for totals)."""
    name = optim_cfg.schedule
    if name == "cifar_piecewise":
        if optim_cfg.boundaries:
            return piecewise_constant(optim_cfg.boundaries, optim_cfg.values)
        return cifar_piecewise(optim_cfg.base_lr)
    if name == "imagenet_warmup":
        kwargs = {}
        if optim_cfg.boundaries:
            kwargs["boundaries"] = optim_cfg.boundaries
        return imagenet_warmup(optim_cfg.warmup_steps,
                               optim_cfg.warmup_init_lr,
                               peak_lr=optim_cfg.base_lr * 4, **kwargs)
    if name == "constant":
        return constant(optim_cfg.base_lr)
    if name == "cosine":
        return cosine(optim_cfg.base_lr, train_cfg.train_steps,
                      optim_cfg.warmup_steps)
    raise ValueError(f"unknown schedule {name!r}")
