"""Functional train state — replaces the reference's graph collections,
global_step variable and session hooks (reference resnet_model.py:45-67,
resnet_cifar_train.py:275-311) with one immutable pytree."""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray          # int32 scalar — the reference's global_step
    params: Any
    batch_stats: Any           # BN moving mean/var (fp32)
    opt_state: Any

    @classmethod
    def create(cls, params, batch_stats, tx: optax.GradientTransformation):
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   batch_stats=batch_stats, opt_state=tx.init(params))


def build_optimizer(optim_cfg, schedule) -> optax.GradientTransformation:
    """sgd / momentum(0.9) per reference resnet_model.py:96-99.

    Weight decay is *not* here — the reference adds L2 to the loss over all
    trainable variables (resnet_model.py:85-86), which interacts with
    momentum differently than decoupled decay; the train step reproduces
    that. The LR schedule is folded into the transformation as a pure
    function of the optimizer step.
    """
    if optim_cfg.optimizer == "sgd":
        return optax.sgd(schedule)
    if optim_cfg.optimizer == "momentum":
        return optax.sgd(schedule, momentum=optim_cfg.momentum)
    raise ValueError(f"unknown optimizer {optim_cfg.optimizer!r}")


def init_state(model, optim_cfg, schedule, rng: jax.Array,
               sample_batch: jnp.ndarray) -> TrainState:
    variables = model.init(rng, sample_batch, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    tx = build_optimizer(optim_cfg, schedule)
    return TrainState.create(params, batch_stats, tx)


def init_partitioned_state(model, optim_cfg, schedule, rng: jax.Array,
                           sample_batch: jnp.ndarray,
                           partitioner) -> TrainState:
    """Init + validate + place: the partitioner
    (``parallel.StatePartitioner``) owns where every leaf of the fresh
    state lives on the mesh — replicated mode reproduces the historical
    ``device_put(state, replicated(mesh))`` exactly; zero1 lands the
    optimizer slots directly in their shards. ``validate`` runs the full
    rule set against the real state tree FIRST, so an unshardable
    (model × mesh × partition) combination dies with per-leaf messages
    before any device transfer or compile is paid.

    Init runs on this process's first local device (``jax.devices()[0]``
    may be a non-addressable remote device on non-primary hosts)."""
    with jax.default_device(jax.local_devices()[0]):
        state = init_state(model, optim_cfg, schedule, rng, sample_batch)
    partitioner.validate(state)
    return partitioner.shard_state(state)


def param_count(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
