"""Metrics/observability — the reference's three channels
(SURVEY.md §5: TensorBoard summaries, console LoggingTensorHook, per-task
log files) rebuilt as one writer:

- console lines every ``log_every`` steps with step/loss/precision/lr and
  measured steps/sec + images/sec (reference resnet_cifar_train.py:282-287
  derived throughput from LoggingTensorHook timestamps),
- append-only ``metrics.jsonl`` scalars (machine-readable superset of the
  summary-file channel, resnet_cifar_train.py:275-280),
- optional TensorBoard event files when TF is importable (kept out of the
  import path — the framework does not depend on TF).

Only process 0 writes (chief-only summary hook, resnet_cifar_train.py:337).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, Optional

import jax

log = logging.getLogger("tpu_resnet")


class MetricsWriter:
    def __init__(self, directory: str, enabled: bool = True,
                 tensorboard: bool = True, tb_flush_secs: float = 10.0):
        self.enabled = enabled
        self.directory = directory
        self._jsonl = None
        self._tb = None
        self._tf = None  # TF module, imported once at init (not per write)
        self._tb_flush_secs = tb_flush_secs
        self._tb_last_flush = time.monotonic()
        if not enabled:
            return
        os.makedirs(directory, exist_ok=True)
        self._jsonl = open(os.path.join(directory, "metrics.jsonl"), "a",
                           buffering=1)
        if tensorboard:
            try:
                import tensorflow as tf  # type: ignore
                self._tf = tf
                self._tb = tf.summary.create_file_writer(directory)
            except Exception:
                self._tb = None

    def _tb_maybe_flush(self, force: bool = False) -> None:
        """Flush the TB event file on an interval (or at close), not on
        every scalar write — per-write flushes serialized the whole event
        pipeline behind the filesystem."""
        now = time.monotonic()
        if force or now - self._tb_last_flush >= self._tb_flush_secs:
            self._tb.flush()
            self._tb_last_flush = now

    def write(self, step: int, scalars: Dict[str, float]) -> None:
        if not self.enabled or self._jsonl is None:
            return
        rec = {"step": int(step), "wall": time.time()}
        rec.update({k: float(v) for k, v in scalars.items()})
        self._jsonl.write(json.dumps(rec) + "\n")
        if self._tb is not None:
            with self._tb.as_default():
                for k, v in scalars.items():
                    self._tf.summary.scalar(k, float(v), step=int(step))
            self._tb_maybe_flush()

    def write_images(self, step: int, images, name: str = "input_images",
                     max_images: int = 4) -> None:
        """Input-batch image summary (reference cifar_input.py:118 wrote
        the augmented training batch via tf.summary.image). ``images`` is
        a [B,H,W,C] array, float (standardized/mean-subtracted) or uint8;
        each image is min-max normalized for display. Written to
        TensorBoard when available, and always as a PNG grid under
        ``<dir>/images/`` so the channel exists without TF."""
        if not self.enabled or self._jsonl is None:
            return
        import numpy as np

        imgs = np.asarray(images)[:max_images].astype(np.float32)
        lo = imgs.min(axis=(1, 2, 3), keepdims=True)
        hi = imgs.max(axis=(1, 2, 3), keepdims=True)
        imgs = ((imgs - lo) / np.maximum(hi - lo, 1e-6) * 255).astype(
            np.uint8)
        if self._tb is not None:
            with self._tb.as_default():
                self._tf.summary.image(name, imgs, step=int(step),
                                       max_outputs=max_images)
            self._tb_maybe_flush()
        try:
            from PIL import Image

            grid = np.concatenate(list(imgs), axis=1)  # side-by-side strip
            img_dir = os.path.join(self.directory, "images")
            os.makedirs(img_dir, exist_ok=True)
            Image.fromarray(grid).save(
                os.path.join(img_dir, f"{name}_step{int(step)}.png"))
        except Exception:  # PIL missing/headless quirks must not kill train
            pass

    def close(self) -> None:
        """Idempotent: double-close and write-after-close are no-ops, so
        shutdown races (sidecar threads, atexit, finally blocks) never die
        on a closed-file ValueError."""
        if self._jsonl is not None:
            jsonl, self._jsonl = self._jsonl, None
            jsonl.close()
        if self._tb is not None:
            tb, self._tb = self._tb, None
            self._tb_maybe_flush_writer_close(tb)

    @staticmethod
    def _tb_maybe_flush_writer_close(tb) -> None:
        try:
            tb.flush()
            tb.close()
        except Exception:  # TF teardown-order quirks must not kill shutdown
            pass


class ThroughputMeter:
    """steps/sec + images/sec (+ per-chip) between log points — the
    steps/s / images/s/chip comparison axes of the reference's published
    tables (SURVEY.md §6, README.md:20-51)."""

    def __init__(self, global_batch: int, num_chips: int = 0):
        self.global_batch = global_batch
        self.num_chips = num_chips or jax.device_count()
        self._t = time.perf_counter()
        self._step = None

    def rate(self, step: int) -> Optional[Dict[str, float]]:
        now = time.perf_counter()
        out = None
        if self._step is not None and step > self._step and now > self._t:
            sps = (step - self._step) / (now - self._t)
            out = {"steps_per_sec": sps,
                   "images_per_sec": sps * self.global_batch,
                   "images_per_sec_per_chip":
                       sps * self.global_batch / self.num_chips}
        self._t = now
        self._step = step
        return out
