"""Compiled train/eval steps over a device mesh.

This module is where the reference's entire distribution machinery —
``SyncReplicasOptimizer`` gradient accumulation over gRPC parameter servers
(reference resnet_model.py:102-113) and Horovod's NCCL allreduce
(resnet_model.py:115-117) — collapses into *one* jitted SPMD function: the
batch is sharded over the mesh's ``data`` axis, parameters are replicated,
and XLA inserts the ICI all-reduces that the sharding math requires. The
same compiled function is the single-device program when the mesh has one
device (reference serial branch, resnet_cifar_train.py:313-326).

Step semantics (reference file:line):
- loss = softmax cross-entropy on one-hot labels (resnet_model.py:76-80)
  + weight_decay * Σ l2_loss(w) over trainable variables
  (resnet_model.py:85-86; tf.nn.l2_loss = sum(w²)/2).
- BN statistics update inside the step — the analog of running update_ops as
  control deps of minimize (resnet_model.py:120-122). Under global-batch jit
  semantics BN moments are computed over the *global* batch (synced BN);
  the reference's per-replica BN is the shard_map variant.
- LR is a pure function of step (schedule.py) evaluated inside the step;
  exposed in metrics like the reference's learning_rate summary
  (resnet_model.py:92-93).
- Train-precision metric from argmax(logits) == label
  (resnet_cifar_train.py:271-273).
- Augmentation runs on-device at the top of the step with a per-step RNG
  derived from fold_in(base, step) — deterministic on resume.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_resnet.train.state import TrainState, build_optimizer


def softmax_xent(logits: jnp.ndarray, labels: jnp.ndarray,
                 num_classes: int, label_smoothing: float = 0.0) -> jnp.ndarray:
    """Mean softmax cross-entropy on integer labels (one-hot inside, per
    reference resnet_model.py:76-80 / cifar_input.py:104-108)."""
    onehot = jax.nn.one_hot(labels, num_classes, dtype=logits.dtype)
    if label_smoothing:
        onehot = (onehot * (1 - label_smoothing)
                  + label_smoothing / num_classes)
    return optax.softmax_cross_entropy(logits, onehot).mean()


def l2_weight_penalty(params, include_bn: bool) -> jnp.ndarray:
    """weight_decay · Σ sum(w²)/2 over trainable vars
    (reference resnet_model.py:85-86). ``include_bn=False`` drops the 1-D
    scale/bias leaves (the modern variant)."""
    total = jnp.float32(0.0)
    for leaf in jax.tree_util.tree_leaves(params):
        if not include_bn and leaf.ndim <= 1:
            continue
        total += jnp.sum(jnp.square(leaf.astype(jnp.float32))) / 2
    return total


def check_step_config(cfg, data_axis: int) -> None:
    """Config-space legality gate for a compiled step, shared by the
    train loop and the static config-matrix verifier
    (tpu_resnet/analysis/configmatrix.py) so both enforce the SAME rules:
    a combination the verifier certifies is exactly one the loop accepts.

    The fused Pallas kernels take batch moments over the batch the kernel
    sees; their supported multi-chip dispatch is shard_map-explicit (each
    replica's Pallas call gets its concrete local shard — per-replica BN,
    the reference's semantics, resnet_model.py:120-122). Global-batch
    sync-BN under auto-sharded jit is not implemented for the fused
    custom call: fail loudly rather than ship unclear moment semantics
    (VERDICT r4 item 5)."""
    from tpu_resnet.parallel.partition import check_partition_mode

    per_replica_bn = (not cfg.model.sync_bn) and data_axis > 1
    partition = check_partition_mode(
        getattr(cfg.mesh, "partition", "replicated"))
    if partition == "zero1" and per_replica_bn:
        raise ValueError(
            "mesh.partition=zero1 on a multi-chip data axis requires "
            "model.sync_bn=true: per-replica BN runs the step inside "
            "shard_map, where the zero1 sharding annotations "
            "(with_sharding_constraint over the mesh) cannot be applied "
            "— the auto-sharded jit path is the supported dispatch for "
            "cross-replica optimizer sharding (docs/PARALLELISM.md)")
    if cfg.model.fused_blocks and data_axis > 1 and not per_replica_bn:
        raise ValueError(
            "model.fused_blocks on a multi-chip data axis requires "
            "model.sync_bn=false (per-replica BN via shard_map — the "
            "reference's BN semantics); global-batch sync-BN is not "
            "implemented for the fused kernels")
    if (getattr(cfg.model, "fused_epilogue", "off") != "off"
            and data_axis > 1 and not per_replica_bn):
        raise ValueError(
            "model.fused_epilogue on a multi-chip data axis requires "
            "model.sync_bn=false (per-replica BN via shard_map): the "
            "epilogue pallas_call cannot be auto-partitioned by the "
            "sharded jit — same dispatch rule as fused_blocks")


def make_train_step(model, optim_cfg, schedule, num_classes: int,
                    augment_fn: Optional[Callable] = None,
                    base_rng: Optional[jax.Array] = None,
                    mesh: Optional[Mesh] = None,
                    grad_axis: Optional[str] = None,
                    xent_probe_batch: int = 128,
                    partitioner=None):
    """Returns ``train_step(state, images, labels) -> (state, metrics)``.

    ``images`` may be raw uint8 (augment_fn applied on device) or
    pre-processed floats (augment_fn=None).

    ``grad_axis`` selects the per-replica-BN SPMD style: when set, the step
    is meant to run inside ``shard_map`` over that mesh axis — BN moments
    come from the *local* batch shard (the reference's per-worker BN
    update_ops, resnet_model.py:120-122), and gradients / metrics / stored
    BN stats are explicitly ``pmean``-ed across the axis. When None (the
    default), the step runs under auto-sharded ``jit`` and BN moments are
    global-batch (synced BN); XLA inserts the gradient all-reduces.

    ``partitioner`` (parallel.StatePartitioner) owns the weight-update
    sharding: zero1 pins the optimizer step to the slot shards
    (parallel/zero.py); None or replicated traces the identical plain
    optax chain this function always inlined.
    """
    from tpu_resnet.parallel import zero

    tx = build_optimizer(optim_cfg, schedule)
    apply_update = zero.make_update_fn(tx, partitioner)
    if base_rng is None:
        base_rng = jax.random.PRNGKey(0)

    # Fused Pallas xent dispatch (config.py use_pallas_xent, docs/PERF.md):
    # "auto" (default) runs the compile-time per-shape A/B once at
    # step-build time (host code, charged to the compile window) and
    # takes the measured winner — the BENCH_r04 0.901x regression class
    # auto-falls back to XLA; "on"/"off" force an arm. CPU and
    # label_smoothing always take the optax chain (program unchanged —
    # the config-matrix goldens are defined over that trace). Mesh
    # dispatch lives in ops.make_pallas_xent.
    from tpu_resnet.ops import (ensure_xent_probe, is_tpu_backend,
                                make_pallas_xent)
    mode = str(getattr(optim_cfg, "use_pallas_xent", "off")).lower()
    mode = {"true": "on", "1": "on", "yes": "on",
            "false": "off", "0": "off", "no": "off"}.get(mode, mode)
    if mode not in ("on", "off", "auto"):
        # Same fail-loud guard as model.fused_epilogue: a typo must not
        # silently mean "off" while the operator believes the A/B runs.
        raise ValueError(f"optim.use_pallas_xent must be auto|on|off, "
                         f"got {optim_cfg.use_pallas_xent!r}")
    use_pallas = (mode in ("on", "auto")
                  and optim_cfg.label_smoothing == 0.0
                  and is_tpu_backend())
    if use_pallas and mode == "auto":
        use_pallas = ensure_xent_probe(xent_probe_batch,
                                       num_classes).use_pallas
    if use_pallas:
        _pallas_xent = make_pallas_xent(mesh if grad_axis is None else None)

    def train_step(state: TrainState, images, labels):
        rng = jax.random.fold_in(base_rng, state.step)
        if grad_axis is not None:
            # Distinct augmentation stream per shard — without this every
            # replica would replay the same crops/flips on its slot-j
            # example.
            rng = jax.random.fold_in(rng, jax.lax.axis_index(grad_axis))
        if augment_fn is not None:
            images = augment_fn(rng, images)

        def loss_fn(params):
            logits, new_model_state = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                images, train=True, mutable=["batch_stats"])
            if use_pallas:
                xent = _pallas_xent(logits.astype(jnp.float32), labels)
            else:
                xent = softmax_xent(logits.astype(jnp.float32), labels,
                                    num_classes, optim_cfg.label_smoothing)
            penalty = optim_cfg.weight_decay * l2_weight_penalty(
                params, optim_cfg.weight_decay_on_bn)
            return xent + penalty, (logits, new_model_state)

        (loss, (logits, new_model_state)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        new_batch_stats = new_model_state["batch_stats"]
        precision = jnp.mean(
            (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
        if grad_axis is not None:
            # Explicit ICI all-reduces (the shard_map analog of what XLA
            # emits on the jit path): average grads; average the EMA stats
            # so the stored state is one consistent replicated tree.
            grads = jax.lax.pmean(grads, grad_axis)
            new_batch_stats = jax.lax.pmean(new_batch_stats, grad_axis)
            loss = jax.lax.pmean(loss, grad_axis)
            precision = jax.lax.pmean(precision, grad_axis)
        new_params, new_opt_state = apply_update(grads, state.opt_state,
                                                 state.params)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_batch_stats,
            opt_state=new_opt_state,
        )
        metrics = {
            "loss": loss,
            "precision": precision,
            "learning_rate": schedule(state.step),
            "grad_norm": optax.global_norm(grads),
        }
        return new_state, metrics

    return train_step


def make_eval_step(model, num_classes: int,
                   preprocess_fn: Optional[Callable] = None):
    """``eval_step(state, images, labels) -> (correct_count, loss_sum,
    valid_count)``; labels < 0 are padding (pipeline.eval_batches)."""

    def eval_step(state: TrainState, images, labels):
        if preprocess_fn is not None:
            images = preprocess_fn(images)
        logits = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            images, train=False)
        valid = labels >= 0
        safe_labels = jnp.maximum(labels, 0)
        onehot = jax.nn.one_hot(safe_labels, num_classes,
                                dtype=logits.dtype)
        per_ex = optax.softmax_cross_entropy(logits, onehot)
        correct = (jnp.argmax(logits, axis=-1) == safe_labels) & valid
        return (jnp.sum(correct.astype(jnp.int32)),
                jnp.sum(per_ex * valid.astype(per_ex.dtype)),
                jnp.sum(valid.astype(jnp.int32)))

    return eval_step


def per_replica_shard_map(fn, mesh: Mesh, in_specs):
    """Wrap a step/chunk built with ``grad_axis='data'`` in shard_map.
    Outputs (state, metrics) are replicated by construction — every shard
    applies the same pmean-ed grads/stats — hence ``out_specs=P()`` with
    VMA checking off (the explicit pmeans are the replication proof)."""
    from tpu_resnet.parallel import get_shard_map

    shard_map, kwargs = get_shard_map()
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=(P(), P()), **kwargs)


def shard_step(step_fn, mesh: Mesh, donate_state: bool = True,
               per_replica_bn: bool = False, state_sharding=None):
    """Compile a step for the mesh: batch split over 'data', state laid
    out per the partitioner. XLA emits the gradient/BN all-reduces over
    ICI — the entire replacement for ps push/pull + Horovod fusion
    threads.

    ``state_sharding`` is the TrainState-shaped sharding tree from
    ``StatePartitioner.state_shardings`` (None = fully replicated,
    today's default — every caller without an opinion keeps the exact
    historical program). zero1 callers pass their sharded tree so the
    optimizer-slot arguments compile to per-shard buffers.

    ``per_replica_bn=True`` compiles the ``shard_map`` variant: the step
    body (built with ``grad_axis='data'``) sees only its local batch shard,
    so BN moments are per-replica like the reference's, and the body's
    explicit ``pmean``s carry the cross-replica reductions."""
    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P("data"))
    if per_replica_bn:
        step_fn = per_replica_shard_map(
            step_fn, mesh, in_specs=(P(), P("data"), P("data")))
    return jax.jit(
        step_fn,
        in_shardings=(state_sharding if state_sharding is not None
                      else repl, data, data),
        donate_argnums=(0,) if donate_state else (),
    )
