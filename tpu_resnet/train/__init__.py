from tpu_resnet.train.checkpoint import CheckpointManager, latest_step_in
from tpu_resnet.train.loop import train
from tpu_resnet.train.metrics_io import MetricsWriter, ThroughputMeter
from tpu_resnet.train.schedule import build_schedule
from tpu_resnet.train.state import TrainState, init_state, param_count
from tpu_resnet.train.step import (
    make_eval_step,
    make_train_step,
    shard_step,
)

__all__ = [
    "CheckpointManager",
    "latest_step_in",
    "train",
    "MetricsWriter",
    "ThroughputMeter",
    "build_schedule",
    "TrainState",
    "init_state",
    "param_count",
    "make_eval_step",
    "make_train_step",
    "shard_step",
]
