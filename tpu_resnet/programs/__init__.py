"""Compiled-program registry (docs/PERF.md "Cold start", docs/CHECKS.md).

One owner for the canonical program-key spelling, program construction,
and the persistent cross-process AOT executable cache. Import stays
jax-free (jax only inside functions) so stdlib-only consumers — the
bench parent, perfwatch, doctor — can spell keys and inspect cache
directories without a backend.
"""

from tpu_resnet.programs.registry import (CACHE_DIR_ENV, CACHE_KILL_ENV,
                                          DonationContractError,
                                          ExecutableCache, ProgramRegistry,
                                          default_cache_dir,
                                          fingerprint_lowered, spell,
                                          spell_entry, spell_shape,
                                          staged_chunk_hook, state_avals,
                                          wrap_train_step)

__all__ = [
    "CACHE_DIR_ENV", "CACHE_KILL_ENV", "DonationContractError",
    "ExecutableCache", "ProgramRegistry", "default_cache_dir",
    "fingerprint_lowered", "spell", "spell_entry", "spell_shape",
    "staged_chunk_hook", "state_avals", "wrap_train_step",
]
