"""Unified compiled-program registry — one owner for program identity.

Before this module, the spelling that identifies a compiled program
(``train|cifar10_rn50_bf16|mesh8x1|b128``) was re-derived independently
by the FLOPs registry (obs/mfu.py), the memory ledger (obs/memory.py),
the golden-jaxpr/memory check engines (analysis/), and the autotune
decision table (ops/autotune.py) — and the five program-construction
paths (train loop, evaluator, serve bucket warmup, the check engines,
sweep_measure) each built their jitted programs through their own code.
Every one of those paths also re-paid XLA compilation on every process
start, which PR 10's capacity waves and PR 11's rolling upgrades turned
from an exceptional cost into a routine one: serving economics at fleet
scale are set by time-to-ready as much as steady-state throughput, and
pjit-era systems treat ahead-of-time compilation and executable reuse
as a first-class scaling tool (arXiv:2204.06514).

This module owns three things:

``spell`` / ``spell_entry`` / ``spell_shape``
    THE canonical key spelling. ``obs.mfu.train_program_key`` and
    ``ops.autotune.shape_key`` now delegate here, the config-matrix
    verifier asserts every traced entry resolves through it (one key =
    one program), and the cache below is keyed by it.

``ProgramRegistry``
    Per-run handle that routes program construction: when the cache is
    disabled it is an identity pass-through (the exact jit objects the
    constructors always built — golden jaxprs byte-unchanged); when
    enabled it goes ahead-of-time (``jitted.lower(avals).compile()``),
    asserts the donation contract on the lowered program, and
    round-trips the compiled executable through the persistent cache.

``ExecutableCache``
    The persistent cross-process AOT executable cache:
    ``jax.experimental.serialize_executable`` payloads on disk, one file
    per (program key × backend × device-kind × device-count), with the
    jax/jaxlib versions and a sharding/donation **fingerprint** of the
    lowered program recorded in the header. Stale (version or
    fingerprint mismatch), truncated, or corrupt entries are DELETED and
    recompiled — never trusted.

**The PR 1 hazard, engineered around, not ignored.** This jaxlib's CPU
executable deserialization was observed (tests/conftest.py) to (a)
SIGSEGV on the second in-process deserialization of the same entry and
(b) once serve a silently wrong executable. The cache is therefore:

- **cross-process only**: an entry this process just stored is never
  re-loaded by it (the in-memory compiled object is already in hand);
- **load-at-most-once per process**: a process-global ledger of
  deserialized entries; a second request for the same entry recompiles
  instead of deserializing again (``_loaded_once``);
- **fingerprint-verified before use**: every entry records the
  sharding/donation fingerprint of the lowered program it serialized
  (HLO text + donation vector + in/out shardings), plus a
  **precondition digest** over everything lowering is a deterministic
  function of (tpu_resnet source digest, the resolved model/data/optim/
  mesh config, the avals, library versions, XLA flags, the autotune
  decision table). A load first checks the precondition: a match proves
  re-lowering would reproduce the recorded fingerprint, so the entry is
  trusted without paying a fresh trace (the warm-restart fast path); on
  a mismatch the program is re-lowered and the full fingerprint is
  compared — match re-blesses the entry under the new precondition,
  mismatch DELETES it. ``TPU_RESNET_PROGRAM_CACHE_VERIFY=1`` forces the
  re-lowering path on every load (the paranoid switch). Either way a
  cache key collision or a drifted program can never hand back the
  wrong executable;
- **payload-hashed**: the serialized bytes carry their sha256; torn or
  bit-rotted files fail the hash and are deleted, never deserialized;
- **kill-switched**: ``TPU_RESNET_PROGRAM_CACHE=0`` disables every load
  AND store, whatever the config says.

Module import stays jax-free (jax only inside functions) so stdlib-only
consumers (bench parent, perfwatch, doctor) can use the spelling and
inspect cache dirs without a backend.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import struct
import threading
import time
from typing import Dict, Optional, Tuple

log = logging.getLogger("tpu_resnet")

CACHE_DIR_ENV = "TPU_RESNET_PROGRAM_CACHE_DIR"
CACHE_KILL_ENV = "TPU_RESNET_PROGRAM_CACHE"
CACHE_VERIFY_ENV = "TPU_RESNET_PROGRAM_CACHE_VERIFY"
CACHE_SUBDIR = "progcache"

_MAGIC = b"TPRC1\n"
_FORMAT = 1

class DonationContractError(ValueError):
    """A program the registry was about to cache violates its declared
    donation contract — a real program bug that must surface loudly
    (cached with the wrong donation it would silently double parameter
    HBM for every consumer), unlike the registry's other failure modes,
    which all degrade to plain jit dispatch."""


# Process-global ledger of cache entries already deserialized once:
# this jaxlib segfaults on the SECOND in-process deserialization of an
# entry (PR 1, tests/conftest.py) — a repeat request recompiles instead.
_loaded_once: set = set()
_loaded_lock = threading.Lock()


# ================================================================ spelling
def spell(cfg, mesh_shape: Dict[str, int], kind: str = "train",
          batch: Optional[int] = None) -> str:
    """THE canonical program-key spelling:

        train|cifar10_rn50_bf16|mesh8x1|b128
        train|cifar10_rn8_f32_zero1|mesh8x1|b16
        serve|cifar10_rn50_bf16|mesh1x1|b4

    One key names exactly one compiled program (the config-matrix
    coverage check enforces it), so the family variant carries every
    config dimension that changes the traced program: ``_fused`` /
    ``_remat`` (block implementation), ``_ep`` (fused_epilogue forced
    on), ``_nos2d`` (ImageNet stem without space-to-depth), ``_pr``
    (per-replica BN — the shard_map dispatch is a different program
    from the auto-sharded sync-BN jit), and the partition mode when not
    replicated. ``data.engine`` is deliberately NOT part of the key:
    thread and process engines feed byte-identical programs (the
    engine-invariance twins the verifier pins). ``fused_epilogue=auto``
    spells like ``off`` — its dispatch is probe-dependent by design, and
    the executable cache's lowered-program fingerprint (not the key) is
    what guards an auto run against a mismatched cached program.

    ``batch`` overrides ``cfg.train.global_batch_size`` — the serve
    path spells one key per bucket shape.
    """
    m = cfg.model
    name = m.name if m.name != "resnet" else f"rn{m.resnet_size}"
    if m.name == "resnet" and m.width_multiplier != 1:
        name = f"wrn{m.resnet_size}_{m.width_multiplier}"
    dataset = cfg.data.dataset
    if dataset == "synthetic" and getattr(cfg.data, "synthetic_classes",
                                          10) != 10:
        dataset = f"synthetic{cfg.data.synthetic_classes}"
    dtype = {"bfloat16": "bf16", "float32": "f32"}.get(
        m.compute_dtype, m.compute_dtype)
    data_axis = mesh_shape.get("data", 1)
    partition = getattr(getattr(cfg, "mesh", None), "partition",
                        "replicated")
    per_replica = (not m.sync_bn) and data_axis > 1
    quantized = (kind == "serve" and getattr(
        getattr(cfg, "serve", None), "quantize", "off") == "int8")
    variant = (("_fused" if m.fused_blocks else "")
               + ("_remat" if m.remat else "")
               + ("_ep" if getattr(m, "fused_epilogue", "off") == "on"
                  else "")
               + ("_nos2d" if dataset.startswith("imagenet")
                  and not getattr(m, "stem_space_to_depth", True) else "")
               + ("_pr" if per_replica else "")
               + (f"_{partition}" if partition != "replicated" else "")
               # Quantized serve programs (serve.quantize=int8) take the
               # int8 argument tree of ops/quant.py — a different
               # signature AND different math, so a different key family
               # (the _ep/_zero1 pattern). Serve-only: training is never
               # quantized here.
               + ("_q8" if quantized else ""))
    b = batch if batch is not None else cfg.train.global_batch_size
    return (f"{kind}|{dataset}_{name}_{dtype}{variant}"
            f"|mesh{data_axis}x{mesh_shape.get('model', 1)}|b{b}")


def spell_entry(entry) -> str:
    """Key for one config-matrix row (analysis/configmatrix.MatrixEntry)
    — the registry-coverage bridge between the check engines and the
    runtime: the verifier asserts every traced entry resolves through
    this, and that no two entries with different programs share a key.
    Staged-chunk rows spell under kind ``chunk`` with their stage/step
    shape appended (``|s8c4``) — matching the sub-keys the train loop's
    registry uses for its per-chunk programs, because the fused
    multi-step dispatch is a different program per (stage, c). The
    FLOPs/memory entries of a RUN keep kind ``train`` — one run entry
    covers all its dispatch shapes, as documented there."""
    if getattr(entry, "builder", "config") == "staged-chunk":
        base = spell(entry.to_config(),
                     {"data": entry.data_axis, "model": entry.model_axis},
                     kind="chunk", batch=entry.batch)
        return f"{base}|s{entry.stage_rows}c{entry.chunk_steps}"
    if getattr(entry, "builder", "config") == "serve":
        # Serve rows spell under kind "serve" — the exact bucket keys the
        # CheckpointBackend's registry uses (quantized rows pick up the
        # _q8 suffix from serve.quantize in to_config()).
        return spell(entry.to_config(),
                     {"data": entry.data_axis, "model": entry.model_axis},
                     kind="serve", batch=entry.batch)
    return spell(entry.to_config(),
                 {"data": entry.data_axis, "model": entry.model_axis},
                 kind="train", batch=entry.batch)


def spell_shape(*dims) -> str:
    """Canonical shape-key spelling, e.g. ``b128x1000`` — the autotune
    decision table's key (ops/autotune.py delegates here)."""
    return "x".join(str(int(d)) for d in dims)


# ============================================================= fingerprint
def fingerprint_lowered(lowered) -> str:
    """Sharding/donation fingerprint of a lowered program: sha256 over
    the canonicalized module text, the per-leaf donation vector, and the
    input/output sharding reprs. Two programs with the same key but
    different math, donation, or layout can never exchange executables —
    the "silently wrong executable" incident class (PR 1) is excluded
    by construction, not by hope."""
    import jax

    from tpu_resnet.analysis.configmatrix import canonicalize

    parts = [canonicalize(lowered.as_text())]
    try:
        info = lowered.args_info
        parts.append(repr([bool(i.donated)
                           for i in jax.tree_util.tree_leaves(info)]))
    except Exception:  # noqa: BLE001 - older jax without args_info
        parts.append("no-args-info")
    for attr in ("in_avals", "out_info"):
        try:
            tree = getattr(lowered, attr)
            parts.append(repr([(tuple(x.shape), str(x.dtype),
                                str(getattr(x, "sharding", None)))
                               for x in jax.tree_util.tree_leaves(tree)]))
        except Exception:  # noqa: BLE001 - attr varies across jax APIs
            parts.append(f"no-{attr}")
    return hashlib.sha256("\n".join(parts).encode()).hexdigest()


_source_digest_cache: Dict[str, str] = {}


def source_digest() -> str:
    """sha256 over every ``.py`` file of the installed tpu_resnet
    package (path + content), computed once per process (~15 ms). The
    coarse half of the cache precondition: ANY source edit — model
    code, step construction, a helper three imports away — invalidates
    every fast-path load, because lowering is a function of the whole
    package and a precondition must never be cleverer than that."""
    if "v" in _source_digest_cache:
        return _source_digest_cache["v"]
    import tpu_resnet

    root = os.path.dirname(os.path.abspath(tpu_resnet.__file__))
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__" and not d.startswith("."))
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            h.update(os.path.relpath(path, root).encode())
            try:
                with open(path, "rb") as f:
                    h.update(f.read())
            except OSError:
                h.update(b"<unreadable>")
    _source_digest_cache["v"] = h.hexdigest()
    return _source_digest_cache["v"]


def default_cache_dir(cfg) -> str:
    """<train_dir>/progcache — the per-run default when the cache is on
    but no explicit directory was configured. Serve replicas restarting
    against one train_dir (the PR 11 rolling-upgrade window) land on the
    same directory and hit each other's entries."""
    return os.path.join(cfg.train.train_dir, CACHE_SUBDIR)


# ============================================================ on-disk cache
class ExecutableCache:
    """Persistent cross-process AOT executable cache.

    One file per (program key × backend × device-kind × device-count):
    ``<sha16>.aotx`` = magic + header-JSON + pickled
    ``serialize_executable.serialize`` payload. The header records the
    producing jax/jaxlib versions, the program fingerprint, and the
    payload sha256; any mismatch on load DELETES the entry and reports a
    miss (the caller recompiles and overwrites). Writes are atomic
    (tmp + rename) so concurrent replicas never read a torn entry."""

    def __init__(self, cache_dir: str):
        self.dir = cache_dir
        self.evictions = 0

    # -------------------------------------------------------------- naming
    @staticmethod
    def _env() -> dict:
        import jax
        import jaxlib

        dev = jax.devices()[0]
        return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
                "backend": jax.default_backend(),
                "device_kind": str(getattr(dev, "device_kind", "?")),
                "n_devices": int(jax.device_count())}

    def path_for(self, key: str, env: dict) -> str:
        material = "|".join((key, env["backend"], env["device_kind"],
                             str(env["n_devices"])))
        digest = hashlib.sha256(material.encode()).hexdigest()[:24]
        return os.path.join(self.dir, f"{digest}.aotx")

    # --------------------------------------------------------------- store
    def _write(self, path: str, header: dict, payload: bytes
               ) -> Optional[str]:
        hdr = json.dumps(header, sort_keys=True).encode()
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(_MAGIC)
                f.write(struct.pack(">I", len(hdr)))
                f.write(hdr)
                f.write(payload)
            os.replace(tmp, path)
            return path
        except OSError as e:
            log.warning("program cache: cannot write %s: %s", path, e)
            return None

    def store(self, key: str, fingerprint: str, precondition: str,
              compiled) -> Optional[str]:
        """Serialize ``compiled`` under ``key``; best-effort (a cache
        that cannot write must never fail the run). Returns the path or
        None."""
        from jax.experimental import serialize_executable

        try:
            payload = pickle.dumps(serialize_executable.serialize(compiled))
        except Exception as e:  # noqa: BLE001 - backend-specific
            log.warning("program cache: cannot serialize %s (%s: %s)",
                        key, type(e).__name__, e)
            return None
        env = self._env()
        header = dict(env, format=_FORMAT, key=key,
                      fingerprint=fingerprint,
                      precondition=precondition,
                      payload_sha256=hashlib.sha256(payload).hexdigest(),
                      payload_bytes=len(payload),
                      created_unix=round(time.time(), 3))
        return self._write(self.path_for(key, env), header, payload)

    # ---------------------------------------------------------------- load
    def read_header(self, path: str) -> Optional[dict]:
        """Header of one entry file (None when unreadable/corrupt)."""
        try:
            with open(path, "rb") as f:
                if f.read(len(_MAGIC)) != _MAGIC:
                    return None
                (n,) = struct.unpack(">I", f.read(4))
                return json.loads(f.read(n))
        except (OSError, ValueError, struct.error):
            return None

    def _evict(self, path: str, why: str) -> None:
        self.evictions += 1
        log.warning("program cache: evicting %s (%s) — will recompile",
                    os.path.basename(path), why)
        try:
            os.remove(path)
        except OSError:
            pass

    def _read_checked(self, key: str):
        """(path, header, payload) for ``key`` after the structural and
        environment checks shared by both load paths: magic, header
        parse, jax/jaxlib/backend/device-kind/count match, format/key
        match, payload sha256. Every failure evicts and returns None —
        a torn or stale entry is never deserialized."""
        env = self._env()
        path = self.path_for(key, env)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        if not blob.startswith(_MAGIC) or len(blob) < len(_MAGIC) + 4:
            self._evict(path, "bad magic/truncated")
            return None
        try:
            (n,) = struct.unpack(
                ">I", blob[len(_MAGIC):len(_MAGIC) + 4])
            header = json.loads(blob[len(_MAGIC) + 4:len(_MAGIC) + 4 + n])
            payload = blob[len(_MAGIC) + 4 + n:]
        except (ValueError, struct.error):
            self._evict(path, "corrupt header")
            return None
        for field in ("jax", "jaxlib", "backend", "device_kind",
                      "n_devices"):
            if header.get(field) != env[field]:
                self._evict(path, f"{field} mismatch "
                                  f"({header.get(field)!r} != "
                                  f"{env[field]!r})")
                return None
        if header.get("format") != _FORMAT or header.get("key") != key:
            self._evict(path, "format/key mismatch")
            return None
        if hashlib.sha256(payload).hexdigest() != \
                header.get("payload_sha256"):
            self._evict(path, "payload hash mismatch (torn/bit-rot)")
            return None
        return path, header, payload

    def _deserialize(self, key: str, path: str, payload: bytes):
        with _loaded_lock:
            if path in _loaded_once:
                # PR 1 hazard: this jaxlib segfaults on the SECOND
                # in-process deserialization of an entry. Recompile.
                log.info("program cache: %s already deserialized once in "
                         "this process — recompiling instead of a second "
                         "deserialization (PR 1 hazard)", key)
                return None
            _loaded_once.add(path)
        from jax.experimental import serialize_executable

        try:
            ser, in_tree, out_tree = pickle.loads(payload)
            return serialize_executable.deserialize_and_load(
                ser, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 - never crash on a cache
            self._evict(path, f"deserialization failed "
                              f"({type(e).__name__}: {e})")
            return None

    def load_fast(self, key: str, precondition: str):
        """The warm-restart fast path: trust the entry WITHOUT
        re-lowering when its recorded precondition digest matches —
        lowering is a deterministic function of everything the digest
        covers, so a match proves a fresh trace would reproduce the
        recorded fingerprint. None on any mismatch (the caller then
        takes :meth:`load_verified`, which re-lowers)."""
        found = self._read_checked(key)
        if found is None:
            return None
        path, header, payload = found
        if not precondition or header.get("precondition") != precondition:
            return None  # not evicted: load_verified decides its fate
        return self._deserialize(key, path, payload)

    def load_verified(self, key: str, fingerprint: str,
                      precondition: str = ""):
        """The full check: the entry's recorded lowered-program
        fingerprint must equal ``fingerprint`` (computed by the caller
        from a FRESH lowering). A match under a new ``precondition``
        re-blesses the entry (header rewritten) so the next restart
        takes the fast path again; a mismatch means the program for
        this key CHANGED — serving the entry anyway is the PR 1
        incident, so it is deleted instead."""
        found = self._read_checked(key)
        if found is None:
            return None
        path, header, payload = found
        if header.get("fingerprint") != fingerprint:
            self._evict(path, "program fingerprint drifted")
            return None
        if precondition and header.get("precondition") != precondition:
            header["precondition"] = precondition
            self._write(path, header, payload)
        return self._deserialize(key, path, payload)


# =============================================================== programs
class _Program:
    """A registry-built program: the AOT executable (cached or freshly
    compiled) with the plain jitted function as a lazy fallback — a call
    whose concrete arguments don't match the compiled signature (an
    unexpected batch shape, a layout surprise) pays one normal jit
    compile instead of crashing, and can never produce a wrong result."""

    def __init__(self, compiled, jitted, key: str):
        self._compiled = compiled
        self._jitted = jitted
        self.key = key
        self._fell_back = False

    def __call__(self, *args):
        if self._compiled is not None:
            try:
                return self._compiled(*args)
            except (TypeError, ValueError) as e:
                if not self._fell_back:
                    self._fell_back = True
                    log.warning(
                        "program %s: AOT executable rejected the call "
                        "(%s: %s) — falling back to jit dispatch",
                        self.key, type(e).__name__, e)
                self._compiled = None
        return self._jitted(*args)


class ProgramRegistry:
    """Per-run program-construction front door.

    ``context`` selects the cache default under ``programs.cache=auto``:
    serve replicas cache by default (cold start IS their cost model —
    the PR 11 rolling-upgrade window); train/eval/sweep cache only when
    a directory is configured (``programs.cache_dir`` or the
    ``TPU_RESNET_PROGRAM_CACHE_DIR`` env — the elastic-resume and sweep
    levers). ``TPU_RESNET_PROGRAM_CACHE=0`` kills the cache everywhere.

    With the cache disabled every ``wrap``/builder call returns its
    input jit object untouched: the registry is an identity transform
    on compiled programs (the golden-jaxpr acceptance contract)."""

    def __init__(self, cfg, mesh=None, telemetry=None, spans=None,
                 cache_dir: Optional[str] = None, context: str = "train"):
        self.cfg = cfg
        self.mesh = mesh
        self.telemetry = telemetry
        self.spans = spans
        self.context = context
        self.hits = 0
        self.misses = 0
        mode = str(getattr(getattr(cfg, "programs", None), "cache",
                           "auto")).lower()
        if mode not in ("auto", "on", "off"):
            raise ValueError(f"programs.cache must be auto|on|off, "
                             f"got {mode!r}")
        configured = (cache_dir
                      or getattr(getattr(cfg, "programs", None),
                                 "cache_dir", "")
                      or os.environ.get(CACHE_DIR_ENV, ""))
        if os.environ.get(CACHE_KILL_ENV, "1") == "0":
            enabled = False  # the operator's hard off-switch
        elif mode == "off":
            enabled = False
        elif mode == "on":
            enabled = True
        else:  # auto
            enabled = bool(configured) or context == "serve"
        self.cache: Optional[ExecutableCache] = None
        if enabled:
            self.cache = ExecutableCache(
                configured or default_cache_dir(cfg))

    # ------------------------------------------------------------- spelling
    @property
    def cache_enabled(self) -> bool:
        return self.cache is not None

    def key(self, kind: str = "train", batch: Optional[int] = None) -> str:
        mesh_shape = dict(self.mesh.shape) if self.mesh is not None else {}
        return spell(self.cfg, mesh_shape, kind=kind, batch=batch)

    # ------------------------------------------------------------ telemetry
    def _count(self, hit: bool) -> None:
        if hit:
            self.hits += 1
        else:
            self.misses += 1
        if self.telemetry is not None:
            try:
                self.telemetry.set("compile_cache_hits", float(self.hits))
                self.telemetry.set("compile_cache_misses",
                                   float(self.misses))
            except Exception:  # noqa: BLE001 - accounting must not kill
                pass

    def stats(self) -> dict:
        return {"compile_cache_hits": self.hits,
                "compile_cache_misses": self.misses,
                "cache_dir": self.cache.dir if self.cache else None,
                "evictions": self.cache.evictions if self.cache else 0}

    # ----------------------------------------------------------- assertions
    @staticmethod
    def assert_donation(lowered, key: str, donated_args=()) -> None:
        """The registry's donation contract on a program it is about to
        cache: every leaf of each argument index in ``donated_args``
        must be donated in the lowered program, and no other argument
        may be. An executable cached with the wrong donation would
        silently double parameter HBM on every consumer — fail loudly
        at build time instead."""
        import jax

        try:
            info = lowered.args_info
        except Exception:  # noqa: BLE001 - older jax without args_info
            return
        args = info[0] if isinstance(info, tuple) and len(info) == 2 \
            and isinstance(info[1], dict) else info
        for i, arg in enumerate(args):
            leaves = jax.tree_util.tree_leaves(arg)
            donated = [bool(leaf.donated) for leaf in leaves]
            if i in donated_args and not all(donated):
                raise DonationContractError(
                    f"program {key}: argument {i} must be fully donated "
                    f"but {donated.count(False)}/{len(donated)} leaves "
                    f"are not — the donation contract the registry "
                    f"certifies (docs/CHECKS.md) is broken")
            if i not in donated_args and any(donated):
                raise DonationContractError(
                    f"program {key}: argument {i} is donated but only "
                    f"{tuple(donated_args)} may be — an input buffer "
                    f"a consumer still owns would be invalidated")

    # --------------------------------------------------------- precondition
    def _precondition(self, avals: Tuple) -> str:
        """Digest over everything lowering is a deterministic function
        of, short of the trace itself: the package source digest, the
        resolved model/data/optim/mesh config sections, the argument
        avals (shape/dtype/sharding), library versions, XLA/x64 flags,
        and the autotune decision table (probe-dependent dispatch —
        ops/autotune.py — is trace-time input too). A matching digest
        lets a load trust the recorded lowered-program fingerprint
        without re-paying the trace; anything uncovered lands in the
        slow path, never in a wrong executable."""
        import jax

        from tpu_resnet.ops import autotune

        cfg_dict = self.cfg.to_dict()
        sections = {k: cfg_dict.get(k)
                    for k in ("model", "data", "optim", "mesh")}
        leaves = [(tuple(x.shape), str(x.dtype),
                   str(getattr(x, "sharding", None)))
                  for x in jax.tree_util.tree_leaves(avals)]
        versions = {}
        for mod in ("flax", "optax", "numpy"):
            try:
                versions[mod] = __import__(mod).__version__
            except Exception:  # noqa: BLE001
                versions[mod] = "?"
        # Only the DISPATCH-relevant slice of the autotune table: the
        # trace reads use_pallas() per (op, shape), never the measured
        # microsecond timings — digesting those would change the digest
        # every process and permanently defeat the fast path for
        # exactly the auto-dispatch configs it targets.
        dispatch = {k: bool(v.get("use_pallas"))
                    for k, v in autotune.decisions().items()}
        material = json.dumps(
            {"source": source_digest(), "config": sections,
             "avals": leaves, "versions": versions,
             "xla_flags": os.environ.get("XLA_FLAGS", ""),
             "x64": os.environ.get("JAX_ENABLE_X64", ""),
             "autotune": dispatch},
            sort_keys=True, default=str)
        return hashlib.sha256(material.encode()).hexdigest()

    # ------------------------------------------------------------- the core
    def wrap(self, key: str, jitted, avals: Tuple,
             donated_args: Tuple[int, ...] = ()):
        """Route one program through the registry: identity when the
        cache is off; else AOT-compile (or cache-load) over ``avals``
        and return a :class:`_Program`. Returns ``(program,
        cache_hit)``. Any failure in the AOT/cache path degrades to the
        plain jit object — the registry must never be the reason a run
        dies.

        Load order: precondition fast path (no re-trace) →
        fingerprint-verified path (fresh lowering; re-blesses or evicts
        the entry) → AOT compile + store. ``TPU_RESNET_PROGRAM_CACHE_VERIFY=1``
        skips the fast path so every load re-verifies the full
        fingerprint."""
        if self.cache is None:
            return jitted, False
        t0 = time.time()
        try:
            pre = self._precondition(avals)
            if os.environ.get(CACHE_VERIFY_ENV, "0") != "1":
                loaded = self.cache.load_fast(key, pre)
                if loaded is not None:
                    self._count(True)
                    self._span(key, t0, hit=True, verified="precondition")
                    return _Program(loaded, jitted, key), True
            lowered = jitted.lower(*avals)
            fp = fingerprint_lowered(lowered)
            loaded = self.cache.load_verified(key, fp, precondition=pre)
            if loaded is not None:
                self._count(True)
                self._span(key, t0, hit=True, verified="fingerprint")
                return _Program(loaded, jitted, key), True
            compiled = lowered.compile()
            self.assert_donation(lowered, key, donated_args)
            self.cache.store(key, fp, pre, compiled)
            self._count(False)
            self._span(key, t0, hit=False)
            return _Program(compiled, jitted, key), False
        except DonationContractError:
            raise  # a real program bug, never a cache degrade
        except Exception as e:  # noqa: BLE001 - cache must degrade: a
            # registry-side aval/sharding mistake (lower/compile raising
            # ValueError included) must not kill a run that works with
            # the cache off
            log.warning("program registry: AOT/cache path failed for %s "
                        "(%s: %s) — using plain jit dispatch",
                        key, type(e).__name__, e)
            self._count(False)
            return jitted, False

    def _span(self, key: str, t0: float, hit: bool,
              verified: str = "") -> None:
        if self.spans is None:
            return
        try:
            attrs = {"program_key": key, "cache_hit": hit}
            if verified:
                attrs["verified_by"] = verified
            self.spans.record("cache_load", t0, time.time(), **attrs)
        except Exception:  # noqa: BLE001
            pass


def state_avals(state):
    """ShapeDtypeStruct avals (shardings included) of a concrete state
    tree — what the registry lowers train programs over. One helper so
    every caller spells avals identically."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=x.sharding), state)


def _batch_dtype(cfg) -> str:
    # ImageNet streams pre-processed floats; every other dataset feeds
    # raw uint8 and augments on device — the account_train_step rule.
    return "float32" if cfg.data.dataset == "imagenet" else "uint8"


def wrap_train_step(registry: ProgramRegistry, step_fn, avals,
                    donate_state: bool = True):
    """Route the single-step train program through the registry over
    the canonical batch avals. The one spelling of the single-step key
    (+``|nodon`` for the sweep's donation knob), shared by the train
    loop and sweep_measure so their cache entries can never drift."""
    import jax

    from tpu_resnet import parallel

    cfg = registry.cfg
    gb = cfg.train.global_batch_size
    size = cfg.data.resolved_image_size
    bsh = parallel.batch_sharding(registry.mesh)
    program, _ = registry.wrap(
        registry.key("train") + ("" if donate_state else "|nodon"),
        step_fn,
        (avals,
         jax.ShapeDtypeStruct((gb, size, size, 3), _batch_dtype(cfg),
                              sharding=bsh),
         jax.ShapeDtypeStruct((gb,), "int32", sharding=bsh)),
        donated_args=(0,) if donate_state else ())
    return program


def staged_chunk_hook(registry: ProgramRegistry, avals, rows: int,
                      donate_state: bool = True):
    """``program_hook`` for ``device_data.compile_staged_stream_steps``
    / ``compile_resident_steps``: routes each per-``c`` chunk jit
    through the registry under the canonical
    ``chunk|…[|nodon]|s{rows}c{c}`` key over the canonical staged
    avals. One constructor (train loop AND sweep_measure) so the
    one-key-one-program invariant can't be broken by two drifting
    copies."""
    import jax

    from tpu_resnet import parallel

    cfg = registry.cfg
    gb = cfg.train.global_batch_size
    size = cfg.data.resolved_image_size
    ssh = parallel.staged_batch_sharding(registry.mesh)
    gi = jax.ShapeDtypeStruct((rows, gb, size, size, 3),
                              _batch_dtype(cfg), sharding=ssh)
    gl = jax.ShapeDtypeStruct((rows, gb), "int32", sharding=ssh)
    off = jax.ShapeDtypeStruct((), "int32")
    base_key = registry.key("chunk") + ("" if donate_state else "|nodon")
    donated = (0,) if donate_state else ()

    def hook(c, jitted):
        program, _ = registry.wrap(f"{base_key}|s{rows}c{c}", jitted,
                                   (avals, gi, gl, off),
                                   donated_args=donated)
        return program

    return hook
